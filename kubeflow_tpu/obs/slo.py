# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Declarative SLOs + Google-SRE multi-window burn-rate alerting.

The alerting half of the fleet telemetry pipeline (obs/collector.py
holds the store this evaluates against). Two SLO shapes cover the
tree's service promises:

- **ratio** — "99% of requests meet their deadline": ``bad_metrics``
  (shed + expired counters) over ``total_metrics``, both as
  cross-replica summed rates.
- **latency** — "TTFT p95 < X ms" / "reconcile p99 < Y ms": the
  fraction of histogram observations ABOVE the threshold bucket is
  the error ratio (p95 < X ⟺ ≤5% of observations exceed X), so one
  burn-rate pipeline serves both shapes.

Burn rate = error ratio ÷ error budget (1 − objective): burn 1 spends
exactly the budget over the SLO period; burn 14.4 exhausts a 30-day
budget in 2 days. The SRE-workbook rule needs BOTH a long and a short
window above the factor — the long window proves significance, the
short window proves the problem is STILL happening (so a resolved
incident stops paging while the long window is still digesting it):

- fast page: 5 m AND 1 h over 14.4× — budget-threatening, page now.
- slow ticket: 6 h AND 3 d over 1× — steady leak, file a ticket.

:class:`AlertManager` runs the state machine per (SLO, window):
``inactive → pending → firing → resolved``, with a ``for`` duration
before firing and a clear-hold before resolving (flap damping — a
burn rate oscillating around the threshold neither fires per blip nor
resolves per dip). Firing/resolved transitions publish a Kubernetes
Event + the ``kft-alerts`` ConfigMap (the operator-metrics pattern:
the dashboard reads the same object the alerter wrote) and every
state is exported as the ``kft_alert_state`` gauge.
"""

from __future__ import annotations

import datetime
import json
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from kubeflow_tpu.obs import metrics as obs_metrics
from kubeflow_tpu.obs.collector import TimeSeriesStore

logger = logging.getLogger(__name__)

__all__ = [
    "ALERTS_CONFIGMAP",
    "ALERTS_KEY",
    "AlertManager",
    "BurnWindow",
    "FAST_PAGE",
    "SLO",
    "SLOW_TICKET",
    "default_slos",
]

#: ConfigMap firing alerts are published to (dashboard + kubectl read
#: the same object; also the Events' involvedObject).
ALERTS_CONFIGMAP = "kft-alerts"
ALERTS_KEY = "alerts.json"

#: Alert states as the ``kft_alert_state`` gauge encodes them.
STATE_VALUES = {"inactive": 0.0, "pending": 1.0, "firing": 2.0,
                "resolved": 0.0}

_G_ALERT_STATE = obs_metrics.Gauge(
    "kft_alert_state",
    "SLO alert state (0=inactive/resolved, 1=pending, 2=firing)",
    ("slo", "severity"))
_C_TRANSITIONS = obs_metrics.Counter(
    "kft_alert_transitions_total",
    "Alert state-machine transitions", ("slo", "to"))


@dataclass(frozen=True)
class BurnWindow:
    """One multi-window burn-rate rule: alert when the error budget
    burns faster than ``factor``× over BOTH windows."""

    name: str
    long_s: float
    short_s: float
    factor: float
    severity: str  # "page" | "ticket"


#: The Google SRE workbook pair (§ alerting on SLOs): page on a fast
#: burn, ticket on a slow leak.
FAST_PAGE = BurnWindow("fast", long_s=3600.0, short_s=300.0,
                       factor=14.4, severity="page")
SLOW_TICKET = BurnWindow("slow", long_s=3 * 86400.0, short_s=6 * 3600.0,
                         factor=1.0, severity="ticket")


@dataclass
class SLO:
    """One service-level objective over the collector's store.

    Ratio form: ``bad_metrics`` / ``total_metrics`` (counter names,
    rates summed across every matching series). Latency form:
    ``histogram`` + ``threshold_s`` — the error ratio is the fraction
    of observations above the threshold's bucket.
    """

    name: str
    objective: float
    description: str = ""
    bad_metrics: Tuple[str, ...] = ()
    total_metrics: Tuple[str, ...] = ()
    histogram: Optional[str] = None
    threshold_s: Optional[float] = None
    label_filter: Optional[Dict[str, str]] = None
    windows: Tuple[BurnWindow, ...] = (FAST_PAGE, SLOW_TICKET)

    def __post_init__(self):
        if not (0.0 < self.objective < 1.0):
            raise ValueError(f"objective must be in (0, 1), got "
                             f"{self.objective}")
        ratio = bool(self.bad_metrics or self.total_metrics)
        latency = self.histogram is not None
        if ratio == latency:
            raise ValueError(
                f"SLO {self.name!r}: define exactly one of "
                f"bad/total_metrics (ratio) or histogram+threshold_s "
                f"(latency)")
        if latency and self.threshold_s is None:
            raise ValueError(f"SLO {self.name!r}: latency form needs "
                             f"threshold_s")

    @property
    def error_budget(self) -> float:
        return 1.0 - self.objective

    def _sum_rates(self, store: TimeSeriesStore, names: Sequence[str],
                   window_s: float, now: float) -> Optional[float]:
        total = None
        for name in names:
            rate = store.sum_rate(name, window_s, now,
                                  self.label_filter)
            if rate is not None:
                total = (total or 0.0) + rate
        return total

    def error_ratio(self, store: TimeSeriesStore, window_s: float,
                    now: float) -> Optional[float]:
        """Fraction of events violating the objective over the
        window; None when the store has no data (no data is NOT a
        zero error rate — alerting on blindness both ways is wrong,
        so the state machine simply holds)."""
        if self.histogram is not None:
            buckets = store.bucket_rates(self.histogram, window_s, now,
                                         self.label_filter)
            if not buckets:
                return None
            total = buckets.get(float("inf"),
                                max(buckets.values(), default=0.0))
            if total <= 0.0:
                return 0.0
            # Cumulative rate at the threshold's bucket = the GOOD
            # fraction. A threshold between bounds uses the LARGEST
            # bound ≤ threshold — genuinely conservative at the
            # bucket grid's resolution: observations between that
            # bound and the threshold count as violations (slight
            # over-alerting), never the reverse (a mid-bucket
            # threshold that can silently never fire).
            finite = sorted(b for b in buckets if b != float("inf"))
            good = 0.0
            for bound in finite:
                if bound <= self.threshold_s:
                    good = buckets[bound]
                else:
                    break
            return max(0.0, min(1.0, (total - good) / total))
        bad = self._sum_rates(store, self.bad_metrics, window_s, now)
        total = self._sum_rates(store, self.total_metrics, window_s,
                                now)
        if total is None:
            return None
        if total <= 0.0:
            return 0.0
        return max(0.0, min(1.0, (bad or 0.0) / total))

    def burn_rate(self, store: TimeSeriesStore, window_s: float,
                  now: float) -> Optional[float]:
        ratio = self.error_ratio(store, window_s, now)
        if ratio is None:
            return None
        return ratio / self.error_budget


def default_slos(*, deadline_objective: float = 0.99,
                 ttft_p95_s: Optional[float] = None,
                 reconcile_p99_s: Optional[float] = None,
                 tenants: Optional[Sequence[str]] = None,
                 tenant_objective: float = 0.99,
                 windows: Optional[Tuple[BurnWindow, ...]] = None
                 ) -> List[SLO]:
    """The stock fleet SLO set: requests-meet-deadline (always), TTFT
    p95 and operator reconcile p99 (when given thresholds), plus —
    when ``tenants`` names them — a per-tenant deadline SLO over the
    tenant-labeled families (ISSUE 14: one noisy neighbor burning the
    FLEET SLO is exactly the blur tenancy exists to remove; the
    per-tenant burn shows whose budget is actually on fire). The
    deadline SLOs count shed AND expired as violations — a request
    turned away at admission missed its deadline as surely as one
    that lapsed in queue. Per-tenant series are cardinality-capped at
    the source (serving/tenancy.py): name only tenants inside the
    top-K cap, or their series read as ``other``'s."""
    kw: Dict[str, Any] = {}
    if windows is not None:
        kw["windows"] = windows
    slos = [SLO(
        name="serving-deadline",
        objective=deadline_objective,
        description=f"{deadline_objective:.0%} of requests dispatch "
                    f"within their deadline (not shed, not expired)",
        bad_metrics=("kft_serving_shed_total",
                     "kft_serving_expired_total"),
        total_metrics=("kft_serving_batch_rows_total",
                       "kft_serving_shed_total",
                       "kft_serving_expired_total"),
        **kw)]
    for tenant in tenants or ():
        slos.append(SLO(
            name=f"tenant-{tenant}-deadline",
            objective=tenant_objective,
            description=f"{tenant_objective:.0%} of tenant "
                        f"{tenant!r}'s requests are served (not "
                        f"quota-shed, not overload-shed, not "
                        f"expired)",
            bad_metrics=("kft_tenant_shed_total",
                         "kft_tenant_expired_total"),
            total_metrics=("kft_tenant_requests_total",),
            label_filter={"tenant": tenant},
            **kw))
    if ttft_p95_s is not None:
        slos.append(SLO(
            name="serving-ttft-p95",
            objective=0.95,
            description=f"95% of streamed generates reach first "
                        f"token within {ttft_p95_s * 1e3:.0f} ms",
            histogram="kft_serving_ttft_seconds",
            threshold_s=ttft_p95_s, **kw))
    if reconcile_p99_s is not None:
        slos.append(SLO(
            name="operator-reconcile-p99",
            objective=0.99,
            description=f"99% of reconciles complete within "
                        f"{reconcile_p99_s * 1e3:.0f} ms",
            histogram="kft_operator_reconcile_seconds",
            threshold_s=reconcile_p99_s, **kw))
    return slos


@dataclass
class _AlertRecord:
    """Mutable per-(SLO, window) state-machine cell."""

    state: str = "inactive"
    pending_since: Optional[float] = None
    clear_since: Optional[float] = None
    fired_at: Optional[float] = None
    fire_count: int = 0


class AlertManager:
    """Evaluates every SLO's burn-rate windows against the store and
    drives the per-(SLO, window) alert state machine; registered as a
    collector ``on_cycle`` hook so evaluation rides each scrape.

    ``for_s`` is the classic alerting ``for:`` clause (the condition
    must hold this long before an alert fires); ``resolve_s`` is the
    flap damper on the way down (the condition must stay clear this
    long before a firing alert resolves). Publishing is best-effort:
    a broken apiserver must never wedge the telemetry loop.
    """

    def __init__(self, store: TimeSeriesStore, slos: Sequence[SLO], *,
                 api: Optional[Any] = None, namespace: str = "default",
                 for_s: float = 30.0, resolve_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic,
                 history_size: int = 256):
        self.store = store
        self.slos = list(slos)
        self.api = api
        self.namespace = namespace
        self.for_s = float(for_s)
        self.resolve_s = float(resolve_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._records: Dict[Tuple[str, str], _AlertRecord] = {}
        #: Transition history (bounded): the CI artifact + dashboard
        #: timeline. Entries: {slo, window, severity, to, at (wall
        #: ISO, stamped at the transition), at_monotonic}.
        self.history: deque = deque(maxlen=int(history_size))
        self.last_evaluation: List[Dict[str, Any]] = []
        self._published_sig: Optional[Tuple] = None

    # -- state machine ---------------------------------------------------

    def _transition(self, slo: SLO, window: BurnWindow,
                    record: _AlertRecord, to: str, now: float,
                    burn: Dict[str, Any]) -> None:
        record.state = to
        _C_TRANSITIONS.labels(slo.name, to).inc()
        self.history.append({"slo": slo.name, "window": window.name,
                             "severity": window.severity, "to": to,
                             "at": datetime.datetime.now(
                                 datetime.timezone.utc).isoformat(),
                             "at_monotonic": round(now, 3),
                             "burn": burn})
        if to == "firing":
            record.fired_at = now
            record.fire_count += 1
            self._publish_event(slo, window, "AlertFiring", "Warning",
                                record, burn)
        elif to == "resolved":
            self._publish_event(slo, window, "AlertResolved", "Normal",
                                record, burn)

    def _step(self, slo: SLO, window: BurnWindow, now: float,
              long_burn: Optional[float], short_burn: Optional[float]
              ) -> _AlertRecord:
        key = (slo.name, window.name)
        record = self._records.setdefault(key, _AlertRecord())
        burn = {"long": None if long_burn is None
                else round(long_burn, 3),
                "short": None if short_burn is None
                else round(short_burn, 3),
                "factor": window.factor}
        if long_burn is None or short_burn is None:
            return record  # blind: hold whatever state we're in
        condition = (long_burn > window.factor
                     and short_burn > window.factor)
        if record.state in ("inactive", "resolved"):
            if condition:
                record.pending_since = now
                self._transition(slo, window, record, "pending", now,
                                 burn)
                if self.for_s <= 0.0:
                    self._transition(slo, window, record, "firing",
                                     now, burn)
            elif record.state == "resolved":
                record.state = "inactive"
        elif record.state == "pending":
            if not condition:
                record.pending_since = None
                self._transition(slo, window, record, "inactive", now,
                                 burn)
            elif now - (record.pending_since or now) >= self.for_s:
                self._transition(slo, window, record, "firing", now,
                                 burn)
        elif record.state == "firing":
            if condition:
                record.clear_since = None  # flap: stays firing
            else:
                if record.clear_since is None:
                    record.clear_since = now
                if now - record.clear_since >= self.resolve_s:
                    record.clear_since = None
                    self._transition(slo, window, record, "resolved",
                                     now, burn)
        return record

    def evaluate(self, now: Optional[float] = None
                 ) -> List[Dict[str, Any]]:
        """One evaluation pass over every SLO × window; returns (and
        retains) the full status rows the dashboard renders."""
        now = self._clock() if now is None else now
        rows: List[Dict[str, Any]] = []
        with self._lock:
            for slo in self.slos:
                row: Dict[str, Any] = {
                    "slo": slo.name,
                    "objective": slo.objective,
                    "description": slo.description,
                    "windows": [],
                }
                worst = "inactive"
                for window in slo.windows:
                    long_burn = slo.burn_rate(self.store, window.long_s,
                                              now)
                    short_burn = slo.burn_rate(self.store,
                                               window.short_s, now)
                    record = self._step(slo, window, now, long_burn,
                                        short_burn)
                    if (STATE_VALUES[record.state]
                            > STATE_VALUES[worst]):
                        worst = record.state
                    row["windows"].append({
                        "window": window.name,
                        "severity": window.severity,
                        "factor": window.factor,
                        "long_s": window.long_s,
                        "short_s": window.short_s,
                        "long_burn": None if long_burn is None
                        else round(long_burn, 3),
                        "short_burn": None if short_burn is None
                        else round(short_burn, 3),
                        "state": record.state,
                        "fire_count": record.fire_count,
                    })
                    _G_ALERT_STATE.labels(
                        slo.name, window.severity).set(
                        STATE_VALUES[record.state])
                row["state"] = worst
                rows.append(row)
            self.last_evaluation = rows
            # Publish only when the state-machine picture CHANGED: a
            # quiet fleet must not write the apiserver every scrape
            # cycle (burn rates jitter per cycle; states don't).
            sig = tuple(
                (key, record.state, record.fire_count)
                for key, record in sorted(self._records.items()))
            publish = sig != self._published_sig
        if publish:
            self._publish_configmap(rows)
            with self._lock:
                self._published_sig = sig
        return rows

    def firing(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [
                {"slo": slo_name, "window": window_name}
                for (slo_name, window_name), record
                in self._records.items() if record.state == "firing"]

    def state(self) -> Dict[str, Any]:
        """Evaluator snapshot (dashboard + CI artifact): last
        evaluation rows + transition history."""
        with self._lock:
            return {"slos": list(self.last_evaluation),
                    "history": list(self.history),
                    "for_s": self.for_s,
                    "resolve_s": self.resolve_s}

    # -- publishing ------------------------------------------------------

    def _publish_event(self, slo: SLO, window: BurnWindow,
                       reason: str, event_type: str,
                       record: _AlertRecord,
                       burn: Dict[str, Any]) -> None:
        """One k8s Event per firing/resolved transition (the operator
        lifecycle-event pattern; ``kubectl get events`` is the zero-
        dashboard alert surface). Deterministic name per episode so
        retried publishes dedupe via Conflict."""
        if self.api is None:
            return
        wall = datetime.datetime.now(
            datetime.timezone.utc).isoformat()
        message = (f"SLO {slo.name} ({window.severity}/{window.name} "
                   f"window): burn long={burn['long']} "
                   f"short={burn['short']} vs factor "
                   f"{window.factor} — {reason}")
        event = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {
                "name": f"kft-alert.{slo.name}.{window.name}"
                        f".{record.fire_count}.{reason.lower()}",
                "namespace": self.namespace,
            },
            "involvedObject": {
                "apiVersion": "v1",
                "kind": "ConfigMap",
                "name": ALERTS_CONFIGMAP,
                "namespace": self.namespace,
            },
            "reason": reason,
            "message": message,
            "type": event_type,
            "source": {"component": "kft-collector"},
            "firstTimestamp": wall,
            "lastTimestamp": wall,
            "count": 1,
        }
        try:
            self.api.create(event)
        except Exception:  # noqa: BLE001 — alerting must not wedge
            logger.warning("alert event publish failed",
                           exc_info=True)

    def _publish_configmap(self, rows: List[Dict[str, Any]]) -> None:
        """Best-effort ``kft-alerts`` ConfigMap publish — only called
        on a state change (evaluate gates it), so a steady fleet costs
        the apiserver nothing. History ships the wall time stamped at
        each transition, never per-cycle recomputed fields (monotonic
        stamps mean nothing to other processes and a churning payload
        would defeat the no-op-write suppression)."""
        if self.api is None:
            return
        with self._lock:
            history = []
            for h in self.history:
                h = dict(h)
                h.pop("at_monotonic", None)
                history.append(h)
        payload = json.dumps({"slos": rows, "history": history[-50:]},
                             sort_keys=True)
        try:
            from kubeflow_tpu.operator.fake import NotFound

            try:
                self.api.patch(
                    "ConfigMap", self.namespace, ALERTS_CONFIGMAP,
                    lambda o: o.setdefault("data", {}).update(
                        {ALERTS_KEY: payload}))
            except NotFound:
                self.api.create({
                    "apiVersion": "v1",
                    "kind": "ConfigMap",
                    "metadata": {"name": ALERTS_CONFIGMAP,
                                 "namespace": self.namespace},
                    "data": {ALERTS_KEY: payload},
                })
        except Exception:  # noqa: BLE001 — publishing must not wedge
            logger.debug("alerts ConfigMap publish failed",
                         exc_info=True)
