# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Trace assembly + latency attribution over fleet-collected spans.

One request that crosses the fleet (proxy → server → gRPC server →
engine, plus the second hop of role-split / hedge / resume) leaves its
spans in N processes whose ``time.monotonic()`` clocks never met —
absolute timestamps are NOT comparable across processes, only
durations and parent links are. This module is the join:

- :func:`assemble` builds the request tree from the ``span_id`` /
  ``parent_id`` linkage (:func:`obs.tracing.span_args`): each hop's
  root span carries its own id + its caller's id; spans recorded
  under a context are leaves parented on that hop.
- :func:`attribution` buckets the request's end-to-end latency into
  **queue** (admission wait: engine queue + micro-batcher
  queue_wait/batch_assembly), **prefill** (prompt passes), **decode**
  (token slices / batched executes), **relay** (proxy time around its
  upstream legs) and **gap** (server-side residual the instrumented
  spans don't explain: transport, JSON, scheduling), and reports how
  much of the measured wall time the buckets cover.
- :func:`waterfall_lines` renders the tree as text — the ``kft-trace``
  CLI's output (``python -m kubeflow_tpu.obs.trace <trace_id>
  --collector http://host:port`` against a collector sidecar's
  ``/trace`` endpoint, or ``--spans file`` over a /tracez dump).

The dashboard's Waterfall page (dashboard/server.py) renders the same
assembly/attribution over the in-process collector's
:class:`~kubeflow_tpu.obs.collector.SpanStore`.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request
from typing import Any, Dict, List, Optional

__all__ = [
    "SERVER_ROOT_SPANS",
    "assemble",
    "attribution",
    "export_workload",
    "waterfall_lines",
]

#: Per-hop root spans: one per process a request traversed. The proxy
#: root is the client-measured wall clock; server roots bound each
#: upstream leg.
PROXY_ROOT_SPANS = frozenset({"proxy_request"})
SERVER_ROOT_SPANS = frozenset({"http_request", "grpc_request",
                               "grpc_web_request"})

#: Span-name → attribution bucket for duration-carrying spans.
_BUCKET_BY_NAME = {
    "queue_wait": "queue",
    "batch_assembly": "queue",
    "engine_prefill": "prefill",
    "execute": "decode",
}


def _args(span: Dict[str, Any]) -> Dict[str, Any]:
    args = span.get("args")
    return args if isinstance(args, dict) else {}


def _f(value: Any) -> float:
    """Total float coercion: spans can arrive over the UNvalidated
    push path (POST /spans takes any dict), and one malformed field
    must degrade to 0 for that span, never 500 every read of its
    trace."""
    try:
        return float(value or 0.0)
    except (TypeError, ValueError):
        return 0.0


def _dur_ms(span: Dict[str, Any]) -> float:
    return _f(span.get("dur")) / 1e3


def assemble(spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Build the request tree for ONE trace's spans.

    Nodes are ``{"span": <event>, "children": [nodes]}``. A span with
    an ``args.span_id`` is a hop root (it can parent others); spans
    carrying only ``parent_id`` are leaves of that hop. Roots are
    spans whose parent id is absent or unknown (the collector may not
    have scraped every process yet — orphan subtrees surface as extra
    roots rather than disappearing). Children sort by timestamp
    (within one process that is meaningful; across processes the
    parent links, not the timestamps, carry the truth)."""
    by_id: Dict[str, Dict[str, Any]] = {}
    nodes = []
    for span in spans:
        node = {"span": span, "children": []}
        nodes.append(node)
        span_id = _args(span).get("span_id")
        if span_id and span_id not in by_id:
            by_id[span_id] = node
    roots = []
    for node in nodes:
        parent_id = _args(node["span"]).get("parent_id")
        parent = by_id.get(parent_id) if parent_id else None
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    for node in nodes:
        node["children"].sort(key=lambda n: _f(n["span"].get("ts")))
    # Proxy root first, then the longest spans — the waterfall's
    # natural reading order when a trace has stray roots.
    roots.sort(key=lambda n: (
        0 if n["span"].get("name") in PROXY_ROOT_SPANS else 1,
        -_f(n["span"].get("dur"))))
    return {"roots": roots, "spans": len(spans)}


def attribution(spans: List[Dict[str, Any]],
                total_ms: Optional[float] = None) -> Dict[str, Any]:
    """Bucket one trace's end-to-end latency.

    ``total_ms`` overrides the measured wall time (a client-side
    stopwatch); by default it is the proxy root span's duration,
    falling back to the server legs' sum for direct-to-server traces.

    - **queue / kv_fetch / prefill / decode** come from the engine's
      exact per-request figures (``engine_request``) plus the
      micro-batcher spans — no cross-process timestamp arithmetic.
      ``kv_fetch`` is the fleet KV tier's pull-through spend (ISSUE
      20), bucketed apart so it is never mistaken for decode time.
    - **relay** is MEASURED: the proxy root wall minus the proxy's
      own ``proxy_upstream`` windows (its time outside upstream
      awaits).
    - **gap** is the network+server residual of legs whose server
      span arrived: (upstream window − server wall) + (server wall −
      engine-attributed time).

    ``coverage`` counts only span-evidenced time: an upstream window
    whose server-side root never arrived (a process the collector
    didn't scrape) is NOT covered and lands in ``missing`` — exactly
    the signal the assembly layer owes you."""
    proxy_ms = 0.0
    server_ms = 0.0
    queue = prefill = decode = kv_fetch = 0.0
    legs: Dict[str, float] = {}
    upstream: Dict[str, float] = {}
    engine_seen = any(s.get("name") == "engine_request"
                      for s in spans)
    for span in spans:
        name = span.get("name", "")
        args = _args(span)
        if name in PROXY_ROOT_SPANS:
            proxy_ms += _dur_ms(span)
            continue
        if name == "proxy_upstream":
            leg = str(args.get("leg") or "primary")
            upstream[leg] = upstream.get(leg, 0.0) + _dur_ms(span)
            continue
        if name in SERVER_ROOT_SPANS:
            server_ms += _dur_ms(span)
            leg = str(args.get("leg") or "primary")
            legs[leg] = legs.get(leg, 0.0) + _dur_ms(span)
            continue
        if name == "engine_request":
            # The engine's own per-request attribution (queue wait
            # before a slot, prefill, decode-slice share) — exact, no
            # span-interval arithmetic needed.
            queue += _f(args.get("queue_ms"))
            prefill += _f(args.get("prefill_ms"))
            decode += _f(args.get("decode_ms"))
            # Fleet KV fetch spend (ISSUE 20) gets its OWN bucket:
            # pulling prefix pages from the rendezvous owner happens
            # before prefill and must never read as decode time.
            kv_fetch += _f(args.get("kv_fetch_ms"))
            continue
        bucket = _BUCKET_BY_NAME.get(name)
        if bucket == "queue":
            queue += _dur_ms(span)
        elif bucket == "decode":
            decode += _dur_ms(span)
        elif bucket == "prefill":
            # A slot-bound admission's engine_prefill rides inside
            # its engine_request's prefill_ms — don't double-count it.
            # The slot-less prefill-role hop (run_prefill, tagged
            # handoff=True) has no engine_request and ALWAYS counts:
            # it is the split path's real prefill.
            if args.get("handoff") or not engine_seen:
                prefill += _dur_ms(span)
    if total_ms is None:
        total_ms = proxy_ms if proxy_ms > 0 else server_ms
    attributed = queue + kv_fetch + prefill + decode
    server_residual = max(0.0, server_ms - attributed) \
        if server_ms > 0 else 0.0
    missing = []
    if proxy_ms == 0.0:
        missing.append("proxy_request")
    if server_ms == 0.0:
        missing.append("server_root")
    if not engine_seen and decode == 0.0:
        missing.append("engine_request")
    if upstream:
        upstream_total = sum(upstream.values())
        relay = (max(0.0, total_ms - upstream_total)
                 if proxy_ms > 0 else 0.0)
        explained = net_gap = 0.0
        for leg, window_ms in sorted(upstream.items()):
            server_leg = legs.get(leg, 0.0)
            if server_leg > 0.0:
                # Window fully evidenced: server wall + network gap.
                explained += window_ms
                net_gap += max(0.0, window_ms - server_leg)
            else:
                missing.append(f"server_leg:{leg}")
        gap = net_gap + server_residual
        covered = min(total_ms, relay + explained)
    else:
        # No proxy_upstream evidence (direct-to-server trace, or an
        # old proxy build): relay degrades to the proxy-vs-server
        # residual and coverage to what the server spans explain.
        relay = (max(0.0, total_ms - server_ms)
                 if proxy_ms > 0 else 0.0)
        gap = server_residual
        covered = min(total_ms, server_ms + relay) if server_ms > 0 \
            else min(total_ms, attributed)
    return {
        "total_ms": round(total_ms, 3),
        "buckets": {
            "queue_ms": round(queue, 3),
            "kv_fetch_ms": round(kv_fetch, 3),
            "prefill_ms": round(prefill, 3),
            "decode_ms": round(decode, 3),
            "relay_ms": round(relay, 3),
            "gap_ms": round(gap, 3),
        },
        "coverage": round(covered / total_ms, 4) if total_ms else 0.0,
        "legs": {leg: round(ms, 3)
                 for leg, ms in sorted(legs.items())},
        "upstream_legs": {leg: round(ms, 3)
                          for leg, ms in sorted(upstream.items())},
        "missing": missing,
    }


_INTERESTING_ARGS = ("leg", "model", "tenant", "outcome", "slot",
                     "reason", "tokens", "prompt_len", "rows",
                     "program", "shapes", "batch")


def waterfall_lines(assembled: Dict[str, Any]) -> List[str]:
    """Text waterfall of an assembled trace (the CLI's view)."""
    lines: List[str] = []

    def walk(node: Dict[str, Any], depth: int) -> None:
        span = node["span"]
        args = _args(span)
        extras = " ".join(
            f"{k}={args[k]}" for k in _INTERESTING_ARGS if k in args)
        lines.append(
            f"{'  ' * depth}{span.get('name', '?'):<18} "
            f"{_dur_ms(span):>9.2f} ms  pid={span.get('pid', '?')}"
            f"{'  ' + extras if extras else ''}")
        for child in node["children"]:
            walk(child, depth + 1)

    for root in assembled["roots"]:
        walk(root, 0)
    return lines


def _attribution_lines(report: Dict[str, Any]) -> List[str]:
    total = report["total_ms"] or 1.0
    lines = [f"e2e wall: {report['total_ms']:.2f} ms, "
             f"coverage {report['coverage'] * 100:.1f}%"]
    for key, ms in report["buckets"].items():
        frac = ms / total
        bar = "#" * max(0, min(40, int(round(frac * 40))))
        lines.append(f"  {key.removesuffix('_ms'):<8}"
                     f"{ms:>9.2f} ms  {frac * 100:>5.1f}%  {bar}")
    if report["legs"]:
        lines.append("  legs: " + ", ".join(
            f"{leg}={ms:.2f}ms" for leg, ms in report["legs"].items()))
    if report["missing"]:
        lines.append(f"  missing spans: {', '.join(report['missing'])}")
    return lines


def export_workload(spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Workload document for the fleet simulator (ISSUE 19): one row
    per traced request — relative arrival seconds, request class
    (model / tenant / phase hints) and the engine's EXACT service
    attribution triple, so ``scaling/simulator.py`` can replay the
    recorded traffic against a modeled fleet.

    Arrival anchors are each trace's root span timestamp, proxy root
    preferred: absolute ``ts`` values are only comparable within one
    process, and a fleet's proxy roots all come from the proxy.
    Traces anchored on different processes still export (a degraded
    arrival order beats a dropped request), the first arrival defines
    t=0."""
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for span in spans:
        args = _args(span)
        tid = args.get("trace_id") or args.get("request_id")
        if tid:
            by_trace.setdefault(str(tid), []).append(span)
    rows: List[Dict[str, Any]] = []
    for tid, tspans in by_trace.items():
        root = None
        for names in (PROXY_ROOT_SPANS, SERVER_ROOT_SPANS,
                      frozenset({"engine_request"})):
            anchored = [s for s in tspans if s.get("name") in names]
            if anchored:
                root = min(anchored, key=lambda s: _f(s.get("ts")))
                break
        if root is None:
            continue
        model = tenant = None
        for span in tspans:
            args = _args(span)
            model = model or args.get("model")
            tenant = tenant or args.get("tenant")
        report = attribution(tspans)
        buckets = report["buckets"]
        rows.append({
            "trace_id": tid,
            "ts_us": _f(root.get("ts")),
            "model": model,
            "tenant": tenant,
            "total_ms": report["total_ms"],
            "queue_ms": buckets["queue_ms"],
            "prefill_ms": buckets["prefill_ms"],
            "decode_ms": buckets["decode_ms"],
        })
    rows.sort(key=lambda r: (r["ts_us"], r["trace_id"]))
    t0 = rows[0]["ts_us"] if rows else 0.0
    for row in rows:
        row["arrival_s"] = round((row.pop("ts_us") - t0) / 1e6, 6)
    return {"version": 1, "requests": rows}


def _spans_from_file(path: str) -> List[Dict[str, Any]]:
    """Spans from a /tracez JSON document or a JSONL span dump."""
    with open(path) as f:
        text = f.read()
    text = text.strip()
    events: Any = None
    if text.startswith("{"):
        # A /tracez document is one JSON object; a JSONL dump's first
        # line is ALSO an object, so fall through on trailing data.
        try:
            doc = json.loads(text)
        except json.JSONDecodeError:
            pass
        else:
            events = doc.get("traceEvents", doc.get("spans", []))
    elif text.startswith("["):
        events = json.loads(text)
    if events is None:
        events = [json.loads(line) for line in text.splitlines()
                  if line.strip()]
    return [e for e in events if e.get("ph", "X") == "X"]


def _fetch_json(url: str, timeout_s: float) -> Any:
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode("utf-8", "replace"))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="kft-trace",
        description="Assemble one request's fleet-wide trace and "
                    "attribute its latency (docs/observability.md, "
                    "'Distributed tracing & latency attribution').")
    parser.add_argument("trace_id", nargs="?", default=None,
                        help="trace id (or request id) to assemble; "
                             "omit with --list to enumerate")
    parser.add_argument("--collector", default="http://localhost:9500",
                        help="collector exposition base URL (the "
                             "sidecar's --metrics_port surface)")
    parser.add_argument("--spans", default=None,
                        help="read spans from a /tracez JSON or span "
                             "JSONL file instead of the collector")
    parser.add_argument("--list", action="store_true",
                        help="list the trace ids the collector holds")
    parser.add_argument("--export-workload", default=None,
                        metavar="PATH", dest="export_workload",
                        help="write a simulator workload JSON (one "
                             "row per traced request: arrival time + "
                             "class + exact service attribution) from "
                             "ALL traces the collector (or --spans "
                             "file) holds; see docs/capacity.md")
    parser.add_argument("--timeout", type=float, default=5.0)
    parser.add_argument("--json", action="store_true",
                        help="emit the assembled document as JSON")
    args = parser.parse_args(argv)
    base = args.collector.rstrip("/")
    if "://" not in base:
        base = f"http://{base}"
    if args.list:
        doc = _fetch_json(f"{base}/traces", args.timeout)
        for row in doc.get("traces", []):
            print(f"{row['trace_id']}  spans={row['spans']}")
        return 0
    if args.export_workload:
        if args.spans:
            spans = _spans_from_file(args.spans)
        else:
            from urllib.parse import quote

            doc = _fetch_json(f"{base}/traces", args.timeout)
            spans = []
            for row in doc.get("traces", []):
                tid = quote(str(row["trace_id"]), safe="")
                trace_doc = _fetch_json(
                    f"{base}/trace?trace_id={tid}", args.timeout)
                spans.extend(trace_doc.get("spans", []))
        workload = export_workload(spans)
        with open(args.export_workload, "w") as f:
            json.dump(workload, f, indent=1, sort_keys=True)
        print(f"wrote {len(workload['requests'])} request(s) to "
              f"{args.export_workload}")
        return 0 if workload["requests"] else 1
    if not args.trace_id:
        parser.error("a trace_id is required (or --list)")
    if args.spans:
        spans = [s for s in _spans_from_file(args.spans)
                 if args.trace_id in (_args(s).get("trace_id"),
                                      _args(s).get("request_id"))]
    else:
        from urllib.parse import quote

        # Request ids are arbitrary client strings (X-Request-Id up
        # to 128 chars) — quote or metacharacters query the wrong id.
        doc = _fetch_json(
            f"{base}/trace?trace_id={quote(args.trace_id, safe='')}",
            args.timeout)
        spans = doc.get("spans", [])
    if not spans:
        print(f"no spans for trace {args.trace_id}", file=sys.stderr)
        return 1
    assembled = assemble(spans)
    report = attribution(spans)
    if args.json:
        print(json.dumps({"trace_id": args.trace_id,
                          "attribution": report,
                          "spans": spans}, indent=1))
        return 0
    print(f"trace {args.trace_id} — {assembled['spans']} span(s)")
    for line in waterfall_lines(assembled):
        print(line)
    print()
    for line in _attribution_lines(report):
        print(line)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
