# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Scrape surfaces + structured access logs.

Three delivery mechanisms for the same registry/tracer:

- :class:`MetricsHandler` / :class:`ChromeTraceHandler` — tornado
  routes for the processes that already run tornado (serving server,
  HTTP proxy, dashboard): ``/metrics`` (Prometheus text) and
  ``/tracez`` (Chrome trace JSON).
- :func:`start_exposition_server` — a stdlib ``http.server`` thread
  for the operator (no tornado in its control loop): same two paths
  plus ``/healthz``.
- :func:`access_log_function` — tornado's ``log_function`` hook
  emitting ONE JSON line per request on the ``kft.access`` logger
  (request_id, method, path, status, latency_ms, model, outcome)
  instead of tornado's unstructured access noise. The logger has no
  handler of its own: production mains configure logging and see the
  lines; pytest (which configures nothing) stays quiet.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from kubeflow_tpu.obs import metrics as obs_metrics
from kubeflow_tpu.obs import tracing as obs_tracing

# The tornado handlers are optional: the operator image runs no
# tornado — its scrape surface is the stdlib thread below, and this
# module must import cleanly there (controller.py main imports it).
try:
    import tornado.web as _tornado_web
except ImportError:  # pragma: no cover — serving images ship tornado
    _tornado_web = None

__all__ = [
    "ACCESS_LOGGER",
    "ChromeTraceHandler",
    "MetricsHandler",
    "TraceContextHandlerMixin",
    "access_log_function",
    "start_exposition_server",
]

#: The structured access-log channel. One JSON object per line.
ACCESS_LOGGER = "kft.access"


class TraceContextHandlerMixin:
    """The shared per-request observability behavior of every tornado
    surface (serving server, proxy, dashboard) — mix in BEFORE
    RequestHandler. ``prepare`` adopts/mints the trace context and
    echoes ``X-Request-Id``; ``on_finish`` records one server-side
    span when the subclass opts in via ``_obs_span``. Plain class (no
    tornado dependency): it only touches handler attributes, so it
    imports fine in tornado-less processes too."""

    #: Span name recorded per request; None keeps a handler out of
    #: the ring buffer (health/metrics polls every few seconds would
    #: evict the real request spans).
    _obs_span: Optional[str] = None
    #: Chrome-trace category for this surface's spans.
    _obs_cat = "app"

    def prepare(self) -> None:
        self._obs_ctx = obs_tracing.ensure_context(self.request.headers)
        self._obs_request_id = self._obs_ctx.request_id
        self.set_header(obs_tracing.REQUEST_ID_HEADER,
                        self._obs_ctx.request_id)

    def on_finish(self) -> None:
        if self._obs_span and obs_tracing.TRACER.enabled:
            dur = self.request.request_time()
            ctx = self._obs_ctx
            # The hop's ROOT span: carries its own span id (children
            # recorded under this context parent on it via
            # span_args's parent_id) plus the inbound parent — the
            # linkage the fleet-wide assembly joins on. Model and
            # tenant ride request-root spans only (the tenant value
            # arrives pre-capped via TenantLabelCapper — a
            # key-sprayer cannot explode span cardinality either).
            args = obs_tracing.root_span_args(
                ctx,
                path=self.request.path,
                status=self.get_status(),
                outcome=getattr(self, "_obs_outcome", None)
                or ("ok" if self.get_status() < 400 else "error"))
            model = getattr(self, "_obs_model", None)
            if model:
                args["model"] = model
            tenant = getattr(self, "_obs_tenant", None)
            if tenant:
                args["tenant"] = tenant
            obs_tracing.TRACER.record(
                self._obs_span, self._obs_cat,
                time.monotonic() - dur, dur, args)


def _tracez_filters(get_arg) -> Dict[str, Any]:
    """Parse the shared /tracez query grammar (?trace_id= / ?status= /
    ?min_duration_ms= / ?limit=) from any ``get_arg(name) ->
    Optional[str]``. Raises ValueError on a non-numeric number — the
    handlers answer 400, never 500."""
    filters: Dict[str, Any] = {
        "trace_id": get_arg("trace_id") or None,
        "status": get_arg("status") or None,
        "min_duration_ms": None,
        "limit": None,
    }
    raw = get_arg("min_duration_ms")
    if raw:
        filters["min_duration_ms"] = float(raw)
    raw = get_arg("limit")
    if raw:
        filters["limit"] = int(raw)
    return filters


def _tracez_body(tracer, filters: Dict[str, Any]) -> str:
    spans = obs_tracing.filter_spans(tracer.snapshot(), **filters)
    return json.dumps(tracer.export_chrome(spans=spans))


if _tornado_web is not None:
    class MetricsHandler(_tornado_web.RequestHandler):
        """GET /metrics — Prometheus text exposition of the default
        registry (or a ``metrics_registry`` app setting override).
        Content negotiation: OpenMetrics (with exemplars) when the
        scraper's Accept asks for it, text 0.0.4 otherwise."""

        def get(self):
            registry = self.application.settings.get("metrics_registry")
            ctype = obs_metrics.negotiate_content_type(
                self.request.headers.get("Accept"))
            self.set_header("Content-Type", ctype)
            self.finish(obs_metrics.render(
                registry,
                openmetrics=ctype is obs_metrics
                .CONTENT_TYPE_OPENMETRICS))

    class ChromeTraceHandler(_tornado_web.RequestHandler):
        """GET /tracez — the span ring buffer as Chrome trace-event
        JSON (open in Perfetto / chrome://tracing;
        docs/observability.md). Query filters ?trace_id= / ?status= /
        ?min_duration_ms= / ?limit= narrow the dump (a full ring is
        megabytes of JSON; the exemplar workflow lands here with a
        trace id in hand)."""

        def get(self):
            tracer = (self.application.settings.get("tracer")
                      or obs_tracing.TRACER)
            try:
                filters = _tracez_filters(
                    lambda name: self.get_query_argument(name, ""))
            except ValueError as e:
                self.set_status(400)
                return self.finish({"error": str(e)})
            self.set_header("Content-Type", "application/json")
            self.finish(_tracez_body(tracer, filters))
else:  # pragma: no cover — tornado-less images use the stdlib server
    MetricsHandler = ChromeTraceHandler = None


def access_log_function(component: str):
    """Build tornado's ``log_function`` for one component: called once
    per finished request, emits the structured line. Handlers may stash
    ``_obs_request_id`` / ``_obs_model`` / ``_obs_outcome`` attributes
    on themselves to enrich the record."""
    logger = logging.getLogger(ACCESS_LOGGER)

    def log(handler) -> None:
        try:
            record: Dict[str, Any] = {
                "component": component,
                "method": handler.request.method,
                "path": handler.request.uri,
                "status": handler.get_status(),
                "latency_ms": round(
                    handler.request.request_time() * 1e3, 3),
            }
            request_id = getattr(handler, "_obs_request_id", None)
            if request_id:
                record["request_id"] = request_id
            model = getattr(handler, "_obs_model", None)
            if model:
                record["model"] = model
            outcome = getattr(handler, "_obs_outcome", None)
            if outcome:
                record["outcome"] = outcome
            logger.info("%s", json.dumps(record, sort_keys=True))
        except Exception:  # noqa: BLE001 — logging must never 500
            logger.debug("access log failed", exc_info=True)

    return log


#: Push-body ceiling for POST /spans: a batch bigger than this is a
#: misbehaving shipper, not traffic — rejected, never buffered.
MAX_SPAN_PUSH_BYTES = 4 * 1024 * 1024


class _ExpositionHandler(BaseHTTPRequestHandler):
    """stdlib handler: /metrics, /tracez, /healthz — plus, when the
    server carries a ``span_store`` (collector sidecar), the trace
    assembly surface: GET /traces (ids), GET /trace?trace_id= (spans
    + attribution, what ``kft-trace`` reads) and POST /spans (the
    shipper's push path). Server attributes carry the registry/
    tracer/span_store (set by start_exposition_server)."""

    def do_GET(self):  # noqa: N802 — stdlib contract
        path, _, query = self.path.partition("?")
        span_store = getattr(self.server, "span_store", None)
        if path == "/metrics":
            ctype = obs_metrics.negotiate_content_type(
                self.headers.get("Accept"))
            body = obs_metrics.render(
                getattr(self.server, "registry", None),
                openmetrics=ctype is obs_metrics.CONTENT_TYPE_OPENMETRICS
            ).encode()
        elif path == "/tracez":
            from urllib.parse import parse_qs

            tracer = (getattr(self.server, "tracer", None)
                      or obs_tracing.TRACER)
            params = parse_qs(query)
            try:
                filters = _tracez_filters(
                    lambda name: (params.get(name) or [""])[0])
            except ValueError as e:
                self.send_error(400, str(e))
                return
            body = _tracez_body(tracer, filters).encode()
            ctype = "application/json"
        elif path == "/traces" and span_store is not None:
            body = json.dumps(
                {"traces": span_store.trace_ids(),
                 "store": span_store.state()}).encode()
            ctype = "application/json"
        elif path == "/trace" and span_store is not None:
            from urllib.parse import parse_qs

            from kubeflow_tpu.obs import trace as obs_trace

            trace_id = (parse_qs(query).get("trace_id")
                        or [""])[0]
            if not trace_id:
                self.send_error(400, "trace_id is required")
                return
            spans = span_store.trace(trace_id)
            body = json.dumps(
                {"trace_id": trace_id, "spans": spans,
                 "attribution": (obs_trace.attribution(spans)
                                 if spans else None)}).encode()
            ctype = "application/json"
        elif path == "/healthz":
            body = b'{"status": "ok"}'
            ctype = "application/json"
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):  # noqa: N802 — stdlib contract
        path, _, _query = self.path.partition("?")
        span_store = getattr(self.server, "span_store", None)
        if path != "/spans" or span_store is None:
            self.send_error(404)
            return
        length = int(self.headers.get("Content-Length") or 0)
        if not 0 < length <= MAX_SPAN_PUSH_BYTES:
            self.send_error(413 if length else 400,
                            "span push body outside bounds")
            return
        try:
            doc = json.loads(self.rfile.read(length))
            spans = doc.get("spans", [])
            if not isinstance(spans, list) or not all(
                    isinstance(s, dict) for s in spans):
                raise ValueError("'spans' must be a list of span "
                                 "objects")
            ingested, dropped = span_store.ingest(
                spans, instance=doc.get("component") or None,
                path="push")
        except (ValueError, TypeError) as e:
            self.send_error(400, f"bad span push: {e}")
            return
        body = json.dumps({"ingested": ingested,
                           "dropped": dropped}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, format, *args):  # noqa: A002 — stdlib sig
        pass  # scrapes every few seconds must not spam stderr


def start_exposition_server(port: int = 0, *,
                            registry: Optional[Any] = None,
                            tracer: Optional[Any] = None,
                            span_store: Optional[Any] = None,
                            host: str = "0.0.0.0"):
    """Serve /metrics + /tracez + /healthz from a daemon thread (the
    operator's scrape surface — it runs no tornado). With a
    ``span_store`` (collector sidecar), also serves the trace
    assembly endpoints (/traces, /trace) and accepts span pushes
    (POST /spans). Returns the ``ThreadingHTTPServer``;
    ``server.server_address[1]`` is the bound port (useful with
    port=0), ``server.shutdown()`` stops it."""
    server = ThreadingHTTPServer((host, port), _ExpositionHandler)
    server.daemon_threads = True
    server.registry = registry
    server.tracer = tracer
    server.span_store = span_store
    thread = threading.Thread(target=server.serve_forever,
                              name="obs-exposition", daemon=True)
    thread.start()
    return server
