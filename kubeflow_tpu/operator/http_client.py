# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Stdlib HTTP client for the Kubernetes apiserver.

The production surface of the watch-driven operator: no kubectl
binary, no kubernetes python package — just urllib against the
apiserver REST API with the in-cluster ServiceAccount credentials
(token + CA bundle mounted by the kubelet). Replaces the
kubectl-subprocess shim as the operator image's client (the shim
remains for dev workflows); the reference's equivalent was client-go
inside the external Go operator image
(``kubeflow/core/prototypes/all.jsonnet:10``).

Same method surface as the in-memory fake
(:mod:`kubeflow_tpu.operator.fake`) plus ``watch`` — so the
reconciler, the watch controller, and the fuzz suite run unchanged
against either. Error taxonomy maps HTTP onto the fake's exceptions:
404 → NotFound, 409 → Conflict, 410 → Gone, 429 → TooManyRequests,
5xx → ServerError.
"""

from __future__ import annotations

import json
import logging
import os
import ssl
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from kubeflow_tpu.manifests.tpujob import GROUP, KIND, PLURAL, VERSION
from kubeflow_tpu.operator.fake import (
    Conflict,
    Gone,
    NotFound,
    ServerError,
    TooManyRequests,
)

logger = logging.getLogger(__name__)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# kind → (api prefix, group/version, plural). Only what the
# reconciler touches; unknown kinds fail loudly.
_RESOURCES: Dict[str, Tuple[str, str, str]] = {
    KIND: ("apis", f"{GROUP}/{VERSION}", PLURAL),
    "Pod": ("api", "v1", "pods"),
    "Deployment": ("apis", "apps/v1", "deployments"),
    "Service": ("api", "v1", "services"),
    "PodDisruptionBudget": ("apis", "policy/v1", "poddisruptionbudgets"),
    "Event": ("api", "v1", "events"),
    "ConfigMap": ("api", "v1", "configmaps"),
    "Lease": ("apis", "coordination.k8s.io/v1", "leases"),
}


class HttpApiClient:
    """Apiserver access over plain HTTP(S) with a bearer token."""

    def __init__(self, base_url: str, *, token: Optional[str] = None,
                 ca_cert: Optional[str] = None,
                 timeout: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout
        if ca_cert:
            self._ssl = ssl.create_default_context(cafile=ca_cert)
        elif base_url.startswith("https"):
            self._ssl = ssl.create_default_context()
        else:
            self._ssl = None
        # Fencing for watch streams during shutdown.
        self._lock = threading.Lock()

    @classmethod
    def in_cluster(cls) -> "HttpApiClient":
        """The kubelet-mounted ServiceAccount contract."""
        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        with open(f"{SA_DIR}/token") as f:
            token = f.read().strip()
        return cls(f"https://{host}:{port}", token=token,
                   ca_cert=f"{SA_DIR}/ca.crt")

    # -- plumbing ---------------------------------------------------------

    def _path(self, kind: str, namespace: Optional[str],
              name: Optional[str] = None, *,
              subresource: Optional[str] = None) -> str:
        try:
            prefix, group_version, plural = _RESOURCES[kind]
        except KeyError:
            raise ValueError(f"unmapped kind {kind!r}") from None
        parts = [self.base_url, prefix, group_version]
        if namespace is not None:
            parts += ["namespaces", namespace]
        parts.append(plural)
        if name is not None:
            parts.append(name)
        if subresource:
            parts.append(subresource)
        return "/".join(parts)

    def _request(self, method: str, url: str,
                 body: Optional[Dict[str, Any]] = None,
                 timeout: Optional[float] = None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            return urllib.request.urlopen(
                req, timeout=self.timeout if timeout is None else timeout,
                context=self._ssl)
        except urllib.error.HTTPError as err:
            detail = err.read().decode(errors="replace")[:500]
            if err.code == 404:
                raise NotFound(f"{method} {url}: {detail}") from None
            if err.code == 409:
                raise Conflict(f"{method} {url}: {detail}") from None
            if err.code == 410:
                raise Gone(f"{method} {url}: {detail}") from None
            if err.code == 429:
                raise TooManyRequests(
                    f"{method} {url}: {detail}") from None
            if err.code >= 500:
                raise ServerError(
                    f"{method} {url} -> {err.code}: {detail}") from None
            raise RuntimeError(
                f"{method} {url} -> {err.code}: {detail}") from None

    def _json(self, method: str, url: str,
              body: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        with self._request(method, url, body) as resp:
            return json.loads(resp.read().decode())

    # -- store surface (same shape as FakeApiServer) ----------------------

    def create(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        kind = obj["kind"]
        ns = obj.get("metadata", {}).get("namespace", "default")
        return self._json("POST", self._path(kind, ns), obj)

    def get(self, kind: str, namespace: str, name: str) -> Dict[str, Any]:
        return self._json("GET", self._path(kind, namespace, name))

    @staticmethod
    def _selector(label_selector: Dict[str, Optional[str]]) -> str:
        """Dict → k8s labelSelector string; None values = existence
        (``key``), else equality (``key=value``)."""
        return ",".join(k if v is None else f"{k}={v}"
                        for k, v in label_selector.items())

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, Optional[str]]] = None,
             field_selector: Optional[Dict[str, str]] = None
             ) -> List[Dict[str, Any]]:
        return self.list_with_version(kind, namespace, label_selector,
                                      field_selector)[0]

    def list_with_version(self, kind: str,
                          namespace: Optional[str] = None,
                          label_selector: Optional[
                              Dict[str, Optional[str]]] = None,
                          field_selector: Optional[Dict[str, str]] = None
                          ) -> Tuple[List[Dict[str, Any]], int]:
        """(items, collection resourceVersion) — the version is the
        watch resume horizon: watching from it replays exactly the
        events after this list. ``field_selector`` filters server-side
        (``fieldSelector=involvedObject.name=myjob``) so e.g. a
        dashboard event query never lists a whole busy namespace."""
        url = self._path(kind, namespace)
        params = {}
        if label_selector:
            params["labelSelector"] = self._selector(label_selector)
        if field_selector:
            params["fieldSelector"] = ",".join(
                f"{k}={v}" for k, v in field_selector.items())
        if params:
            url += "?" + urllib.parse.urlencode(params)
        body = self._json("GET", url)
        version = int(
            body.get("metadata", {}).get("resourceVersion", 0) or 0)
        items = body.get("items", [])
        for item in items:
            # List items legally omit kind/apiVersion; the watch
            # controller keys on obj["kind"].
            item.setdefault("kind", kind)
        return items, version

    def patch(self, kind: str, namespace: str, name: str,
              mutate: Callable[[Dict[str, Any]], None]) -> Dict[str, Any]:
        """Read-modify-PUT with optimistic concurrency: the PUT
        carries the read's resourceVersion, so a concurrent writer
        surfaces as Conflict (the taxonomy the reconciler already
        handles) instead of a lost update. A mutation that changes
        nothing skips the PUT entirely (the apiserver would suppress
        the no-change write anyway — skipping it client-side saves
        the round trip, half of a steady-state pass's traffic)."""
        obj = self.get(kind, namespace, name)
        before = json.loads(json.dumps(obj))
        mutate(obj)
        if obj == before:
            return obj
        sub = "status" if kind == KIND else None
        return self._json(
            "PUT", self._path(kind, namespace, name, subresource=sub),
            obj)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._json("DELETE", self._path(kind, namespace, name))

    # -- scale subresource -------------------------------------------------

    def get_scale(self, kind: str, namespace: str,
                  name: str) -> Dict[str, Any]:
        """GET the scale subresource (autoscaling/v1 Scale) — the
        serving autoscaler's read path."""
        return self._json(
            "GET", self._path(kind, namespace, name,
                              subresource="scale"))

    def update_scale(self, kind: str, namespace: str, name: str,
                     replicas: int) -> Dict[str, Any]:
        """PUT the scale subresource with the desired replica count —
        the narrowest write that resizes a Deployment (what `kubectl
        scale` does; no pod-template RBAC needed). Read-modify-PUT so
        the carried resourceVersion turns a concurrent writer into a
        Conflict, like patch()."""
        scale = self.get_scale(kind, namespace, name)
        scale.setdefault("spec", {})["replicas"] = int(replicas)
        return self._json(
            "PUT", self._path(kind, namespace, name,
                              subresource="scale"), scale)

    def pod_logs(self, namespace: str, name: str, *,
                 tail: int = 100) -> str:
        """GET the pod's log subresource (text/plain, not JSON)."""
        url = (self._path("Pod", namespace, name, subresource="log")
               + "?" + urllib.parse.urlencode({"tailLines": str(tail)}))
        with self._request("GET", url) as resp:
            return resp.read().decode(errors="replace")

    # -- watch ------------------------------------------------------------

    def watch(self, kind: str, namespace: Optional[str] = None,
              resource_version: int = 0,
              stop: Optional[threading.Event] = None,
              timeout: Optional[float] = None,
              label_selector: Optional[Dict[str, Optional[str]]] = None,
              ) -> Iterator[Tuple[str, Dict[str, Any]]]:
        """Stream (event_type, object) from a server-side watch.

        The stream ends at the server's timeout (``timeoutSeconds``);
        the caller (WatchController) re-watches from its last seen
        resourceVersion. A compacted version surfaces as Gone — both
        as HTTP 410 and as an ERROR event in the stream. BOOKMARK
        events are passed through (their only payload is a fresh
        resourceVersion — callers use it to keep the resume point
        current across idle periods instead of going Gone)."""
        params = {"watch": "1",
                  "resourceVersion": str(resource_version),
                  "allowWatchBookmarks": "true",
                  "timeoutSeconds": str(int(timeout or 60))}
        if label_selector:
            params["labelSelector"] = self._selector(label_selector)
        url = self._path(kind, namespace) + "?" + urllib.parse.urlencode(
            params)
        resp = self._request("GET", url, timeout=(timeout or 60) + 10)
        with resp:
            for raw in resp:
                if stop is not None and stop.is_set():
                    return
                line = raw.strip()
                if not line:
                    continue
                event = json.loads(line)
                event_type = event.get("type")
                obj = event.get("object", {})
                if event_type == "ERROR":
                    code = obj.get("code")
                    if code == 410:
                        raise Gone(obj.get("message", "compacted"))
                    if code == 429:
                        raise TooManyRequests(
                            obj.get("message", "throttled"))
                    if code is not None and code >= 500:
                        raise ServerError(
                            obj.get("message", f"watch error {code}"))
                    raise RuntimeError(f"watch error: {obj}")
                obj.setdefault("kind", kind)
                yield event_type, obj
