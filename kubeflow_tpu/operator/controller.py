"""Controller loop: drives the Reconciler against an apiserver.

Two client flavors: the in-memory fake (tests) and a kubectl-backed
shim (real clusters; the environment ships no kubernetes python
client — kubectl is the portable surface, and `kft apply` already
uses it). The loop is deliberately level-triggered polling: TPU jobs
are long-running and gang transitions are coarse, so a short resync
period is simpler and more robust than a watch cache.
"""

from __future__ import annotations

import argparse
import json
import logging
import subprocess
import time
from typing import Any, Callable, Dict, List, Optional

from kubeflow_tpu.manifests.tpujob import KIND, PLURAL, GROUP
from kubeflow_tpu.operator.fake import Conflict, NotFound
from kubeflow_tpu.operator.reconciler import Reconciler

logger = logging.getLogger(__name__)


class KubectlClient:
    """Apiserver access via the kubectl CLI (same interface as
    FakeApiServer's store surface)."""

    def _run(self, *args: str, input_data: Optional[str] = None) -> str:
        proc = subprocess.run(
            ["kubectl", *args], capture_output=True, text=True,
            input=input_data)
        if proc.returncode != 0:
            if "NotFound" in proc.stderr or "not found" in proc.stderr:
                raise NotFound(proc.stderr.strip())
            if "AlreadyExists" in proc.stderr or "already exists" in proc.stderr:
                # Same taxonomy as the fake store, so the reconciler's
                # idempotent-create handling works on real clusters
                # too (the dashboard maps this string the same way).
                raise Conflict(proc.stderr.strip())
            raise RuntimeError(f"kubectl {' '.join(args)}: {proc.stderr}")
        return proc.stdout

    @staticmethod
    def _resource(kind: str) -> str:
        return f"{PLURAL}.{GROUP}" if kind == KIND else kind.lower() + "s"

    def create(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        out = self._run("create", "-f", "-", "-o", "json",
                        input_data=json.dumps(obj))
        return json.loads(out)

    def get(self, kind: str, namespace: str, name: str) -> Dict[str, Any]:
        out = self._run("get", self._resource(kind), name, "-n", namespace,
                        "-o", "json")
        return json.loads(out)

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None
             ) -> List[Dict[str, Any]]:
        args = ["get", self._resource(kind), "-o", "json"]
        args += ["-n", namespace] if namespace else ["--all-namespaces"]
        if label_selector:
            args += ["-l", ",".join(f"{k}={v}"
                                    for k, v in label_selector.items())]
        return json.loads(self._run(*args)).get("items", [])

    def patch(self, kind: str, namespace: str, name: str,
              mutate: Callable[[Dict[str, Any]], None]) -> Dict[str, Any]:
        obj = self.get(kind, namespace, name)
        mutate(obj)
        sub = ["--subresource=status"] if kind == KIND else []
        out = self._run("replace", *sub, "-f", "-", "-o", "json",
                        input_data=json.dumps(obj))
        return json.loads(out)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._run("delete", self._resource(kind), name, "-n", namespace,
                  "--wait=false")


def run_controller(api, *, resync_seconds: float = 5.0,
                   namespace: Optional[str] = None,
                   max_iterations: Optional[int] = None) -> None:
    reconciler = Reconciler(api)
    iteration = 0
    while max_iterations is None or iteration < max_iterations:
        iteration += 1
        try:
            jobs = api.list(KIND, namespace)
        except Exception:  # noqa: BLE001
            logger.exception("listing TPUJobs failed")
            jobs = []
        for job in jobs:
            try:
                reconciler.reconcile(job)
            except Exception:  # noqa: BLE001
                logger.exception(
                    "reconcile failed for %s/%s",
                    job["metadata"].get("namespace"),
                    job["metadata"]["name"])
        if max_iterations is None or iteration < max_iterations:
            time.sleep(resync_seconds)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tpujob-operator")
    parser.add_argument("--namespace", default=None)
    parser.add_argument("--resync-seconds", type=float, default=5.0)
    parser.add_argument("--controller-config-file", default=None)
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(levelname)s|%(asctime)s|%(pathname)s|%(lineno)d| %(message)s",
        datefmt="%Y-%m-%dT%H:%M:%S",
    )
    if args.controller_config_file:
        logger.info("controller config: %s", args.controller_config_file)
    run_controller(KubectlClient(), resync_seconds=args.resync_seconds,
                   namespace=args.namespace)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
