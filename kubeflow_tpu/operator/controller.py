# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Controller loops: drive the Reconciler against an apiserver.

Primary mode is WATCH-driven (the reference's informer pattern — its
operator was an external Go image built on client-go informers,
``kubeflow/core/prototypes/all.jsonnet:10``): list+watch TPUJobs and
their pods with resourceVersion resume, enqueue the owning job on
every event, reconcile from a worker loop, and fall back to a
periodic full relist as the level-triggered safety net. Reaction to a
pod failure is event-latency (sub-second), not a resync period.

Clients: the in-memory fake (tests), the stdlib-HTTP apiserver client
(production, :mod:`kubeflow_tpu.operator.http_client` — no kubectl in
the operator image), and a kubectl-backed shim kept for dev
clusters/`kft apply` parity. The old polling loop remains as
``run_controller`` for the kubectl shim, which has no watch surface.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import subprocess
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from kubeflow_tpu.manifests.tpujob import KIND, PLURAL, GROUP
from kubeflow_tpu.operator.fake import Conflict, Gone, NotFound
from kubeflow_tpu.operator.reconciler import JOB_LABEL, Reconciler

logger = logging.getLogger(__name__)


class KubectlClient:
    """Apiserver access via the kubectl CLI (same interface as
    FakeApiServer's store surface)."""

    def _run(self, *args: str, input_data: Optional[str] = None) -> str:
        proc = subprocess.run(
            ["kubectl", *args], capture_output=True, text=True,
            input=input_data)
        if proc.returncode != 0:
            if "NotFound" in proc.stderr or "not found" in proc.stderr:
                raise NotFound(proc.stderr.strip())
            if "AlreadyExists" in proc.stderr or "already exists" in proc.stderr:
                # Same taxonomy as the fake store, so the reconciler's
                # idempotent-create handling works on real clusters
                # too (the dashboard maps this string the same way).
                raise Conflict(proc.stderr.strip())
            raise RuntimeError(f"kubectl {' '.join(args)}: {proc.stderr}")
        return proc.stdout

    @staticmethod
    def _resource(kind: str) -> str:
        return f"{PLURAL}.{GROUP}" if kind == KIND else kind.lower() + "s"

    def create(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        out = self._run("create", "-f", "-", "-o", "json",
                        input_data=json.dumps(obj))
        return json.loads(out)

    def get(self, kind: str, namespace: str, name: str) -> Dict[str, Any]:
        out = self._run("get", self._resource(kind), name, "-n", namespace,
                        "-o", "json")
        return json.loads(out)

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None,
             field_selector: Optional[Dict[str, str]] = None
             ) -> List[Dict[str, Any]]:
        args = ["get", self._resource(kind), "-o", "json"]
        args += ["-n", namespace] if namespace else ["--all-namespaces"]
        if label_selector:
            args += ["-l", ",".join(f"{k}={v}"
                                    for k, v in label_selector.items())]
        if field_selector:
            args += ["--field-selector",
                     ",".join(f"{k}={v}"
                              for k, v in field_selector.items())]
        return json.loads(self._run(*args)).get("items", [])

    def patch(self, kind: str, namespace: str, name: str,
              mutate: Callable[[Dict[str, Any]], None]) -> Dict[str, Any]:
        obj = self.get(kind, namespace, name)
        mutate(obj)
        sub = ["--subresource=status"] if kind == KIND else []
        out = self._run("replace", *sub, "-f", "-", "-o", "json",
                        input_data=json.dumps(obj))
        return json.loads(out)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._run("delete", self._resource(kind), name, "-n", namespace,
                  "--wait=false")

    def pod_logs(self, namespace: str, name: str, *,
                 tail: int = 100) -> str:
        return self._run("logs", name, "-n", namespace,
                         f"--tail={tail}")


class WatchController:
    """Informer-style controller: watch TPUJobs + pods, enqueue the
    owning job per event, reconcile from one worker loop (serialized —
    the reconciler is pass-atomic but not designed for concurrent
    passes over one job), periodic relist as the safety net."""

    def __init__(self, api, *, namespace: Optional[str] = None,
                 relist_seconds: float = 30.0,
                 reconciler: Optional[Reconciler] = None,
                 elector=None):
        self.api = api
        self.namespace = namespace
        self.relist_seconds = relist_seconds
        self.reconciler = reconciler or Reconciler(api)
        # Optional LeaderElector (operator/leader.py): watchers run
        # regardless (warm cache), reconciles only while leading.
        self.elector = elector
        self.stop = threading.Event()
        self._queue: Set[Tuple[str, str]] = set()  # (ns, name)
        self._cond = threading.Condition()
        self._watchers: List[threading.Thread] = []

    # -- queue ------------------------------------------------------------

    def enqueue(self, namespace: str, name: str) -> None:
        with self._cond:
            self._queue.add((namespace, name))
            self._cond.notify()

    def _drain_queue(self) -> List[Tuple[str, str]]:
        with self._cond:
            if not self._queue:
                self._cond.wait(timeout=0.2)
            keys = sorted(self._queue)
            self._queue.clear()
            return keys

    # -- watchers ---------------------------------------------------------

    def _job_key_of(self, kind: str, obj: Dict[str, Any]
                    ) -> Optional[Tuple[str, str]]:
        meta = obj.get("metadata", {})
        ns = meta.get("namespace", "default")
        if kind == KIND:
            return (ns, meta["name"])
        label = meta.get("labels", {}).get(JOB_LABEL)
        return (ns, label) if label else None

    def _watch_loop(self, kind: str) -> None:
        """One resumable watch: list for the horizon revision, then
        stream events, re-watching from the last seen version on
        stream end and relisting on Gone (the compacted-version 410).
        The Pod watch is bounded by a JOB_LABEL-existence selector —
        the operator must scale with gang count, not with whatever
        else runs on the cluster."""
        selector = {JOB_LABEL: None} if kind == "Pod" else None
        version = 0
        while not self.stop.is_set():
            try:
                if version == 0:
                    # Fresh horizon: everything current is (re)queued
                    # so no event preceding the watch can be missed.
                    items, version = self.api.list_with_version(
                        kind, self.namespace, selector)
                    for obj in items:
                        key = self._job_key_of(kind, obj)
                        if key:
                            self.enqueue(*key)
                for event_type, obj in self.api.watch(
                        kind, self.namespace, resource_version=version,
                        stop=self.stop, timeout=self.relist_seconds,
                        label_selector=selector):
                    version = int(obj.get("metadata", {})
                                  .get("resourceVersion", version))
                    if event_type == "BOOKMARK":
                        continue  # payload IS the fresh resume point
                    key = self._job_key_of(kind, obj)
                    if key:
                        self.enqueue(*key)
                # Server-side watch timeout: re-watch from `version`.
            except Gone:
                logger.info("%s watch compacted; relisting", kind)
                version = 0
            except Exception:  # noqa: BLE001
                logger.exception("%s watch failed; relisting", kind)
                version = 0
                self.stop.wait(1.0)

    # -- main loop --------------------------------------------------------

    def run(self, *, max_seconds: Optional[float] = None) -> None:
        for kind in (KIND, "Pod"):
            t = threading.Thread(target=self._watch_loop, args=(kind,),
                                 name=f"watch-{kind}", daemon=True)
            t.start()
            self._watchers.append(t)
        if self.elector is not None:
            t = threading.Thread(target=self.elector.loop,
                                 name="leader-elector", daemon=True)
            t.start()
            self._watchers.append(t)
        deadline = (time.monotonic() + max_seconds
                    if max_seconds is not None else None)
        last_relist = time.monotonic()
        was_leader = False
        try:
            while not self.stop.is_set():
                if deadline is not None and time.monotonic() >= deadline:
                    break
                if self.elector is not None:
                    if self.elector.broken.is_set():
                        # The lease path is persistently failing (e.g.
                        # 403 from stale RBAC): crash loudly so the
                        # pod restarts visibly instead of idling as a
                        # forever-follower — a silent outage.
                        raise RuntimeError(
                            "leader elector broken: lease API "
                            "persistently unavailable")
                    if not self.elector.is_leader():
                        # Follower: keep the queue (events accumulate
                        # for the takeover), reconcile nothing.
                        was_leader = False
                        self.stop.wait(0.05)
                        continue
                    if not was_leader:
                        # Fresh leadership: force an immediate relist —
                        # anything the previous leader half-finished
                        # must be re-observed now, not a relist period
                        # from now.
                        was_leader = True
                        last_relist = float("-inf")
                now = time.monotonic()
                if now - last_relist >= self.relist_seconds:
                    # Level-triggered safety net: a dropped event can
                    # delay a job at most one relist period.
                    last_relist = now
                    try:
                        for job in self.api.list(KIND, self.namespace):
                            meta = job["metadata"]
                            self.enqueue(
                                meta.get("namespace", "default"),
                                meta["name"])
                    except Exception:  # noqa: BLE001
                        logger.exception("relist failed")
                for ns, name in self._drain_queue():
                    try:
                        job = self.api.get(KIND, ns, name)
                    except NotFound:
                        continue  # deleted; GC is ownerReference-driven
                    try:
                        self.reconciler.reconcile(job)
                    except Exception:  # noqa: BLE001
                        logger.exception("reconcile failed for %s/%s",
                                         ns, name)
                        self.enqueue(ns, name)  # retry next wake-up
                        self.stop.wait(0.5)
        finally:
            self.stop.set()
            if self.elector is not None:
                self.elector.stop.set()
            for t in self._watchers:
                t.join(timeout=5.0)


def run_watch_controller(api, *, namespace: Optional[str] = None,
                         relist_seconds: float = 30.0,
                         max_seconds: Optional[float] = None) -> None:
    WatchController(
        api, namespace=namespace, relist_seconds=relist_seconds,
    ).run(max_seconds=max_seconds)


def run_controller(api, *, resync_seconds: float = 5.0,
                   namespace: Optional[str] = None,
                   max_iterations: Optional[int] = None) -> None:
    reconciler = Reconciler(api)
    iteration = 0
    while max_iterations is None or iteration < max_iterations:
        iteration += 1
        try:
            jobs = api.list(KIND, namespace)
        except Exception:  # noqa: BLE001
            logger.exception("listing TPUJobs failed")
            jobs = []
        for job in jobs:
            try:
                reconciler.reconcile(job)
            except Exception:  # noqa: BLE001
                logger.exception(
                    "reconcile failed for %s/%s",
                    job["metadata"].get("namespace"),
                    job["metadata"]["name"])
        if max_iterations is None or iteration < max_iterations:
            time.sleep(resync_seconds)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tpujob-operator")
    parser.add_argument("--namespace", default=None)
    parser.add_argument("--resync-seconds", type=float, default=5.0,
                        help="poll mode resync period")
    parser.add_argument("--relist-seconds", type=float, default=30.0,
                        help="watch mode relist safety-net period")
    parser.add_argument("--controller-config-file", default=None)
    parser.add_argument(
        "--mode", choices=("auto", "watch", "poll"), default="auto",
        help="auto: watch via the in-cluster HTTP client when the "
             "ServiceAccount mount exists (the operator image path), "
             "else kubectl polling (dev clusters)")
    parser.add_argument(
        "--no-leader-election", action="store_true",
        help="watch mode without a coordination.k8s.io lease (single-"
             "replica deployments / clusters without the RBAC rule)")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(levelname)s|%(asctime)s|%(pathname)s|%(lineno)d| %(message)s",
        datefmt="%Y-%m-%dT%H:%M:%S",
    )
    if args.controller_config_file:
        logger.info("controller config: %s", args.controller_config_file)
    mode = args.mode
    if mode == "auto":
        mode = ("watch" if os.environ.get("KUBERNETES_SERVICE_HOST")
                else "poll")
    if mode == "watch":
        from kubeflow_tpu.operator.http_client import HttpApiClient
        from kubeflow_tpu.operator.leader import LeaderElector

        client = HttpApiClient.in_cluster()
        elector = None
        if not args.no_leader_election:
            lease_ns = os.environ.get("KFT_NAMESPACE", "default")
            # The lease NAME carries the watch scope: two operators
            # watching different namespaces run disjoint workloads and
            # must not contend one lock (the loser's namespace would
            # silently never reconcile).
            lease_name = ("tpujob-operator" if args.namespace is None
                          else f"tpujob-operator-{args.namespace}")
            elector = LeaderElector(client, namespace=lease_ns,
                                    name=lease_name)
            logger.info("lease %s/%s as %s", lease_ns, lease_name,
                        elector.identity)
        logger.info("watch mode: in-cluster HTTP client, relist %.0fs",
                    args.relist_seconds)
        WatchController(client, namespace=args.namespace,
                        relist_seconds=args.relist_seconds,
                        elector=elector).run()
    else:
        logger.info("poll mode: kubectl client, resync %.1fs",
                    args.resync_seconds)
        run_controller(KubectlClient(), resync_seconds=args.resync_seconds,
                       namespace=args.namespace)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
