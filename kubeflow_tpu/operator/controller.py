# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Controller loops: drive the Reconciler against an apiserver.

Primary mode is WATCH-driven (the reference's informer pattern — its
operator was an external Go image built on client-go informers,
``kubeflow/core/prototypes/all.jsonnet:10``): list+watch TPUJobs and
their pods with resourceVersion resume, enqueue the owning job on
every event, reconcile from a worker loop, and fall back to a
periodic full relist as the level-triggered safety net. Reaction to a
pod failure is event-latency (sub-second), not a resync period.

Clients: the in-memory fake (tests), the stdlib-HTTP apiserver client
(production, :mod:`kubeflow_tpu.operator.http_client` — no kubectl in
the operator image), and a kubectl-backed shim kept for dev
clusters/`kft apply` parity. The old polling loop remains as
``run_controller`` for the kubectl shim, which has no watch surface.

Work scheduling (r7): events land in a rate-limited
:class:`~kubeflow_tpu.operator.workqueue.WorkQueue` — per-key
deduplication (one job is never reconciled concurrently), N worker
threads, per-key exponential backoff with jitter on failure (the r6
loop retried at a flat 0.5 s from a single worker), a global
token-bucket limiter, and poison-job quarantine: after
``quarantine_after`` consecutive failures the key parks at the
backoff cap and the job carries a ``ReconcileStalled`` condition +
Event until a reconcile succeeds again.

Read path (r12): the per-pass GET/LIST traffic moved into an
informer-style shared cache (:mod:`kubeflow_tpu.operator.informer`) —
one list+watch-fed, indexed local store per hot-path kind. Workers
and the reconciler read from the store; writes go through the api
client and their results are absorbed immediately, so steady-state
apiserver QPS stays flat as the fleet grows (the r7 design re-read
every job ~5× per relist period). On top of the cache sits priority +
gang preemption: a high-priority gang burning through its scheduling
deadline evicts the lowest-priority running gang, globally
rate-limited (reconciler.PreemptionPolicy).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import subprocess
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubeflow_tpu.manifests.tpujob import KIND, PLURAL, GROUP
from kubeflow_tpu.obs import metrics as obs_metrics
from kubeflow_tpu.operator.fake import Conflict, NotFound
from kubeflow_tpu.operator.informer import CachedApiClient, Informer
from kubeflow_tpu.operator.reconciler import (
    JOB_LABEL,
    PreemptionPolicy,
    Reconciler,
)
from kubeflow_tpu.operator.workqueue import (
    ExponentialBackoff,
    TokenBucket,
    WorkQueue,
)

logger = logging.getLogger(__name__)

#: ConfigMap through which the controller publishes its workqueue /
#: reconcile metrics (the dashboard's /tpujobs/api/operator endpoint
#: and the load benchmark read the same numbers).
METRICS_CONFIGMAP = "tpujob-operator-metrics"
METRICS_KEY = "metrics.json"

# Prometheus families for the control loop — the ConfigMap snapshot
# above stays (the dashboard reads it through the apiserver), but the
# same numbers are now scrapeable live at --metrics-port via a stdlib
# exposition thread (no tornado in the operator image). Workqueue
# gauges/counters are render-time callbacks into WorkQueue.counts();
# reconcile latency is a real histogram observed per pass.
_O_RECONCILES = obs_metrics.Counter(
    "kft_operator_reconciles_total", "Successful reconcile passes")
_O_FAILURES = obs_metrics.Counter(
    "kft_operator_reconcile_failures_total",
    "Reconcile passes that raised (scheduled for backoff retry)")
_O_LATENCY = obs_metrics.Histogram(
    "kft_operator_reconcile_seconds",
    "Wall time of one reconcile pass (get + reconcile)")
_O_WATCH_ERRORS = obs_metrics.Counter(
    "kft_operator_watch_errors_total",
    "Watch transport failures (relist + backoff)")
_O_WATCH_GONE = obs_metrics.Counter(
    "kft_operator_watch_gone_total",
    "410 Gone watch compactions (immediate relist, not an error)")
_O_WQ_DEPTH = obs_metrics.Gauge(
    "kft_workqueue_depth", "Keys ready for a worker")
_O_WQ_DELAYED = obs_metrics.Gauge(
    "kft_workqueue_delayed", "Keys waiting out a backoff timer")
_O_WQ_PROCESSING = obs_metrics.Gauge(
    "kft_workqueue_processing", "Keys currently held by workers")
_O_WQ_QUARANTINED = obs_metrics.Gauge(
    "kft_workqueue_quarantined",
    "Poison keys parked at the backoff cap")
_O_WQ_ADDS = obs_metrics.Counter(
    "kft_workqueue_adds_total", "Enqueue attempts (deduplicated)")
_O_WQ_GETS = obs_metrics.Counter(
    "kft_workqueue_gets_total", "Keys handed to workers")
_O_WQ_RETRIES = obs_metrics.Counter(
    "kft_workqueue_retries_total", "Failure-scheduled retries")
_O_INFORMER_OBJECTS = obs_metrics.Gauge(
    "kft_informer_objects_total",
    "Objects resident across the informer caches")
_O_PREEMPTIONS = obs_metrics.Counter(
    "kft_operator_preemptions_total",
    "Gang preemptions granted (victim gangs torn down)")
_O_PREEMPTIONS_LIMITED = obs_metrics.Counter(
    "kft_operator_preemptions_rate_limited_total",
    "Preemption decisions refused by the global rate limiter")
_O_GANG_RESIZES = obs_metrics.Counter(
    "kft_operator_gang_resizes_total",
    "Elastic gang resizes by direction (shrink = member loss / "
    "admission pressure / preemptor shrink; grow = restart back "
    "toward the desired size)",
    ("direction",))

#: Kinds the controller keeps informer caches for — everything the
#: reconcile hot path reads. Pods/Services/PDBs are gang-owned and
#: carry JOB_LABEL, so their watches stay bounded by gang count.
INFORMED_KINDS = (KIND, "Pod", "Service", "PodDisruptionBudget")


class KubectlClient:
    """Apiserver access via the kubectl CLI (same interface as
    FakeApiServer's store surface)."""

    def _run(self, *args: str, input_data: Optional[str] = None) -> str:
        proc = subprocess.run(
            ["kubectl", *args], capture_output=True, text=True,
            input=input_data)
        if proc.returncode != 0:
            if "NotFound" in proc.stderr or "not found" in proc.stderr:
                raise NotFound(proc.stderr.strip())
            if "AlreadyExists" in proc.stderr or "already exists" in proc.stderr:
                # Same taxonomy as the fake store, so the reconciler's
                # idempotent-create handling works on real clusters
                # too (the dashboard maps this string the same way).
                raise Conflict(proc.stderr.strip())
            raise RuntimeError(f"kubectl {' '.join(args)}: {proc.stderr}")
        return proc.stdout

    @staticmethod
    def _resource(kind: str) -> str:
        return f"{PLURAL}.{GROUP}" if kind == KIND else kind.lower() + "s"

    def create(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        out = self._run("create", "-f", "-", "-o", "json",
                        input_data=json.dumps(obj))
        return json.loads(out)

    def get(self, kind: str, namespace: str, name: str) -> Dict[str, Any]:
        out = self._run("get", self._resource(kind), name, "-n", namespace,
                        "-o", "json")
        return json.loads(out)

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None,
             field_selector: Optional[Dict[str, str]] = None
             ) -> List[Dict[str, Any]]:
        args = ["get", self._resource(kind), "-o", "json"]
        args += ["-n", namespace] if namespace else ["--all-namespaces"]
        if label_selector:
            args += ["-l", ",".join(f"{k}={v}"
                                    for k, v in label_selector.items())]
        if field_selector:
            args += ["--field-selector",
                     ",".join(f"{k}={v}"
                              for k, v in field_selector.items())]
        return json.loads(self._run(*args)).get("items", [])

    def patch(self, kind: str, namespace: str, name: str,
              mutate: Callable[[Dict[str, Any]], None]) -> Dict[str, Any]:
        obj = self.get(kind, namespace, name)
        mutate(obj)
        sub = ["--subresource=status"] if kind == KIND else []
        out = self._run("replace", *sub, "-f", "-", "-o", "json",
                        input_data=json.dumps(obj))
        return json.loads(out)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._run("delete", self._resource(kind), name, "-n", namespace,
                  "--wait=false")

    def pod_logs(self, namespace: str, name: str, *,
                 tail: int = 100) -> str:
        return self._run("logs", name, "-n", namespace,
                         f"--tail={tail}")


class WatchController:
    """Informer-style controller: watch TPUJobs + pods, enqueue the
    owning job per event into a rate-limited workqueue, reconcile from
    ``workers`` threads (per-key dedup keeps any one job serialized —
    the reconciler is pass-atomic but not designed for concurrent
    passes over one job), periodic relist as the safety net."""

    def __init__(self, api, *, namespace: Optional[str] = None,
                 relist_seconds: float = 30.0,
                 reconciler: Optional[Reconciler] = None,
                 elector=None,
                 workers: int = 1,
                 queue: Optional[WorkQueue] = None,
                 backoff: Optional[ExponentialBackoff] = None,
                 limiter: Optional[TokenBucket] = None,
                 quarantine_after: int = 6,
                 metrics_namespace: Optional[str] = None,
                 informer_reads: bool = True,
                 resync_seconds: float = 300.0,
                 preemption: Optional[PreemptionPolicy] = None):
        self.api = api
        self.namespace = namespace
        self.relist_seconds = relist_seconds
        self.reconciler = reconciler or Reconciler(
            api, preemption=preemption)
        if reconciler is not None and preemption is not None:
            self.reconciler.preemption = preemption
        # Optional LeaderElector (operator/leader.py): watchers run
        # regardless (warm cache), reconciles only while leading.
        self.elector = elector
        self.workers = max(1, int(workers))
        self.stop = threading.Event()
        self.queue = queue or WorkQueue(
            backoff=backoff or ExponentialBackoff(),
            limiter=limiter or TokenBucket(qps=50.0, burst=100),
            quarantine_after=quarantine_after)
        # Metrics ConfigMap home; None = alongside the watch scope
        # (its namespace, or "default" for cluster-wide controllers).
        self.metrics_namespace = (metrics_namespace or namespace
                                  or "default")
        self._watchers: List[threading.Thread] = []
        # Bounded WaitForCacheSync window; armed by run().
        self._sync_deadline: Optional[float] = None
        self._sync_timeout_logged = False
        # Keys whose ReconcileStalled condition has been written (so
        # quarantined retries don't re-patch it every cap interval).
        self._stalled: set = set()
        self._counters_lock = threading.Lock()
        self._reconciles = 0
        self._reconcile_failures = 0
        # The informer layer (r12 tentpole): one list+watch-fed local
        # store per hot-path kind. The informers are ALWAYS the event
        # source; `informer_reads` additionally routes the reconcile
        # read path through the shared cache (False = the r7
        # direct-read behavior, kept for the benchmark's QPS-contrast
        # and as an escape hatch).
        self.informer_reads = informer_reads
        self.informers: Dict[str, Informer] = {}
        for kind in INFORMED_KINDS:
            selector = {JOB_LABEL: None} if kind != KIND else None
            self.informers[kind] = Informer(
                api, kind, namespace=namespace,
                label_selector=selector,
                index_label=JOB_LABEL if kind == "Pod" else None,
                handler=self._on_informer_event,
                watch_timeout=relist_seconds,
                resync_seconds=resync_seconds)
        if informer_reads:
            stores = {k: inf.store for k, inf in self.informers.items()}
            self.reader = CachedApiClient(api, stores)
            self.reconciler.attach_cache(self.reader)
        else:
            self.reader = api
        # Live /metrics bindings (render-time callbacks — tests build
        # many controllers; the newest instance wins the binding).
        queue = self.queue
        for gauge, key in ((_O_WQ_DEPTH, "depth"),
                           (_O_WQ_DELAYED, "delayed"),
                           (_O_WQ_PROCESSING, "processing"),
                           (_O_WQ_QUARANTINED, "quarantined"),
                           (_O_WQ_ADDS, "adds"),
                           (_O_WQ_GETS, "gets"),
                           (_O_WQ_RETRIES, "retries")):
            gauge.set_function(lambda q=queue, k=key: q.counts()[k])
        _O_WATCH_ERRORS.set_function(
            lambda c=self: sum(c.watch_errors.values()))
        _O_WATCH_GONE.set_function(
            lambda c=self: sum(c.watch_gone.values()))
        _O_RECONCILES.set_function(lambda c=self: c._reconciles)
        _O_FAILURES.set_function(
            lambda c=self: c._reconcile_failures)
        _O_INFORMER_OBJECTS.set_function(
            lambda c=self: sum(len(i.store)
                               for i in c.informers.values()))
        _O_PREEMPTIONS.set_function(
            lambda c=self: c.reconciler.preemption.granted)
        _O_PREEMPTIONS_LIMITED.set_function(
            lambda c=self: c.reconciler.preemption.rate_limited)
        for direction in ("shrink", "grow"):
            _O_GANG_RESIZES.labels(direction=direction).set_function(
                lambda c=self, d=direction:
                c.reconciler.resize_counts().get(d, 0))

    # Watch-loop health, aggregated from the informers. A 410 Gone is
    # NOT an error — the server compacted our resume point and the
    # contract is an immediate relist (see Informer.run).

    @property
    def watch_gone(self) -> Dict[str, int]:
        return {k: inf.gone for k, inf in self.informers.items()
                if inf.gone}

    @property
    def watch_errors(self) -> Dict[str, int]:
        return {k: inf.errors for k, inf in self.informers.items()
                if inf.errors}

    # -- queue ------------------------------------------------------------

    def enqueue(self, namespace: str, name: str) -> None:
        """Event path: supersedes any pending backoff timer (the
        event may carry exactly the change that fixes a failing
        job)."""
        self.queue.add((namespace, name))

    def enqueue_relisted(self, namespace: str, name: str) -> None:
        """Relist path: no new information — backing-off keys keep
        their timers (quarantined poison jobs stay parked at the cap
        instead of being re-admitted every relist period)."""
        self.queue.add_unless_delayed((namespace, name))

    # -- watchers ---------------------------------------------------------

    def _job_key_of(self, kind: str, obj: Dict[str, Any]
                    ) -> Optional[Tuple[str, str]]:
        meta = obj.get("metadata", {})
        ns = meta.get("namespace", "default")
        if kind == KIND:
            return (ns, meta["name"])
        label = meta.get("labels", {}).get(JOB_LABEL)
        return (ns, label) if label else None

    def _on_informer_event(self, kind: str, event_type: str,
                           obj: Dict[str, Any], relisted: bool) -> None:
        """Informer dispatch: the store already reflects the event
        (Informer.run applies before dispatching), so a worker woken
        by this enqueue reads a cache at least as new as the event.
        Relist deliveries carry no new information — backing-off keys
        keep their timers (quarantine survives resyncs)."""
        key = self._job_key_of(kind, obj)
        if key is None:
            return
        if relisted:
            self.enqueue_relisted(*key)
        else:
            self.enqueue(*key)

    # -- workers ----------------------------------------------------------

    def _reconcile_allowed(self) -> bool:
        return self.elector is None or self.elector.is_leader()

    def _caches_ready(self) -> bool:
        """All informer stores synced, OR the bounded sync window has
        expired. The normal case resolves in one list round trip; the
        timeout covers a kind whose LIST persistently fails (RBAC
        drift, disabled API group) — reconciling against a partially
        cold cache costs Conflict-tolerated wasted passes, while
        waiting forever would silently halt the whole fleet with no
        condition surfaced anywhere (a worse outage than the pre-r12
        direct-read behavior)."""
        if all(inf.synced.is_set() for inf in self.informers.values()):
            return True
        if self._sync_deadline is None:
            return False  # run() not started yet (tests drive workers)
        if time.monotonic() < self._sync_deadline:
            return False
        if not self._sync_timeout_logged:
            self._sync_timeout_logged = True
            cold = [k for k, inf in self.informers.items()
                    if not inf.synced.is_set()]
            logger.error(
                "informer caches %s never synced within the startup "
                "window; reconciling with partial caches (check LIST "
                "RBAC for those kinds)", cold)
        return True

    def _worker_loop(self) -> None:
        while not self.stop.is_set():
            if not self._reconcile_allowed():
                # Follower: keep the queue (events accumulate for the
                # takeover), reconcile nothing.
                self.stop.wait(0.05)
                continue
            if (self.informer_reads and not self._caches_ready()):
                # WaitForCacheSync — ALL stores, not just TPUJob: a
                # cold job store would mistake a live job for deleted
                # and drop its key; a cold Pod store would read a
                # Running gang as all-MISSING and fire a spurious
                # CREATE_MISSING + Running→Pending flap. Idle until
                # every cache holds an authoritative snapshot — but
                # BOUNDED (see _caches_ready): one kind's persistent
                # list failure must degrade, never halt the fleet.
                self.stop.wait(0.02)
                continue
            key = self.queue.get(timeout=0.2, stop=self.stop)
            if key is None:
                continue
            ns, name = key
            try:
                self._reconcile_one(key, ns, name)
            finally:
                self.queue.done(key)

    def _reconcile_one(self, key: Tuple[str, str], ns: str,
                       name: str) -> None:
        t0 = time.monotonic()
        try:
            self._reconcile_one_inner(key, ns, name)
        finally:
            _O_LATENCY.observe(time.monotonic() - t0)

    def _reconcile_one_inner(self, key: Tuple[str, str], ns: str,
                             name: str) -> None:
        try:
            job = self.reader.get(KIND, ns, name)
        except NotFound:
            # Deleted; GC is ownerReference-driven. Nothing left to
            # retry against either.
            self.queue.forget(key)
            self._stalled.discard(key)
            return
        except Exception:  # noqa: BLE001 — apiserver-side failure
            logger.exception("get failed for %s/%s", ns, name)
            self._note_failure(key, ns, name)
            return
        self.reconciler.requeue_after = None
        try:
            self.reconciler.reconcile(job)
        except Exception:  # noqa: BLE001
            logger.exception("reconcile failed for %s/%s", ns, name)
            self._note_failure(key, ns, name)
            return
        with self._counters_lock:
            self._reconciles += 1
        self.queue.forget(key)
        if key in self._stalled:
            # The job recovered: lift the ReconcileStalled condition.
            self._stalled.discard(key)
            try:
                self.reconciler.clear_stalled(ns, name)
            except Exception:  # noqa: BLE001 — best-effort
                logger.exception("clear_stalled failed for %s/%s",
                                 ns, name)
        # The reconciler can ask to be re-observed (e.g. a pending
        # schedulingDeadlineSeconds): schedule a timer wake-up so the
        # deadline doesn't wait for the next relist period.
        if self.reconciler.requeue_after is not None:
            self.queue.add_after(key,
                                 max(0.05, self.reconciler.requeue_after))

    def _note_failure(self, key: Tuple[str, str], ns: str,
                      name: str) -> None:
        with self._counters_lock:
            self._reconcile_failures += 1
        delay = self.queue.retry(key)
        failures = self.queue.failures(key)
        if self.queue.is_quarantined(key) and key not in self._stalled:
            # Poison job: park at the cap (queue.retry already did)
            # and surface it — a ReconcileStalled condition + Event so
            # `kubectl describe` / the dashboard show WHY the job
            # stopped converging. Best-effort: the job's API is the
            # thing that's failing; re-attempted at every capped retry
            # until the write lands.
            try:
                self.reconciler.mark_stalled(ns, name, failures)
                self._stalled.add(key)
            except Exception:  # noqa: BLE001
                logger.warning("mark_stalled failed for %s/%s "
                               "(will retry at next capped attempt)",
                               ns, name)
        logger.info("requeue %s/%s in %.2fs (failure #%d)",
                    ns, name, delay, failures)

    # -- metrics ----------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._counters_lock:
            reconciles = self._reconciles
            failures = self._reconcile_failures
        return {
            "workers": self.workers,
            "informerReads": self.informer_reads,
            "reconciles": reconciles,
            "reconcileFailures": failures,
            "watchGone": dict(self.watch_gone),
            "watchErrors": dict(self.watch_errors),
            "informers": {kind: inf.stats()
                          for kind, inf in self.informers.items()},
            "preemption": self.reconciler.preemption.stats(),
            "gangResizes": self.reconciler.resize_counts(),
            "requeueLatencyMs": self.queue.latency_percentiles(),
            "queue": self.queue.stats(),
        }

    def publish_metrics(self) -> None:
        """Write the stats snapshot to the operator metrics ConfigMap
        (best-effort; identical snapshots are no-op writes, so a
        quiescent controller publishes nothing). The dashboard's
        /tpujobs/api/operator endpoint and the load benchmark read
        this same object."""
        payload = json.dumps(self.stats(), sort_keys=True)
        ns = self.metrics_namespace
        try:
            try:
                self.api.patch(
                    "ConfigMap", ns, METRICS_CONFIGMAP,
                    lambda o: o.setdefault("data", {}).update(
                        {METRICS_KEY: payload}))
            except NotFound:
                self.api.create({
                    "apiVersion": "v1",
                    "kind": "ConfigMap",
                    "metadata": {"name": METRICS_CONFIGMAP,
                                 "namespace": ns},
                    "data": {METRICS_KEY: payload},
                })
        except Exception:  # noqa: BLE001 — metrics must never wedge
            logger.debug("metrics publish failed", exc_info=True)

    # -- main loop --------------------------------------------------------

    def run(self, *, max_seconds: Optional[float] = None) -> None:
        self._sync_deadline = (time.monotonic()
                               + max(5.0, 2.0 * self.relist_seconds))
        for kind, informer in self.informers.items():
            t = threading.Thread(target=informer.run, args=(self.stop,),
                                 name=f"informer-{kind}", daemon=True)
            t.start()
            self._watchers.append(t)
        if self.elector is not None:
            t = threading.Thread(target=self.elector.loop,
                                 name="leader-elector", daemon=True)
            t.start()
            self._watchers.append(t)
        for i in range(self.workers):
            t = threading.Thread(target=self._worker_loop,
                                 name=f"reconcile-worker-{i}",
                                 daemon=True)
            t.start()
            self._watchers.append(t)
        deadline = (time.monotonic() + max_seconds
                    if max_seconds is not None else None)
        last_relist = time.monotonic()
        was_leader = False
        try:
            while not self.stop.is_set():
                if deadline is not None and time.monotonic() >= deadline:
                    break
                if self.elector is not None:
                    if self.elector.broken.is_set():
                        # The lease path is persistently failing (e.g.
                        # 403 from stale RBAC): crash loudly so the
                        # pod restarts visibly instead of idling as a
                        # forever-follower — a silent outage.
                        raise RuntimeError(
                            "leader elector broken: lease API "
                            "persistently unavailable")
                    if not self.elector.is_leader():
                        # Follower: the workers idle on the same
                        # check; the main loop just keeps the clock.
                        was_leader = False
                        self.stop.wait(0.05)
                        continue
                    if not was_leader:
                        # Fresh leadership: force an immediate relist
                        # AND an informer resync from the server —
                        # anything the previous leader half-finished
                        # must be re-observed now (and not trusted to
                        # a cache that may predate its last writes).
                        # The resync lands within one watch timeout
                        # (= relist_seconds): a quiet in-flight watch
                        # can't be interrupted mid-stream, only told
                        # to relist at its next turn.
                        was_leader = True
                        last_relist = float("-inf")
                        for informer in self.informers.values():
                            informer.request_resync()
                now = time.monotonic()
                if now - last_relist >= self.relist_seconds:
                    # Level-triggered safety net: a dropped event can
                    # delay a job at most one relist period. With
                    # informer reads the sweep comes from the LOCAL
                    # store — zero apiserver requests, so steady-state
                    # QPS stays flat as the fleet grows (the informer's
                    # own resync period bounds cache staleness).
                    last_relist = now
                    try:
                        if self.informer_reads:
                            for ns, name in (
                                    self.informers[KIND].store.keys()):
                                self.enqueue_relisted(ns, name)
                        else:
                            for job in self.api.list(KIND,
                                                     self.namespace):
                                meta = job["metadata"]
                                self.enqueue_relisted(
                                    meta.get("namespace", "default"),
                                    meta["name"])
                    except Exception:  # noqa: BLE001
                        logger.exception("relist failed")
                    self.publish_metrics()
                self.stop.wait(0.05)
        finally:
            self.stop.set()
            if self.elector is not None:
                self.elector.stop.set()
            for t in self._watchers:
                t.join(timeout=5.0)


def run_watch_controller(api, *, namespace: Optional[str] = None,
                         relist_seconds: float = 30.0,
                         workers: int = 1,
                         max_seconds: Optional[float] = None) -> None:
    WatchController(
        api, namespace=namespace, relist_seconds=relist_seconds,
        workers=workers,
    ).run(max_seconds=max_seconds)


def run_controller(api, *, resync_seconds: float = 5.0,
                   namespace: Optional[str] = None,
                   max_iterations: Optional[int] = None,
                   stop: Optional[threading.Event] = None) -> None:
    reconciler = Reconciler(api)
    stop = stop or threading.Event()
    iteration = 0
    while max_iterations is None or iteration < max_iterations:
        iteration += 1
        try:
            jobs = api.list(KIND, namespace)
        except Exception:  # noqa: BLE001
            logger.exception("listing TPUJobs failed")
            jobs = []
        for job in jobs:
            try:
                reconciler.reconcile(job)
            except Exception:  # noqa: BLE001
                logger.exception(
                    "reconcile failed for %s/%s",
                    job["metadata"].get("namespace"),
                    job["metadata"]["name"])
        if max_iterations is None or iteration < max_iterations:
            # Interruptible resync period (NOT a retry loop: failures
            # above are level-triggered away on the next full pass).
            if stop.wait(resync_seconds):
                return


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tpujob-operator")
    parser.add_argument("--namespace", default=None)
    parser.add_argument("--resync-seconds", type=float, default=5.0,
                        help="poll mode resync period")
    parser.add_argument("--relist-seconds", type=float, default=30.0,
                        help="watch mode relist safety-net period")
    parser.add_argument("--workers", type=int, default=4,
                        help="watch mode reconcile worker threads "
                             "(per-job serialization is preserved by "
                             "the workqueue's key dedup)")
    parser.add_argument("--controller-config-file", default=None)
    parser.add_argument(
        "--mode", choices=("auto", "watch", "poll"), default="auto",
        help="auto: watch via the in-cluster HTTP client when the "
             "ServiceAccount mount exists (the operator image path), "
             "else kubectl polling (dev clusters)")
    parser.add_argument(
        "--no-leader-election", action="store_true",
        help="watch mode without a coordination.k8s.io lease (single-"
             "replica deployments / clusters without the RBAC rule)")
    parser.add_argument(
        "--no-informer-reads", action="store_true",
        help="bypass the informer cache on the reconcile read path "
             "(every pass re-reads the apiserver — the pre-r12 "
             "behavior; steady-state QPS grows with fleet size)")
    parser.add_argument(
        "--preemption-interval", type=float, default=30.0,
        help="global minimum seconds between gang preemptions (the "
             "priority-storm rate limit; see docs/operator.md)")
    parser.add_argument(
        "--preemption-fraction", type=float, default=0.5,
        help="fraction of a Pending priority job's scheduling "
             "deadline after which it may preempt a lower-priority "
             "running gang")
    parser.add_argument(
        "--metrics-port", type=int, default=9400,
        help="Prometheus /metrics (+ /tracez, /healthz) exposition "
             "port, served from a stdlib thread; 0 disables")
    parser.add_argument(
        "--trace-tail-keep", type=float, default=None,
        help="enable tail-based span sampling: keep this fraction of "
             "happy-path reconcile spans (error outcomes and the "
             "slowest decile always retained)")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(levelname)s|%(asctime)s|%(pathname)s|%(lineno)d| %(message)s",
        datefmt="%Y-%m-%dT%H:%M:%S",
    )
    if args.controller_config_file:
        logger.info("controller config: %s", args.controller_config_file)
    mode = args.mode
    if mode == "auto":
        mode = ("watch" if os.environ.get("KUBERNETES_SERVICE_HOST")
                else "poll")
    if args.trace_tail_keep is not None:
        from kubeflow_tpu.obs.tracing import TRACER

        TRACER.set_tail_sampling(args.trace_tail_keep)
    if args.metrics_port:
        from kubeflow_tpu.obs.exposition import start_exposition_server

        server = start_exposition_server(args.metrics_port)
        logger.info("metrics exposition on :%d (/metrics, /tracez)",
                    server.server_address[1])
    if mode == "watch":
        from kubeflow_tpu.operator.http_client import HttpApiClient
        from kubeflow_tpu.operator.leader import LeaderElector

        client = HttpApiClient.in_cluster()
        elector = None
        if not args.no_leader_election:
            lease_ns = os.environ.get("KFT_NAMESPACE", "default")
            # The lease NAME carries the watch scope: two operators
            # watching different namespaces run disjoint workloads and
            # must not contend one lock (the loser's namespace would
            # silently never reconcile).
            lease_name = ("tpujob-operator" if args.namespace is None
                          else f"tpujob-operator-{args.namespace}")
            elector = LeaderElector(client, namespace=lease_ns,
                                    name=lease_name)
            logger.info("lease %s/%s as %s", lease_ns, lease_name,
                        elector.identity)
        logger.info("watch mode: in-cluster HTTP client, relist %.0fs",
                    args.relist_seconds)
        WatchController(
            client, namespace=args.namespace,
            relist_seconds=args.relist_seconds,
            workers=args.workers,
            elector=elector,
            informer_reads=not args.no_informer_reads,
            preemption=PreemptionPolicy(
                deadline_fraction=args.preemption_fraction,
                min_interval_seconds=args.preemption_interval),
        ).run()
    else:
        logger.info("poll mode: kubectl client, resync %.1fs",
                    args.resync_seconds)
        run_controller(KubectlClient(), resync_seconds=args.resync_seconds,
                       namespace=args.namespace)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
