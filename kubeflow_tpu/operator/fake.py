# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""A minimal in-memory apiserver for hermetic operator tests.

Implements just the object-store surface the reconciler needs
(create/get/list/patch/delete keyed by (kind, namespace, name)) plus
WATCH streams with resourceVersion resume (the surface the
event-driven controller consumes), and test helpers to drive pod
phase transitions. This is the fake layer SURVEY §4 calls out as
missing from the reference.

Adversity (r7): every front-door request passes through a
:class:`FaultInjector` — rule-matched 409 conflict storms, 429/500
bursts, added latency, and early-terminated watch streams — and is
recorded in a timestamped request log so tests can assert *apiserver
load*, not just final state (e.g. that a quarantined poison job's
request rate decays to the backoff cap). Test helpers that play the
kubelet (``set_pod_phase`` & co.) bypass both: chaos must not be
throttled by its own faults, nor counted as controller traffic.
"""

from __future__ import annotations

import contextlib
import copy
import dataclasses
import random
import re
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

Key = Tuple[str, str, str]  # (kind, namespace, name)


class Conflict(Exception):
    pass


class NotFound(Exception):
    pass


class TooManyRequests(Exception):
    """k8s 429: the apiserver (or its priority-and-fairness layer) is
    shedding load; the client must back off."""


class ServerError(Exception):
    """k8s 5xx: transient apiserver-side failure."""


class Gone(Exception):
    """The requested resourceVersion is no longer in the event window
    (k8s 410 Gone): the watcher must relist and re-watch."""


def _labels_match(obj: Dict[str, Any],
                  selector: Optional[Dict[str, Optional[str]]]) -> bool:
    """k8s label-selector subset: value None = key-existence match
    (``labelSelector=key``), else exact equality (``key=value``)."""
    if not selector:
        return True
    labels = obj.get("metadata", {}).get("labels", {})
    for lk, lv in selector.items():
        if lv is None:
            if lk not in labels:
                return False
        elif labels.get(lk) != lv:
            return False
    return True


def _fields_match(obj: Dict[str, Any],
                  selector: Optional[Dict[str, str]]) -> bool:
    """k8s field-selector subset: dotted-path equality against the
    object (``involvedObject.name=myjob``, ``metadata.namespace=ns``).
    Like the apiserver, comparison is on string representations and a
    missing path only matches the empty string."""
    if not selector:
        return True
    for path, want in selector.items():
        node: Any = obj
        for part in path.split("."):
            node = node.get(part) if isinstance(node, dict) else None
            if node is None:
                break
        if str(node if node is not None else "") != str(want):
            return False
    return True


@dataclasses.dataclass
class FaultRule:
    """One injectable fault: raise ``exc`` when a request matches.

    ``verbs``/``kind``/``name`` are None-means-any filters (``name``
    is a regex, searched). ``rate`` is the match probability;
    ``times`` bounds total firings (None = unbounded)."""

    exc: Callable[[], Exception]
    verbs: Optional[Tuple[str, ...]] = None
    kind: Optional[str] = None
    name: Optional[str] = None
    rate: float = 1.0
    times: Optional[int] = None
    fired: int = 0

    def matches(self, verb: str, kind: str, name: Optional[str],
                rng: random.Random) -> bool:
        if self.times is not None and self.fired >= self.times:
            return False
        if self.verbs is not None and verb not in self.verbs:
            return False
        if self.kind is not None and kind != self.kind:
            return False
        if self.name is not None and not re.search(self.name,
                                                   name or ""):
            return False
        return self.rate >= 1.0 or rng.random() < self.rate


class FaultInjector:
    """Chaos front door for :class:`FakeApiServer` (and hence the
    HTTP facade): 409/429/500 storms, latency, dropped watches."""

    def __init__(self, seed: int = 0):
        self.rules: List[FaultRule] = []
        self.rng = random.Random(seed)
        #: seconds added to every front-door request.
        self.latency: float = 0.0
        #: end each watch stream after this many yielded events (a
        #: dropped connection; the client must resume from its last
        #: resourceVersion). None = never.
        self.watch_max_events: Optional[int] = None
        self._lock = threading.Lock()

    def add_rule(self, exc: Callable[[], Exception], *,
                 verbs: Optional[Tuple[str, ...]] = None,
                 kind: Optional[str] = None,
                 name: Optional[str] = None,
                 rate: float = 1.0,
                 times: Optional[int] = None) -> FaultRule:
        rule = FaultRule(exc=exc, verbs=verbs, kind=kind, name=name,
                         rate=rate, times=times)
        with self._lock:
            self.rules.append(rule)
        return rule

    def clear(self) -> None:
        with self._lock:
            self.rules = []
            self.latency = 0.0
            self.watch_max_events = None

    def check(self, verb: str, kind: str,
              name: Optional[str]) -> None:
        if self.latency:
            time.sleep(self.latency)
        with self._lock:
            for rule in self.rules:
                if rule.matches(verb, kind, name, self.rng):
                    rule.fired += 1
                    raise rule.exc()


class FakeApiServer:
    # Events retained for watch resume; older revisions answer Gone,
    # like a real apiserver compacting its watch cache.
    EVENT_WINDOW = 10_000

    def __init__(self):
        self._objects: Dict[Key, Dict[str, Any]] = {}
        self._lock = threading.RLock()
        self._revision = 0
        # (revision, event_type, object snapshot) — the watch log.
        self._events: List[Tuple[int, str, Dict[str, Any]]] = []
        self._cond = threading.Condition(self._lock)
        # (namespace, pod) → container log text (set_pod_log helper).
        self._logs: Dict[Tuple[str, str], str] = {}
        # Chaos surface: fault rules + the timestamped request log
        # (what the CONTROLLER asked of the apiserver; kubelet-helper
        # writes bypass both — see _admit/as_kubelet).
        self.faults = FaultInjector()
        self._request_log: List[Dict[str, Any]] = []
        self._internal = threading.local()

    # -- front door (faults + request accounting) -------------------------

    @contextlib.contextmanager
    def as_kubelet(self):
        """Suspend fault injection + request logging for helper writes
        that simulate the kubelet/chaos, not the controller."""
        depth = getattr(self._internal, "depth", 0)
        self._internal.depth = depth + 1
        try:
            yield self
        finally:
            self._internal.depth = depth

    def _admit(self, verb: str, kind: str,
               namespace: Optional[str] = None,
               name: Optional[str] = None) -> None:
        if getattr(self._internal, "depth", 0):
            return
        # list.append is atomic under the GIL; readers snapshot.
        self._request_log.append({
            "ts": time.monotonic(), "verb": verb, "kind": kind,
            "namespace": namespace, "name": name,
        })
        self.faults.check(verb, kind, name)

    def request_log(self) -> List[Dict[str, Any]]:
        return list(self._request_log)

    def mark(self) -> int:
        """Position marker into the request log; pass to
        :meth:`request_counts` to count only the traffic between two
        marks. This is how tests assert informer QPS-flatness
        ("reconciles in this window issued N apiserver requests")
        without scraping timestamps."""
        return len(self._request_log)

    def request_counts(self, since_mark: int = 0,
                       until_mark: Optional[int] = None, *,
                       kind: Optional[str] = None,
                       name: Optional[str] = None
                       ) -> Dict[str, int]:
        """Per-verb request counts between two :meth:`mark` positions
        (``name`` is a substring match like :meth:`request_count`).
        The special key ``"total"`` sums every verb — the single
        number most flatness assertions want."""
        counts: Dict[str, int] = {"total": 0}
        log = self._request_log
        until = len(log) if until_mark is None else until_mark
        for entry in list(log[since_mark:until]):
            if kind is not None and entry["kind"] != kind:
                continue
            if name is not None and name not in (entry["name"] or ""):
                continue
            counts[entry["verb"]] = counts.get(entry["verb"], 0) + 1
            counts["total"] += 1
        return counts

    def request_count(self, *, verb: Optional[str] = None,
                      kind: Optional[str] = None,
                      name: Optional[str] = None,
                      since: Optional[float] = None) -> int:
        """Filtered request count; ``name`` is a substring match (a
        job's requests include its pods/events, which embed the job
        name)."""
        n = 0
        for entry in self.request_log():
            if verb is not None and entry["verb"] != verb:
                continue
            if kind is not None and entry["kind"] != kind:
                continue
            if name is not None and name not in (entry["name"] or ""):
                continue
            if since is not None and entry["ts"] < since:
                continue
            n += 1
        return n

    def _record(self, event_type: str, obj: Dict[str, Any]) -> None:
        self._events.append((self._revision, event_type,
                             copy.deepcopy(obj)))
        if len(self._events) > self.EVENT_WINDOW:
            del self._events[:len(self._events) - self.EVENT_WINDOW]
        self._cond.notify_all()

    @staticmethod
    def _key(obj: Dict[str, Any]) -> Key:
        meta = obj.get("metadata", {})
        return (obj["kind"], meta.get("namespace", "default"), meta["name"])

    def create(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        meta = obj.get("metadata", {})
        self._admit("create", obj.get("kind", "?"),
                    meta.get("namespace", "default"), meta.get("name"))
        with self._lock:
            key = self._key(obj)
            if key in self._objects:
                raise Conflict(f"{key} already exists")
            stored = copy.deepcopy(obj)
            self._revision += 1
            stored.setdefault("metadata", {})["resourceVersion"] = str(
                self._revision)
            self._objects[key] = stored
            self._record("ADDED", stored)
            return copy.deepcopy(stored)

    def get(self, kind: str, namespace: str, name: str) -> Dict[str, Any]:
        self._admit("get", kind, namespace, name)
        with self._lock:
            try:
                return copy.deepcopy(self._objects[(kind, namespace, name)])
            except KeyError:
                raise NotFound(f"{kind} {namespace}/{name}") from None

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None,
             field_selector: Optional[Dict[str, str]] = None
             ) -> List[Dict[str, Any]]:
        self._admit("list", kind, namespace)
        return self._list(kind, namespace, label_selector,
                          field_selector)

    def _list(self, kind: str, namespace: Optional[str] = None,
              label_selector: Optional[Dict[str, str]] = None,
              field_selector: Optional[Dict[str, str]] = None
              ) -> List[Dict[str, Any]]:
        with self._lock:
            out = []
            for (k, ns, _), obj in sorted(self._objects.items()):
                if k != kind:
                    continue
                if namespace is not None and ns != namespace:
                    continue
                if not _labels_match(obj, label_selector):
                    continue
                if not _fields_match(obj, field_selector):
                    continue
                out.append(copy.deepcopy(obj))
            return out

    def patch(self, kind: str, namespace: str, name: str,
              mutate: Callable[[Dict[str, Any]], None]) -> Dict[str, Any]:
        """Apply a mutation function under the store lock.

        No-op mutations neither bump resourceVersion nor emit a
        MODIFIED event — the real apiserver's no-change-PUT
        suppression. Without it the controller's own steady-state
        status write would re-enqueue the job it just reconciled,
        a self-sustaining hot loop (r5 review)."""
        self._admit("patch", kind, namespace, name)
        with self._lock:
            key = (kind, namespace, name)
            if key not in self._objects:
                raise NotFound(f"{kind} {namespace}/{name}")
            obj = self._objects[key]
            before = copy.deepcopy(obj)
            mutate(obj)
            if obj == before:
                return copy.deepcopy(obj)
            self._revision += 1
            obj["metadata"]["resourceVersion"] = str(self._revision)
            self._record("MODIFIED", obj)
            return copy.deepcopy(obj)

    def replace(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        """PUT semantics with optimistic concurrency: the incoming
        object's resourceVersion must match the stored one (k8s 409
        otherwise) — the contract HttpApiClient.patch relies on to
        turn concurrent writers into Conflicts instead of lost
        updates."""
        meta = obj.get("metadata", {})
        self._admit("replace", obj.get("kind", "?"),
                    meta.get("namespace", "default"), meta.get("name"))
        with self._lock:
            key = self._key(obj)
            stored = self._objects.get(key)
            if stored is None:
                raise NotFound(f"{key}")
            sent = obj.get("metadata", {}).get("resourceVersion")
            held = stored.get("metadata", {}).get("resourceVersion")
            if sent is not None and sent != held:
                raise Conflict(
                    f"{key}: resourceVersion {sent} != {held}")
            if obj == stored:
                # No-change PUT: no version bump, no event (see patch).
                return copy.deepcopy(stored)
            new = copy.deepcopy(obj)
            self._revision += 1
            new.setdefault("metadata", {})["resourceVersion"] = str(
                self._revision)
            self._objects[key] = new
            self._record("MODIFIED", new)
            return copy.deepcopy(new)

    # -- scale subresource -------------------------------------------------

    @staticmethod
    def _scale_shape(obj: Dict[str, Any]) -> Dict[str, Any]:
        meta = obj.get("metadata", {})
        return {
            "kind": "Scale",
            "apiVersion": "autoscaling/v1",
            "metadata": {"name": meta.get("name"),
                         "namespace": meta.get("namespace", "default"),
                         "resourceVersion": meta.get("resourceVersion")},
            "spec": {"replicas": int(
                obj.get("spec", {}).get("replicas", 0) or 0)},
            "status": {"replicas": int(
                obj.get("status", {}).get("replicas",
                                          obj.get("spec", {})
                                          .get("replicas", 0)) or 0)},
        }

    def get_scale(self, kind: str, namespace: str,
                  name: str) -> Dict[str, Any]:
        """GET the scale subresource (autoscaling/v1 Scale shape) of a
        replica-bearing object — what `kubectl scale` reads and the
        serving autoscaler's DeploymentScaler consumes."""
        self._admit("get_scale", kind, namespace, name)
        with self._lock:
            try:
                obj = self._objects[(kind, namespace, name)]
            except KeyError:
                raise NotFound(f"{kind} {namespace}/{name}") from None
            return self._scale_shape(obj)

    def update_scale(self, kind: str, namespace: str, name: str,
                     replicas: int,
                     resource_version: Optional[str] = None
                     ) -> Dict[str, Any]:
        """PUT the scale subresource: sets spec.replicas WITHOUT
        touching the rest of the object — the narrow write the
        autoscaler's RBAC story depends on (no pod-template access).
        A carried ``resource_version`` that no longer matches raises
        Conflict (the apiserver's optimistic-concurrency contract:
        a read-modify-PUT loses races loudly, never last-write-wins).
        Emits MODIFIED like any spec change; a no-op count neither
        bumps resourceVersion nor wakes watchers (same suppression as
        patch)."""
        self._admit("update_scale", kind, namespace, name)
        replicas = int(replicas)
        if replicas < 0:
            raise Conflict(f"{kind} {namespace}/{name}: negative "
                           f"replicas {replicas}")
        with self._lock:
            key = (kind, namespace, name)
            if key not in self._objects:
                raise NotFound(f"{kind} {namespace}/{name}")
            obj = self._objects[key]
            current_rv = obj.get("metadata", {}).get("resourceVersion")
            if (resource_version is not None
                    and resource_version != current_rv):
                raise Conflict(
                    f"{kind} {namespace}/{name}: scale "
                    f"resourceVersion {resource_version} is stale "
                    f"(now {current_rv})")
            spec = obj.setdefault("spec", {})
            if spec.get("replicas") != replicas:
                spec["replicas"] = replicas
                self._revision += 1
                obj["metadata"]["resourceVersion"] = str(self._revision)
                self._record("MODIFIED", obj)
            return self._scale_shape(obj)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._admit("delete", kind, namespace, name)
        with self._lock:
            key = (kind, namespace, name)
            if key not in self._objects:
                raise NotFound(f"{kind} {namespace}/{name}")
            gone = self._objects.pop(key)
            self._revision += 1
            self._record("DELETED", gone)

    # -- watch ------------------------------------------------------------

    def current_revision(self) -> int:
        with self._lock:
            return self._revision

    def list_with_version(self, kind: str, namespace: Optional[str] = None,
                          label_selector: Optional[Dict[str, str]] = None,
                          field_selector: Optional[Dict[str, str]] = None
                          ) -> Tuple[List[Dict[str, Any]], int]:
        """(items, revision horizon) under one lock acquisition —
        watching from the returned revision replays exactly the
        events after this list (same contract as HttpApiClient)."""
        self._admit("list", kind, namespace)
        with self._lock:
            return self._list(kind, namespace, label_selector,
                              field_selector), self._revision

    def watch(self, kind: str, namespace: Optional[str] = None,
              resource_version: int = 0,
              stop: Optional[threading.Event] = None,
              timeout: Optional[float] = None,
              label_selector: Optional[Dict[str, Optional[str]]] = None,
              allow_bookmarks: bool = False,
              ) -> Iterator[Tuple[str, Dict[str, Any]]]:
        """Stream (event_type, object) for ``kind`` after
        ``resource_version``, blocking for new events until ``stop``
        is set (or ``timeout`` elapses with no event — which ends the
        stream like a server-side watch timeout). Raises Gone when the
        requested version predates the retained window, mirroring the
        apiserver's 410. ``label_selector`` matches like ``list``
        (None values = key existence). An injected
        ``faults.watch_max_events`` ends the stream early after that
        many yields — a dropped connection the client must resume
        from its last seen resourceVersion.

        ``allow_bookmarks`` (the ``allowWatchBookmarks=true`` query,
        which HttpApiClient always sends): before a server-side watch
        timeout ends the stream, emit one BOOKMARK event — an object
        of the watched kind whose ONLY payload is the current
        resourceVersion — so an idle watcher's resume point tracks
        the store head instead of aging into a 410. This is exactly
        the real apiserver's contract, and what finally exercises the
        controller's BOOKMARK special-case under test."""
        self._admit("watch", kind, namespace)
        last = resource_version
        yielded = 0
        head = None  # set = server-side watch timeout at this revision
        while stop is None or not stop.is_set():
            with self._cond:
                if (self._events
                        and last < self._events[0][0] - 1
                        and last < self._revision):
                    raise Gone(f"resourceVersion {last} compacted")
                pending = [e for e in self._events if e[0] > last]
                if not pending:
                    if not self._cond.wait(timeout=timeout or 0.5):
                        if timeout is not None:
                            head = self._revision
                            break  # server-side watch timeout
                    continue
            for rev, event_type, obj in pending:
                last = rev
                if obj.get("kind") != kind:
                    continue
                ns = obj.get("metadata", {}).get("namespace", "default")
                if namespace is not None and ns != namespace:
                    continue
                if not _labels_match(obj, label_selector):
                    continue
                yield event_type, copy.deepcopy(obj)
                yielded += 1
                drop_after = self.faults.watch_max_events
                if drop_after is not None and yielded >= drop_after:
                    return  # injected connection drop
        if head is not None and allow_bookmarks:
            # Outside the lock: the consumer's socket write must never
            # block every other store user mid-frame.
            yield ("BOOKMARK", {
                "kind": kind,
                "metadata": {"resourceVersion": str(head)},
            })

    def pod_logs(self, namespace: str, name: str, *,
                 tail: int = 100) -> str:
        """Last ``tail`` log lines of a pod's container (the kubelet's
        GET /pods/<name>/log surface; same method on the kubectl and
        HTTP clients so the dashboard proxies logs through whichever
        client it was given)."""
        self._admit("logs", "Pod", namespace, name)
        with self._lock:
            if ("Pod", namespace, name) not in self._objects:
                raise NotFound(f"Pod {namespace}/{name}")
            text = self._logs.get((namespace, name), "")
        lines = text.splitlines()
        return "\n".join(lines[-tail:]) + ("\n" if lines else "")

    # -- test helpers -----------------------------------------------------

    def set_pod_log(self, namespace: str, name: str, text: str) -> None:
        with self._lock:
            self._logs[(namespace, name)] = text

    def set_pod_phase(self, namespace: str, name: str, phase: str) -> None:
        with self.as_kubelet():
            self.patch("Pod", namespace, name,
                       lambda o: o.setdefault("status", {}).update(
                           {"phase": phase}))

    def set_pod_terminated(self, namespace: str, name: str,
                           exit_code: int) -> None:
        """Pod finished with ``exit_code``, the way a kubelet reports
        it: phase from the code (0 → Succeeded, else Failed) plus the
        containerStatuses.terminated record the drain detection reads
        (reconciler.pod_drained)."""
        with self.as_kubelet():
            self.patch(
                "Pod", namespace, name,
                lambda o: o.setdefault("status", {}).update({
                    "phase": "Succeeded" if exit_code == 0 else "Failed",
                    "containerStatuses": [{
                        "name": "kubeflow-tpu",
                        "state": {"terminated": {"exitCode": exit_code}},
                    }],
                }))

    def set_all_pod_phases(self, namespace: str, phase: str,
                           label_selector: Optional[Dict[str, str]] = None
                           ) -> None:
        with self.as_kubelet():
            for pod in self._list("Pod", namespace, label_selector):
                self.set_pod_phase(namespace, pod["metadata"]["name"],
                                   phase)
