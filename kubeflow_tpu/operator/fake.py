"""A minimal in-memory apiserver for hermetic operator tests.

Implements just the object-store surface the reconciler needs
(create/get/list/patch/delete keyed by (kind, namespace, name)), plus
test helpers to drive pod phase transitions. This is the fake layer
SURVEY §4 calls out as missing from the reference.
"""

from __future__ import annotations

import copy
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

Key = Tuple[str, str, str]  # (kind, namespace, name)


class Conflict(Exception):
    pass


class NotFound(Exception):
    pass


class FakeApiServer:
    def __init__(self):
        self._objects: Dict[Key, Dict[str, Any]] = {}
        self._lock = threading.RLock()
        self._revision = 0

    @staticmethod
    def _key(obj: Dict[str, Any]) -> Key:
        meta = obj.get("metadata", {})
        return (obj["kind"], meta.get("namespace", "default"), meta["name"])

    def create(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            key = self._key(obj)
            if key in self._objects:
                raise Conflict(f"{key} already exists")
            stored = copy.deepcopy(obj)
            self._revision += 1
            stored.setdefault("metadata", {})["resourceVersion"] = str(
                self._revision)
            self._objects[key] = stored
            return copy.deepcopy(stored)

    def get(self, kind: str, namespace: str, name: str) -> Dict[str, Any]:
        with self._lock:
            try:
                return copy.deepcopy(self._objects[(kind, namespace, name)])
            except KeyError:
                raise NotFound(f"{kind} {namespace}/{name}") from None

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None
             ) -> List[Dict[str, Any]]:
        with self._lock:
            out = []
            for (k, ns, _), obj in sorted(self._objects.items()):
                if k != kind:
                    continue
                if namespace is not None and ns != namespace:
                    continue
                if label_selector:
                    labels = obj.get("metadata", {}).get("labels", {})
                    if any(labels.get(lk) != lv
                           for lk, lv in label_selector.items()):
                        continue
                out.append(copy.deepcopy(obj))
            return out

    def patch(self, kind: str, namespace: str, name: str,
              mutate: Callable[[Dict[str, Any]], None]) -> Dict[str, Any]:
        """Apply a mutation function under the store lock."""
        with self._lock:
            key = (kind, namespace, name)
            if key not in self._objects:
                raise NotFound(f"{kind} {namespace}/{name}")
            obj = self._objects[key]
            mutate(obj)
            self._revision += 1
            obj["metadata"]["resourceVersion"] = str(self._revision)
            return copy.deepcopy(obj)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        with self._lock:
            key = (kind, namespace, name)
            if key not in self._objects:
                raise NotFound(f"{kind} {namespace}/{name}")
            del self._objects[key]

    # -- test helpers -----------------------------------------------------

    def set_pod_phase(self, namespace: str, name: str, phase: str) -> None:
        self.patch("Pod", namespace, name,
                   lambda o: o.setdefault("status", {}).update(
                       {"phase": phase}))

    def set_pod_terminated(self, namespace: str, name: str,
                           exit_code: int) -> None:
        """Pod finished with ``exit_code``, the way a kubelet reports
        it: phase from the code (0 → Succeeded, else Failed) plus the
        containerStatuses.terminated record the drain detection reads
        (reconciler.pod_drained)."""
        self.patch(
            "Pod", namespace, name,
            lambda o: o.setdefault("status", {}).update({
                "phase": "Succeeded" if exit_code == 0 else "Failed",
                "containerStatuses": [{
                    "name": "kubeflow-tpu",
                    "state": {"terminated": {"exitCode": exit_code}},
                }],
            }))

    def set_all_pod_phases(self, namespace: str, phase: str,
                           label_selector: Optional[Dict[str, str]] = None
                           ) -> None:
        for pod in self.list("Pod", namespace, label_selector):
            self.set_pod_phase(namespace, pod["metadata"]["name"], phase)
