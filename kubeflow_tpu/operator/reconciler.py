# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""The TPUJob reconciler.

One reconcile pass is a pure-ish function of (TPUJob CR, owned pods):
it creates the gang's headless service + pods, evaluates the gang
state machine (C++ kernel, kubeflow_tpu.operator.gang), and applies
the decision — create missing pods, restart the whole slice, or mark
the job terminal. The controller loop (controller.py) just calls this
repeatedly; all logic is here so the fake-apiserver tests cover it.

Replaces tf-operator's per-replica reconcile (reference config at
``kubeflow/core/tf-job.libsonnet:31-148``; behavior summarized in
SURVEY §3.2): per-replica Services + independent pod restarts +
TF_CONFIG injection become one gang service + whole-slice lifecycle +
jax.distributed env.

Multi-slice (megascale) jobs: ``spec.numSlices`` > 1 provisions the
replicaSpecs once per slice — slice-major pod ordering, one shared
headless service and PDB over the union, and per-worker
``MEGASCALE_COORDINATOR_ADDRESS`` / ``MEGASCALE_NUM_SLICES`` /
``MEGASCALE_SLICE_ID`` injection on top of the flat ``KFT_*`` gang
env. Recovery stays all-or-nothing across the UNION: one failed pod on
any slice restarts every slice (an SPMD program spanning slices has no
partial-degradation mode). The TPU translation of the reference
operator's cluster-spec assembly
(``kubeflow/core/tf-job.libsonnet:31-95``).
"""

from __future__ import annotations

import copy
import dataclasses
import datetime
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from kubeflow_tpu.manifests import k8s
from kubeflow_tpu.manifests.tpujob import GROUP, KIND, VERSION
from kubeflow_tpu.operator.fake import (
    Conflict,
    NotFound,
    ServerError,
    TooManyRequests,
)
from kubeflow_tpu.operator.gang import Decision, PodPhase, decide
from kubeflow_tpu.training.launcher import (
    DRAIN_EXIT_CODE,
    ENV_COORD,
    ENV_NPROC,
    ENV_PID,
    ENV_REPLICA_INDEX,
    ENV_REPLICA_TYPE,
)

logger = logging.getLogger(__name__)

COORDINATOR_PORT = 8476
# The megascale (cross-slice DCN) coordinator rides a separate port on
# slice 0's first worker, next to the jax.distributed one.
MEGASCALE_PORT = 8477
DEFAULT_MAX_RESTARTS = 3
# Consecutive reconcile passes to re-observe a non-chief Succeeded
# before calling it a slice fault (pod-status propagation skew on a
# normally-finishing SPMD job shows exactly this signature; see
# gang.Decision.HOLD_COMPLETION).
DEFAULT_COMPLETION_GRACE_PASSES = 3
JOB_LABEL = "kubeflow.org/tpujob"
REPLICA_TYPE_LABEL = "kubeflow.org/replica-type"
REPLICA_INDEX_LABEL = "kubeflow.org/replica-index"
SLICE_INDEX_LABEL = "kubeflow.org/slice-index"
# Elastic resize roll bookkeeping (r16): every gang pod carries the
# resize generation it was created under. A resize bumps
# status.resizeGeneration, so the roll can tell a STALE pod (old
# world size baked into its env — same name as its successor) from a
# freshly-created member of the new gang.
GANG_GENERATION_LABEL = "kubeflow.org/gang-generation"
# Non-phase conditions: set alongside the phase conditions, never
# flipped by the phase machinery in _update_conditions.
STALLED_CONDITION = "ReconcileStalled"
DEADLINE_CONDITION = "DeadlineExceeded"
# Gang preemption (r12): the victim wears Preempted (cleared when it
# reschedules back to Running); the preemptor records PreemptedVictim.
PREEMPTED_CONDITION = "Preempted"
PREEMPTOR_CONDITION = "PreemptedVictim"
# Elastic gangs (r16): Resizing is True while a coordinated resize
# roll is in flight (old gang torn down, new gang not yet running);
# Resized records the last completed resize. GangShrunk marks a gang
# the preemptor (or admission pressure) shrank below its desired
# size — cleared only when the gang runs at full size again.
RESIZING_CONDITION = "Resizing"
RESIZED_CONDITION = "Resized"
SHRUNK_CONDITION = "GangShrunk"
# Settle timer while a resize roll waits for old pods to terminate:
# the workqueue re-observes at this cadence instead of waiting for
# the relist period.
RESIZE_SETTLE_SECONDS = 0.2


def pod_drained(pod: Optional[Dict[str, Any]]) -> bool:
    """Whether a Failed pod actually DRAINED: its container exited
    with DRAIN_EXIT_CODE (training/loop.py's SIGTERM path — finish the
    step, checkpoint, exit). Kubernetes phases any nonzero exit as
    Failed; the exit code is the only signal distinguishing 'the
    platform preempted us mid-checkpointed-run' from 'the program
    crashed'."""
    if not pod:
        return False
    for cs in pod.get("status", {}).get("containerStatuses", []):
        term = (cs.get("state") or {}).get("terminated")
        if term and term.get("exitCode") == DRAIN_EXIT_CODE:
            return True
    return False


def _update_conditions(status: Dict[str, Any], phase: str,
                       reason: Optional[str]) -> None:
    """Maintain k8s-conventional status.conditions (one entry per
    phase type; `status` True on the current phase, False on the
    rest; lastTransitionTime only moves on actual transitions) —
    the tf-operator's TFJobCondition surface, which kubectl
    describe/wait and the dashboard consume. Non-phase condition
    types (ReconcileStalled, DeadlineExceeded) pass through
    untouched."""
    now = datetime.datetime.now(datetime.timezone.utc).isoformat()
    conditions = {c["type"]: c for c in status.get("conditions", [])}
    for cond_type in ("Pending", "Running", "Restarting",
                      "Succeeded", "Failed"):
        active = cond_type == phase
        entry = conditions.get(cond_type)
        wanted = "True" if active else "False"
        if entry is None:
            if not active:
                continue  # don't materialize never-entered states
            entry = {"type": cond_type, "status": wanted,
                     "lastTransitionTime": now}
            conditions[cond_type] = entry
        elif entry["status"] != wanted:
            entry["status"] = wanted
            entry["lastTransitionTime"] = now
        if active and reason:
            entry["reason"] = reason
    status["conditions"] = list(conditions.values())


def _set_extra_condition(status: Dict[str, Any], cond_type: str,
                         cond_status: str, reason: str) -> bool:
    """Upsert a non-phase condition (ReconcileStalled,
    DeadlineExceeded); returns whether anything changed.
    lastTransitionTime only moves on actual status flips, matching
    the phase-condition convention."""
    now = datetime.datetime.now(datetime.timezone.utc).isoformat()
    conditions = status.setdefault("conditions", [])
    for entry in conditions:
        if entry.get("type") == cond_type:
            changed = False
            if entry.get("status") != cond_status:
                entry["status"] = cond_status
                entry["lastTransitionTime"] = now
                changed = True
            if entry.get("reason") != reason:
                entry["reason"] = reason
                changed = True
            return changed
    conditions.append({"type": cond_type, "status": cond_status,
                       "reason": reason, "lastTransitionTime": now})
    return True


class _StateMoved(Exception):
    """Raised inside a status mutation when the freshly-read object
    no longer satisfies the decision's precondition (e.g. a
    preemption victim that Succeeded between the cache read and the
    write). Raising BEFORE any mutation aborts the write cleanly on
    every client — the read-modify-write TOCTOU guard, same pattern
    as leader._LostRace."""


def _parse_k8s_time(value: Optional[str]
                    ) -> Optional[datetime.datetime]:
    if not value:
        return None
    try:
        parsed = datetime.datetime.fromisoformat(
            value.replace("Z", "+00:00"))
    except ValueError:
        return None
    if parsed.tzinfo is None:
        parsed = parsed.replace(tzinfo=datetime.timezone.utc)
    return parsed


@dataclasses.dataclass
class ReplicaMember:
    """One expected pod of the job — of ONE gang, on one slice.

    Multi-slice (``spec.numSlices`` > 1) jobs provision the
    replicaSpecs once per slice; ``slice_id`` identifies which copy,
    and ``num_slices`` travels along so pod naming and megascale env
    need no extra context."""

    replica_type: str
    index: int
    spec: Dict[str, Any]
    slice_id: int = 0
    num_slices: int = 1

    def pod_name(self, job_name: str) -> str:
        kind = self.replica_type.lower().replace("_", "-")
        if self.num_slices > 1:
            return f"{job_name}-s{self.slice_id}-{kind}-{self.index}"
        # Single-slice pods keep the pre-r5 names (dashboards, docs,
        # kubectl muscle memory).
        return f"{job_name}-{kind}-{self.index}"


def job_num_slices(job: Dict[str, Any]) -> int:
    return int(job["spec"].get("numSlices", 1) or 1)


def _scheduling_deadline(job: Dict[str, Any]) -> Optional[float]:
    """spec.schedulingDeadlineSeconds as a float, or None (off).
    Zero/negative/garbage reads as off — a bad value must not
    instantly fail every job."""
    raw = job["spec"].get("schedulingDeadlineSeconds")
    if raw is None:
        return None
    try:
        deadline = float(raw)
    except (TypeError, ValueError):
        return None
    return deadline if deadline > 0 else None


def job_priority(job: Dict[str, Any]) -> int:
    """spec.priority as an int, 0 (the default class) on absent or
    garbage — a bad value must neither preempt anyone nor make the
    job preemptible below its intended class."""
    raw = job.get("spec", {}).get("priority")
    if raw is None:
        return 0
    try:
        return int(raw)
    except (TypeError, ValueError):
        return 0


def job_elastic_bounds(job: Dict[str, Any]
                       ) -> Optional[Tuple[int, int]]:
    """``(minReplicas, maxReplicas)`` for an elastic job, or None for
    a rigid one. Elasticity applies to the TPU_WORKER replica count of
    a single-slice job with exactly one TPU_WORKER replicaSpec; any
    garbage/incoherent bound degrades to rigid — a bad value must
    never make the operator resize (or refuse to restart) a gang that
    never asked for elasticity."""
    spec = job.get("spec", {})
    raw_min = spec.get("minReplicas")
    if raw_min is None:
        return None
    if job_num_slices(job) > 1:
        return None  # megascale slices recover all-or-nothing
    workers = [s for s in spec.get("replicaSpecs", [])
               if s.get("tpuReplicaType") == "TPU_WORKER"]
    if len(workers) != 1:
        return None
    try:
        desired = int(workers[0].get("replicas", 1))
        lo = int(raw_min)
        hi = int(spec.get("maxReplicas", desired) or desired)
    except (TypeError, ValueError):
        return None
    if not 1 <= lo <= desired <= hi:
        return None
    return (lo, hi)


def elastic_current_replicas(job: Dict[str, Any]) -> Optional[int]:
    """The elastic gang's CURRENT worker count (status.currentReplicas
    clamped into [min, max]), or None for rigid jobs. Defaults to the
    desired spec count; garbage in status degrades to desired."""
    bounds = job_elastic_bounds(job)
    if bounds is None:
        return None
    lo, hi = bounds
    desired = _desired_workers(job)
    raw = job.get("status", {}).get("currentReplicas")
    try:
        current = desired if raw is None else int(raw)
    except (TypeError, ValueError):
        current = desired
    return max(lo, min(hi, current))


def _desired_workers(job: Dict[str, Any]) -> int:
    return sum(int(s.get("replicas", 1))
               for s in job.get("spec", {}).get("replicaSpecs", [])
               if s.get("tpuReplicaType") == "TPU_WORKER")


def _condition_true(status: Dict[str, Any], cond_type: str) -> bool:
    return any(c.get("type") == cond_type and c.get("status") == "True"
               for c in status.get("conditions", []))


def _resize_generation(status: Dict[str, Any]) -> int:
    try:
        return int(status.get("resizeGeneration", 0))
    except (TypeError, ValueError):
        return 0


def _shrinkable(job: Dict[str, Any]) -> bool:
    """Whether a preemption victim can absorb the eviction as an
    elastic shrink (current size strictly above minReplicas)."""
    bounds = job_elastic_bounds(job)
    if bounds is None:
        return False
    current = elastic_current_replicas(job)
    return current is not None and current > bounds[0]


class PreemptionPolicy:
    """Gang-preemption knobs + the GLOBAL rate limiter.

    ``deadline_fraction``: a Pending job with ``spec.priority`` > 0
    and a scheduling deadline becomes eligible to preempt once its
    time-in-Pending reaches this fraction of the deadline (the r7
    deadline machinery is the trigger — a job without a deadline never
    preempts; it has declared it is willing to wait forever).
    ``min_interval_seconds``: at most one preemption decision fires
    per interval ACROSS THE FLEET — a priority storm (N high-priority
    jobs submitted at once) evicts at a bounded, non-thrashing rate
    instead of flattening every low-priority gang in one pass."""

    def __init__(self, *, deadline_fraction: float = 0.5,
                 min_interval_seconds: float = 30.0,
                 clock=time.monotonic):
        if not 0.0 < deadline_fraction <= 1.0:
            raise ValueError(
                f"deadline_fraction must be in (0, 1], got "
                f"{deadline_fraction}")
        if min_interval_seconds < 0:
            raise ValueError("min_interval_seconds must be >= 0")
        self.deadline_fraction = deadline_fraction
        self.min_interval_seconds = min_interval_seconds
        self._clock = clock
        self._last: Optional[float] = None
        self._lock = threading.Lock()
        # Counters for the stats/metrics surface. ``shrunk`` counts
        # the grants that resolved as an elastic shrink rather than a
        # gang kill (r16 shrink-first rule) — both actions share the
        # interval and the one-victim-per-episode latch.
        self.eligible = 0
        self.granted = 0
        self.rate_limited = 0
        self.no_victim = 0
        self.shrunk = 0

    def try_acquire(self) -> Optional[float]:
        """Claim the global preemption interval if it has elapsed;
        returns the grant token (truthy) or None when rate-limited.
        The ``granted`` counter moves only at :meth:`commit` —
        AFTER the eviction's first write lands — so the Prometheus
        counter bound to it stays monotone (a decrementing Counter
        reads as a reset and corrupts rate())."""
        with self._lock:
            now = self._clock()
            if (self._last is not None
                    and now - self._last < self.min_interval_seconds):
                self.rate_limited += 1
                return None
            self._prev_last = self._last
            self._last = now
            return now

    def commit(self) -> None:
        """The eviction's victim record landed: count it."""
        with self._lock:
            self.granted += 1

    def rollback(self, token: float) -> None:
        """Release a claim: the eviction aborted before ANY cluster
        state changed (victim status write lost its race), so the
        fleet must not serve the interval for it. The clock is
        restored only if OUR claim is still the latest — an eviction
        attempt that outlived the interval must not erase a newer
        claim's cooldown."""
        with self._lock:
            if self._last == token:
                self._last = self._prev_last

    def stats(self) -> Dict[str, Any]:
        return {
            "deadlineFraction": self.deadline_fraction,
            "minIntervalSeconds": self.min_interval_seconds,
            "eligible": self.eligible,
            "granted": self.granted,
            "rateLimited": self.rate_limited,
            "noVictim": self.no_victim,
            "shrunk": self.shrunk,
        }


def expected_members(job: Dict[str, Any]) -> List[ReplicaMember]:
    """Every expected pod, slice-major (slice 0's replicas first) —
    the order that makes the global TPU_WORKER process ids put the
    ``dcn_data`` mesh axis exactly on slice boundaries.

    Elastic jobs (``spec.minReplicas``, r16): the TPU_WORKER count is
    the CURRENT gang size (``status.currentReplicas``, clamped into
    [min, max]) rather than the spec's desired count — the membership
    view every consumer (pod creation, env injection, PDB sizing,
    preemption teardown) must agree on after a resize."""
    num_slices = job_num_slices(job)
    current = elastic_current_replicas(job)
    members: List[ReplicaMember] = []
    for slice_id in range(num_slices):
        for spec in job["spec"].get("replicaSpecs", []):
            n = int(spec.get("replicas", 1))
            if (current is not None
                    and spec.get("tpuReplicaType") == "TPU_WORKER"):
                n = current
            for index in range(n):
                members.append(ReplicaMember(
                    replica_type=spec["tpuReplicaType"], index=index,
                    spec=spec, slice_id=slice_id, num_slices=num_slices))
    return members


def chief_member_index(job: Dict[str, Any],
                       members: List[ReplicaMember]) -> int:
    policy = job["spec"].get("terminationPolicy", {}).get("chief", {})
    chief_type = policy.get("replicaName", "COORDINATOR")
    chief_idx = int(policy.get("replicaIndex", 0))
    for i, m in enumerate(members):
        # The chief lives on slice 0 (a multi-slice job has one chief,
        # not one per slice).
        if (m.replica_type == chief_type and m.index == chief_idx
                and m.slice_id == 0):
            return i
    # Fall back to the first member (a job with no matching chief
    # replica still needs a success definition).
    return 0


class Reconciler:
    def __init__(self, api, *, reader=None,
                 max_restarts: int = DEFAULT_MAX_RESTARTS,
                 completion_grace_passes: int =
                 DEFAULT_COMPLETION_GRACE_PASSES,
                 preemption: Optional[PreemptionPolicy] = None):
        self.api = api
        # The READ path of the reconcile hot loop: an informer-backed
        # CachedApiClient under the watch controller (zero apiserver
        # requests per pass), or the api itself in direct/poll mode.
        # scripts/lint.py check_operator_read_discipline enforces that
        # hot-path reads go through self.reader, so the cache split
        # can't silently erode.
        self.reader = reader if reader is not None else api
        self.max_restarts = max_restarts
        self.completion_grace_passes = completion_grace_passes
        self.preemption = preemption or PreemptionPolicy()
        # Elastic-gang resize ledger (kft_operator_gang_resizes_total
        # {direction} rides these via the controller's render-time
        # callbacks): shrink = member loss / admission pressure /
        # preemptor shrink; grow = a slice restart resetting a shrunk
        # gang back to its desired size.
        self._resize_lock = threading.Lock()
        self._resizes = {"shrink": 0, "grow": 0}
        # Per-pass, PER-THREAD (N controller workers share one
        # Reconciler): seconds after which this job wants another
        # look even with no events (a pending schedulingDeadline).
        # The watch controller turns it into a workqueue timer.
        self._pass_state = threading.local()

    def attach_cache(self, cached) -> None:
        """Rebind both paths onto an informer-backed CachedApiClient
        (reads from the store, writes through-and-absorbed). Called by
        the watch controller; the underlying api client is unchanged —
        the cache wraps it."""
        self.api = cached
        self.reader = cached

    def resize_counts(self) -> Dict[str, int]:
        with self._resize_lock:
            return dict(self._resizes)

    def _count_resize(self, direction: str) -> None:
        with self._resize_lock:
            self._resizes[direction] = self._resizes.get(direction, 0) + 1

    @property
    def requeue_after(self) -> Optional[float]:
        return getattr(self._pass_state, "requeue_after", None)

    @requeue_after.setter
    def requeue_after(self, value: Optional[float]) -> None:
        self._pass_state.requeue_after = value

    # -- object builders --------------------------------------------------

    def _gang_service(self, job: Dict[str, Any]) -> Dict[str, Any]:
        """One headless service giving every gang pod a stable DNS name
        ``<pod>.<job>.<ns>.svc`` (the reference made one Service per
        replica index; a single subdomain service is the k8s-native way
        to DNS a gang)."""
        name = job["metadata"]["name"]
        ns = job["metadata"].get("namespace", "default")
        svc = k8s.service(
            name, ns, {JOB_LABEL: name},
            [k8s.service_port(COORDINATOR_PORT, name="coordinator")],
            cluster_ip="None", labels={JOB_LABEL: name},
        )
        svc["spec"]["publishNotReadyAddresses"] = True
        return svc

    def _gang_pdb(self, job: Dict[str, Any],
                  gang_size: int) -> Dict[str, Any]:
        """PodDisruptionBudget with ``minAvailable`` = the full gang:
        an SPMD slice has no partial-degradation mode — ANY voluntary
        eviction (node drain, autoscaler bin-packing) kills the
        collective, burns a restart, and rolls the job back to its
        checkpoint. The PDB makes the apiserver refuse such evictions
        outright. (Involuntary failures still flow through the
        restart-slice state machine.) Beyond reference parity: the
        2018 operator let replicas die independently by design."""
        name = job["metadata"]["name"]
        ns = job["metadata"].get("namespace", "default")
        return {
            "apiVersion": "policy/v1",
            "kind": "PodDisruptionBudget",
            "metadata": {
                "name": name,
                "namespace": ns,
                "labels": {JOB_LABEL: name},
                "ownerReferences": [{
                    "apiVersion": f"{GROUP}/{VERSION}",
                    "kind": KIND,
                    "name": name,
                    "uid": job["metadata"].get("uid", ""),
                    "controller": True,
                }],
            },
            "spec": {
                "minAvailable": gang_size,
                "selector": {"matchLabels": {JOB_LABEL: name}},
            },
        }

    def _member_pod(self, job: Dict[str, Any], member: ReplicaMember,
                    members: List[ReplicaMember]) -> Dict[str, Any]:
        name = job["metadata"]["name"]
        ns = job["metadata"].get("namespace", "default")
        pod_name = member.pod_name(name)
        template = {} if member.spec.get("template") is None else member.spec["template"]
        pod_spec = dict(template.get("spec", {}))
        containers = [dict(c) for c in pod_spec.get("containers", [])]
        if not containers:
            containers = [{"name": "kubeflow-tpu",
                           "image": "ghcr.io/kubeflow-tpu/trainer:v0.1.0"}]

        # Distributed bootstrap env (replaces TF_CONFIG injection).
        # jax.distributed sees ONE FLAT GANG across every slice:
        # num_processes counts all workers of all slices and
        # process_id is the slice-major global index (expected_members
        # order), so the mesh's outermost dcn_data axis lands exactly
        # on slice boundaries. The TPU runtime's own TPU_WORKER_* vars
        # stay PER-SLICE (each slice's runtime bootstraps its own ICI
        # domain); MEGASCALE_* wires the cross-slice DCN transport.
        workers = [m for m in members if m.replica_type == "TPU_WORKER"]
        n_proc = len(workers) if member.replica_type == "TPU_WORKER" else 1
        coord_pod = (workers[0] if workers else members[0]).pod_name(name)
        coordinator = f"{coord_pod}.{name}.{ns}:{COORDINATOR_PORT}"
        if member.replica_type == "TPU_WORKER":
            process_id = next(
                gid for gid, w in enumerate(workers)
                if w.slice_id == member.slice_id
                and w.index == member.index)
        else:
            process_id = 0
        slice_workers = [w for w in workers
                         if w.slice_id == member.slice_id]
        hostnames = ",".join(
            f"{w.pod_name(name)}.{name}.{ns}" for w in slice_workers)
        env = [
            k8s.env_var(ENV_COORD, coordinator),
            k8s.env_var(ENV_NPROC, n_proc),
            k8s.env_var(ENV_PID, process_id),
            k8s.env_var(ENV_REPLICA_TYPE, member.replica_type),
            k8s.env_var(ENV_REPLICA_INDEX, member.index),
        ]
        if member.replica_type == "TPU_WORKER":
            env += [
                k8s.env_var("TPU_WORKER_ID", member.index),
                k8s.env_var("TPU_WORKER_HOSTNAMES", hostnames),
            ]
            if member.num_slices > 1:
                # The megascale contract (SURVEY §2.4): coordinator =
                # slice 0's first worker, on its own port; build_mesh
                # reads MEGASCALE_NUM_SLICES for the dcn_data axis.
                ms_coord = (f"{workers[0].pod_name(name)}.{name}.{ns}"
                            f":{MEGASCALE_PORT}")
                env += [
                    k8s.env_var("MEGASCALE_COORDINATOR_ADDRESS", ms_coord),
                    k8s.env_var("MEGASCALE_NUM_SLICES", member.num_slices),
                    k8s.env_var("MEGASCALE_SLICE_ID", member.slice_id),
                ]
        for container in containers:
            merged = {e["name"]: e for e in container.get("env", [])}
            for e in env:
                merged.setdefault(e["name"], e)
            container["env"] = list(merged.values())
        pod_spec["containers"] = containers
        # Never let the kubelet restart gang members individually: the
        # operator owns recovery at slice granularity. (The reference
        # relied on per-pod OnFailure restarts, tf-job.libsonnet:30.)
        pod_spec["restartPolicy"] = "Never"
        pod_spec["hostname"] = pod_name
        pod_spec["subdomain"] = name
        return {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": pod_name,
                "namespace": ns,
                "labels": {
                    JOB_LABEL: name,
                    REPLICA_TYPE_LABEL: member.replica_type,
                    REPLICA_INDEX_LABEL: str(member.index),
                    SLICE_INDEX_LABEL: str(member.slice_id),
                    GANG_GENERATION_LABEL: str(_resize_generation(
                        job.get("status", {}))),
                },
                "ownerReferences": [{
                    "apiVersion": f"{GROUP}/{VERSION}",
                    "kind": KIND,
                    "name": name,
                    "uid": job["metadata"].get("uid", ""),
                    "controller": True,
                }],
            },
            "spec": pod_spec,
        }

    # -- reconcile --------------------------------------------------------

    def reconcile(self, job: Dict[str, Any]) -> str:
        """One pass; returns the job phase after the pass."""
        name = job["metadata"]["name"]
        ns = job["metadata"].get("namespace", "default")
        status = job.get("status", {})
        phase = status.get("phase", "Pending")
        self.requeue_after = None
        if phase in ("Succeeded", "Failed"):
            return phase

        members = expected_members(job)
        if not members:
            return self._set_status(job, "Failed",
                                    reason="no replicaSpecs")
        chief = chief_member_index(job, members)
        # Elastic gangs (r16): ``elastic`` carries (min, max) worker
        # bounds (None = rigid); ``resizing`` is True while a
        # coordinated resize roll is in flight (old gang torn down,
        # new one not yet running).
        elastic = job_elastic_bounds(job)
        resizing = _condition_true(status, RESIZING_CONDITION)

        # Gang scheduling deadline bookkeeping happens after the pod
        # scan below — the verdict must come from LIVE pod state, not
        # from a possibly-stale status.phase.
        deadline = _scheduling_deadline(job)

        # Ensure the gang DNS service + the whole-gang disruption
        # budget (minAvailable = gang size: voluntary evictions are
        # refused rather than burning a slice restart).
        for kind, make in (("Service", lambda: self._gang_service(job)),
                           ("PodDisruptionBudget",
                            lambda: self._gang_pdb(job, len(members)))):
            try:
                existing = self.reader.get(kind, ns, name)
                if (kind == "PodDisruptionBudget"
                        and existing["spec"].get("minAvailable")
                        != len(members)):
                    # replicaSpecs were rescaled: a stale budget would
                    # let the apiserver evict the difference — the
                    # exact slice-restart burn the PDB prevents.
                    try:
                        self.api.patch(
                            kind, ns, name,
                            lambda o: o["spec"].update(
                                {"minAvailable": len(members)}))
                    except Conflict:
                        # The real client's patch is read-modify-
                        # replace; a concurrent controller replica can
                        # race it into a resourceVersion conflict.
                        # Next resync re-observes and re-sizes.
                        pass
            except NotFound:
                try:
                    self.api.create(make())
                except Conflict:
                    # Concurrent resync / second controller replica
                    # won the create race — the object exists, which
                    # is all this pass wanted (same rule as the pod
                    # creates below).
                    pass

        pods = {p["metadata"]["name"]: p
                for p in self.reader.list("Pod", ns, {JOB_LABEL: name})}
        restarts = int(status.get("restartCount", 0))

        if phase == "Restarting":
            # Pods were deleted last pass but terminate asynchronously
            # on a real cluster (grace period); re-deciding while they
            # linger as Failed would burn one restart per resync. Hold
            # until the gang is fully gone, then fall through — every
            # member reads MISSING and decide() says CREATE_MISSING.
            if any(m.pod_name(name) in pods for m in members):
                return phase

        if resizing:
            # Coordinated resize roll in flight: the WHOLE old gang
            # must terminate before the new one is created (every
            # pod's KFT_NUM_PROCESSES / TPU_WORKER_HOSTNAMES env
            # changes with the gang size, and an old high-index pod
            # lingering past a shrink would be a zombie voter in the
            # collective). Old and new pods share NAMES — the resize
            # generation label is what tells them apart: pods from an
            # older generation (or none) are stale and get swept,
            # including stragglers whose indices fall outside the NEW
            # membership. Settle timer instead of waiting for a
            # relist. Pods of the CURRENT generation are the new gang
            # — fall through to the normal flow so Resizing settles.
            generation = str(_resize_generation(status))
            stale = [
                pod_name for pod_name, pod in pods.items()
                if pod.get("metadata", {}).get("labels", {})
                .get(GANG_GENERATION_LABEL) != generation]
            if stale:
                for pod_name in stale:
                    try:
                        self.api.delete("Pod", ns, pod_name)
                    except NotFound:
                        pass
                self.requeue_after = RESIZE_SETTLE_SECONDS
                return phase

        # MISSING means the pod OBJECT is absent. A pod that exists
        # but has no status.phase yet (the window between create and
        # the kubelet's first status write) is PENDING — reading it
        # as MISSING made a resync in that window re-create a live
        # pod (Conflict; found by the reconciler fuzz).
        phases = [
            PodPhase.from_k8s(
                pods[m.pod_name(name)].get("status", {}).get("phase")
                or "Pending")
            if m.pod_name(name) in pods else PodPhase.MISSING
            for m in members
        ]

        # Elastic member loss (r16 tentpole): a Running elastic gang
        # that lost TPU_WORKER members — spot preemption, eviction,
        # crash — RESIZES to the survivor count (clamped to [min,
        # max]) instead of riding the restart-budget path: one
        # coordinated roll rewrites every survivor's gang env/world
        # view and the training loop reshards from the continuous
        # checkpoint. Below min the elastic contract is exhausted and
        # the classic whole-slice machinery takes over.
        if elastic is not None and not resizing and phase == "Running":
            new_size = self._plan_member_loss_resize(
                members, phases, elastic)
            if new_size is not None:
                current = elastic_current_replicas(job)
                return self._begin_resize(
                    job, phase, new_size, restarts=restarts, pods=pods,
                    detail=f"member loss: resizing gang "
                           f"{current} -> {new_size} workers "
                           f"(minReplicas={elastic[0]}; restart "
                           f"budget {restarts}/{self.max_restarts} "
                           f"unchanged)")

        # Gang scheduling deadline: a gang that can never place sits
        # Pending forever — on TPUs that is held hardware. Enforced
        # from LIVE pod state: it fires only while the gang has a
        # scheduling attempt outstanding (pods exist, none has ever
        # started — a Running/Succeeded/Failed pod means scheduling
        # happened and other machinery owns the outcome) so a timer
        # racing the pod-event pass can never tear down a healthy
        # gang. On expiry the job Fails with a DeadlineExceeded
        # condition + Event and the gang's pods are torn down so the
        # slices release.
        if deadline is not None and phase == "Pending":
            age = self._pending_age(job)
            awaiting_schedule = (
                any(p != PodPhase.MISSING for p in phases)
                and all(p in (PodPhase.PENDING, PodPhase.MISSING)
                        for p in phases))
            # Elastic admission shrink (r16): a Pending elastic gang
            # burning through its scheduling deadline is asking for
            # more chips than the pool has — shrink one worker toward
            # minReplicas (paced at half the eligibility fraction)
            # and retry, instead of holding out for the full size
            # until the deadline kills it. At min the deadline
            # applies unchanged.
            if (elastic is not None and not resizing
                    and awaiting_schedule and age is not None):
                shrunk = self._maybe_admission_shrink(
                    job, elastic, deadline, age, restarts, pods)
                if shrunk is not None:
                    return shrunk
            if (age is not None and age >= deadline
                    and awaiting_schedule):
                for m in members:
                    try:
                        self.api.delete("Pod", ns, m.pod_name(name))
                    except NotFound:
                        pass
                return self._set_status(
                    job, "Failed",
                    reason=f"gang not scheduled within "
                           f"schedulingDeadlineSeconds={int(deadline)} "
                           f"(Pending for {age:.0f}s); gang torn down",
                    extra_condition=(
                        DEADLINE_CONDITION,
                        f"Pending {age:.0f}s >= deadline "
                        f"{int(deadline)}s"),
                    event_reason=DEADLINE_CONDITION)
            # Gang preemption: a high-priority gang burning through
            # its scheduling deadline means chips are scarce — evict
            # the lowest-priority running gang to make room, at a
            # globally rate-limited cadence. Driven by the same
            # live-pod predicate as the deadline itself: only a gang
            # with a genuine scheduling attempt outstanding preempts.
            # ONE victim per Pending episode (the PreemptedVictim
            # condition is the latch, cleared when the job runs): a
            # gang that still cannot place after its victim's chips
            # freed is doomed anyway — its deadline fails it instead
            # of it cascading down the priority ladder.
            priority = job_priority(job)
            already_made_room = any(
                c.get("type") == PREEMPTOR_CONDITION
                and c.get("status") == "True"
                for c in status.get("conditions", []))
            if (priority > 0 and awaiting_schedule
                    and not already_made_room and age is not None
                    and age >= deadline
                    * self.preemption.deadline_fraction):
                self._maybe_preempt(job, priority)
            if age is not None and all(
                    p in (PodPhase.PENDING, PodPhase.MISSING)
                    for p in phases):
                # Ask to be re-observed right when the deadline lands
                # (events are quiescent for a stuck-Pending gang; the
                # relist period alone could overshoot by a resync) —
                # and, for a priority job, also at the earlier
                # preemption-eligibility instant.
                wake = max(0.0, deadline - age)
                if priority > 0:
                    trigger = (deadline * self.preemption.deadline_fraction
                               - age)
                    if trigger > 0:
                        wake = min(wake, trigger)
                if elastic is not None:
                    # Also wake at the admission-shrink eligibility
                    # instant (same fraction as preemption) so a
                    # stuck elastic gang shrinks on time rather than
                    # at the next relist.
                    current = elastic_current_replicas(job)
                    if current is not None and current > elastic[0]:
                        trigger = (deadline
                                   * self.preemption.deadline_fraction
                                   - age)
                        if trigger > 0:
                            wake = min(wake, trigger)
                self.requeue_after = wake

        allow_restart = job["spec"].get("recoveryPolicy",
                                        "restart-slice") == "restart-slice"
        skew_passes = int(status.get("completionSkewPasses", 0))
        # Preemption drain: when EVERY failed pod exited with the
        # drain code (SIGTERM → finish step → checkpoint → exit 77),
        # the slice restart is the platform's fault, not the job's —
        # it must not consume the restart budget, and budget
        # exhaustion must not fail a job that only ever drained. Any
        # genuinely crashed pod in the mix disables the exemption.
        failed_pods = [pods.get(m.pod_name(name))
                       for m, p in zip(members, phases)
                       if p == PodPhase.FAILED]
        drained_only = bool(failed_pods) and all(
            pod_drained(pod) for pod in failed_pods)
        decision = decide(
            phases, chief, allow_restart=allow_restart,
            restarts=0 if drained_only else restarts,
            max_restarts=self.max_restarts,
            completion_grace=skew_passes < self.completion_grace_passes)
        logger.info("tpujob %s/%s: phases=%s decision=%s drained=%s",
                    ns, name, [p.name for p in phases], decision.name,
                    drained_only)

        if decision == Decision.HOLD_COMPLETION:
            # Completion skew observed: count the pass and re-observe
            # next resync; once the counter hits the grace budget,
            # decide() gets completion_grace=False and rules it a
            # slice fault for real.
            return self._set_status(job, phase, restart_count=restarts,
                                    completion_skew=skew_passes + 1)
        if decision == Decision.CREATE_MISSING:
            # Gang creation is all-or-nothing: every missing pod is
            # created in this pass (no partial slices waiting on PS
            # quota like the reference's independent replicas).
            for m, p in zip(members, phases):
                if p == PodPhase.MISSING:
                    try:
                        self.api.create(self._member_pod(job, m, members))
                    except Conflict:
                        # Lost a race (concurrent resync / second
                        # controller replica): the pod exists, which
                        # is what this pass wanted. Idempotent.
                        pass
            # "Has this job restarted?" must come from the phase, not
            # the budget counter: a drain-exempted restart leaves
            # restartCount at 0 by design, and a long-running job
            # regressing to Pending after a spot preemption would read
            # as never-started on every dashboard.
            if resizing and phase in ("Running", "Pending"):
                # A mid-resize recreate keeps the display phase: an
                # elastic gang rolling to a new size never
                # "restarted" — a Running gang stays Running through
                # the membership change, an admission-shrinking gang
                # stays Pending until it actually schedules.
                return self._set_status(job, phase,
                                        restart_count=restarts)
            recreating = restarts > 0 or phase == "Restarting"
            return self._set_status(
                job, "Running" if recreating else "Pending",
                restart_count=restarts)
        if decision == Decision.RESTART_SLICE:
            for m in members:
                try:
                    self.api.delete("Pod", ns, m.pod_name(name))
                except NotFound:
                    pass
            # Elastic grow-back: a full slice restart is a fresh
            # scheduling attempt — reset a shrunk gang to its desired
            # size (admission shrink re-shrinks it if chips are still
            # scarce). Counted as a grow resize.
            grow_to: Optional[int] = None
            if elastic is not None:
                desired = _desired_workers(job)
                current = elastic_current_replicas(job)
                if current is not None and current < desired:
                    grow_to = desired
                    self._count_resize("grow")
            if drained_only:
                return self._set_status(
                    job, "Restarting", restart_count=restarts,
                    current_replicas=grow_to,
                    reason="preemption drain; restarting from drain "
                           f"checkpoint (budget {restarts}/"
                           f"{self.max_restarts} unchanged)")
            return self._set_status(
                job, "Restarting", restart_count=restarts + 1,
                current_replicas=grow_to,
                reason=f"slice fault; restart {restarts + 1}/"
                       f"{self.max_restarts}")
        if decision == Decision.SUCCEED:
            # Tear down the rest of the gang (the reference's workers
            # slept forever instead, launcher.py:86-90).
            for m, p in zip(members, phases):
                if m.pod_name(name) in pods and p != PodPhase.SUCCEEDED:
                    try:
                        self.api.delete("Pod", ns, m.pod_name(name))
                    except NotFound:
                        pass
            return self._set_status(job, "Succeeded",
                                    restart_count=restarts)
        if decision == Decision.FAIL:
            return self._set_status(
                job, "Failed", restart_count=restarts,
                reason="slice fault and restart budget exhausted"
                       if restarts >= self.max_restarts else "slice fault")
        # Decision.NONE — all pods exist; Running once any runs. A job
        # already Running must not flap back to Pending in the window
        # where freshly-recreated pods (post-restart) lack kubelet
        # status — the same dashboard regression as the CREATE_MISSING
        # branch (exposed by the r5 event-emission test: the flap
        # emitted spurious Pending/Running event pairs every restart).
        pods_running = any(p == PodPhase.RUNNING for p in phases)
        incomplete = any(p == PodPhase.PENDING for p in phases)
        gang_complete = bool(phases) and all(
            p == PodPhase.RUNNING for p in phases)
        running = pods_running or phase == "Running"
        # Post-restart scheduling stall (r16): a display-Running gang
        # whose pods never ALL schedule again (spot storm shrank the
        # pool) holds its chips while making zero progress — the SPMD
        # collective cannot form without every host. The scheduling
        # deadline now covers this stall too, anchored on
        # status.schedulingSince (set below while the gang is
        # incomplete, cleared once it fully runs): an elastic gang
        # shrinks to the workers that actually scheduled; a rigid one
        # Fails with DeadlineExceeded and releases its slices.
        if (deadline is not None and phase == "Running" and incomplete
                and not resizing):
            stalled = self._maybe_scheduling_stall(
                job, deadline, members, phases, elastic, restarts,
                pods)
            if stalled is not None:
                return stalled
        result = self._set_status(job, "Running" if running else "Pending",
                                  restart_count=restarts,
                                  pods_running=pods_running,
                                  gang_complete=gang_complete,
                                  scheduling_pending=incomplete)
        if resizing and gang_complete:
            # The roll completed (EVERY member of the resized gang
            # runs — one pod up is not a formed collective):
            # _set_status just flipped Resizing → Resized inside the
            # same write; the Event records the settle for kubectl
            # describe (phase didn't change, so the phase-transition
            # emitter stayed quiet).
            size = elastic_current_replicas(job)
            self._record_event(
                job, f"{name}.resized.{size}", RESIZED_CONDITION,
                f"TPUJob gang resized; running at {size} workers",
                "Normal")
        return result

    def _pending_age(self, job: Dict[str, Any]) -> Optional[float]:
        """Seconds this job has been Pending, anchored on the Pending
        condition's lastTransitionTime — i.e. on the operator's OWN
        first observation, never metadata.creationTimestamp: a job
        submitted while the operator was down must get its full
        deadline of scheduling time after the operator returns, not
        be executed on the operator's first pass. None until this
        pass's own status write materializes the anchor."""
        now = datetime.datetime.now(datetime.timezone.utc)
        for cond in job.get("status", {}).get("conditions", []):
            if (cond.get("type") == "Pending"
                    and cond.get("status") == "True"):
                anchor = _parse_k8s_time(cond.get("lastTransitionTime"))
                if anchor is not None:
                    return (now - anchor).total_seconds()
        return None

    # -- elastic resize ---------------------------------------------------

    def _plan_member_loss_resize(self, members: List[ReplicaMember],
                                 phases: List[PodPhase],
                                 bounds: Tuple[int, int]
                                 ) -> Optional[int]:
        """The new gang size after member loss, or None when the loss
        is not elastically recoverable (nothing lost; a non-worker
        replica died; survivors fell below minReplicas — the classic
        restart machinery owns those)."""
        lo, hi = bounds
        lost = [(m, p) for m, p in zip(members, phases)
                if p in (PodPhase.FAILED, PodPhase.MISSING)]
        if not lost:
            return None
        if any(m.replica_type != "TPU_WORKER" for m, _ in lost):
            # A coordinator/CPU replica has no elastic dimension.
            return None
        survivors = sum(1 for m, p in zip(members, phases)
                        if m.replica_type == "TPU_WORKER"
                        and p in (PodPhase.RUNNING, PodPhase.PENDING))
        if survivors < lo or survivors < 1:
            return None
        return max(lo, min(hi, survivors))

    def _resize_cooldown_elapsed(self, job: Dict[str, Any],
                                 cooldown: float) -> bool:
        anchor = _parse_k8s_time(
            job.get("status", {}).get("lastResizeTime"))
        if anchor is None:
            return True
        now = datetime.datetime.now(datetime.timezone.utc)
        return (now - anchor).total_seconds() >= cooldown

    def _maybe_scheduling_stall(self, job: Dict[str, Any],
                                deadline: float,
                                members: List[ReplicaMember],
                                phases: List[PodPhase],
                                elastic: Optional[Tuple[int, int]],
                                restarts: int,
                                pods: Dict[str, Any]
                                ) -> Optional[str]:
        """Handle a display-Running gang stuck partially scheduled:
        shrink an elastic gang to its RUNNING worker count (never
        below min) at the eligibility fraction, fail a rigid one at
        the full deadline. Returns the resulting phase, or None when
        nothing fired yet (a requeue timer is armed instead)."""
        name = job["metadata"]["name"]
        ns = job["metadata"].get("namespace", "default")
        since = _parse_k8s_time(
            job.get("status", {}).get("schedulingSince"))
        fraction = self.preemption.deadline_fraction
        if since is None:
            # Anchor lands in this pass's status write; re-observe at
            # the first decision instant.
            self.requeue_after = deadline * fraction
            return None
        now = datetime.datetime.now(datetime.timezone.utc)
        stall = (now - since).total_seconds()
        if elastic is not None:
            current = elastic_current_replicas(job)
            running_workers = sum(
                1 for m, p in zip(members, phases)
                if m.replica_type == "TPU_WORKER"
                and p == PodPhase.RUNNING)
            if (current is not None
                    and running_workers >= elastic[0]
                    and running_workers < current
                    and stall >= deadline * fraction
                    and self._resize_cooldown_elapsed(
                        job, deadline * fraction / 2.0)):
                return self._begin_resize(
                    job, "Running", max(elastic[0], running_workers),
                    restarts=restarts, pods=pods,
                    detail=f"gang partially scheduled for "
                           f"{stall:.0f}s ({running_workers}/{current}"
                           f" workers running); shrinking to fit")
        if stall >= deadline:
            for m in members:
                try:
                    self.api.delete("Pod", ns, m.pod_name(name))
                except NotFound:
                    pass
            return self._set_status(
                job, "Failed", restart_count=restarts,
                reason=f"gang incomplete for {stall:.0f}s >= "
                       f"schedulingDeadlineSeconds={int(deadline)}; "
                       f"gang torn down",
                extra_condition=(
                    DEADLINE_CONDITION,
                    f"gang incomplete {stall:.0f}s >= deadline "
                    f"{int(deadline)}s"),
                event_reason=DEADLINE_CONDITION,
                scheduling_pending=False)
        wake = deadline - stall
        if elastic is not None:
            trigger = deadline * fraction - stall
            if trigger > 0:
                wake = min(wake, trigger)
        self.requeue_after = max(0.05, wake)
        return None

    def _maybe_admission_shrink(self, job: Dict[str, Any],
                                bounds: Tuple[int, int],
                                deadline: float, age: float,
                                restarts: int,
                                pods: Dict[str, Any]
                                ) -> Optional[str]:
        """One admission-pressure shrink step, or None (not eligible
        yet / already at min / still inside the pacing cooldown)."""
        lo, _ = bounds
        current = elastic_current_replicas(job)
        if current is None or current <= lo:
            return None
        fraction = self.preemption.deadline_fraction
        if age < deadline * fraction:
            return None
        # Pace at half the eligibility fraction so a 4→min descent
        # can fit inside one deadline (docs/operator.md runbook).
        if not self._resize_cooldown_elapsed(
                job, deadline * fraction / 2.0):
            return None
        return self._begin_resize(
            job, "Pending", current - 1, restarts=restarts, pods=pods,
            detail=f"gang unscheduled for {age:.0f}s of "
                   f"{int(deadline)}s deadline; shrinking "
                   f"{current} -> {current - 1} toward "
                   f"minReplicas={lo}")

    def _begin_resize(self, job: Dict[str, Any], phase: str,
                      new_size: int, *, restarts: int,
                      pods: Dict[str, Any], detail: str) -> str:
        """Start a coordinated resize roll: write the new size +
        Resizing condition (one status write), tear the old gang down
        (EVERY pod — the gang env is a function of the size, so
        survivors must roll too), and arm the settle timer. The next
        passes hold until the old pods are gone, recreate the gang at
        the new size, and flip Resizing → Resized once pods run."""
        name = job["metadata"]["name"]
        ns = job["metadata"].get("namespace", "default")
        current = elastic_current_replicas(job)
        self._count_resize(
            "grow" if current is not None and new_size > current
            else "shrink")
        result = self._set_status(
            job, phase, restart_count=restarts, reason=detail,
            extra_condition=(RESIZING_CONDITION, detail),
            current_replicas=new_size, stamp_resize=True)
        # Phase is unchanged by design, so the transition emitter
        # stays quiet — record the resize explicitly.
        self._record_event(job, f"{name}.resizing.{new_size}",
                           RESIZING_CONDITION,
                           f"TPUJob {detail}", "Normal")
        for pod_name in list(pods):
            try:
                self.api.delete("Pod", ns, pod_name)
            except NotFound:
                pass
        self.requeue_after = RESIZE_SETTLE_SECONDS
        return result

    # -- gang preemption --------------------------------------------------

    def _select_victim(self, job: Dict[str, Any],
                       priority: int) -> Optional[Dict[str, Any]]:
        """THE lowest-priority chip-holding gang strictly below
        ``priority`` — never an equal-or-higher class, never more
        than one per decision. Candidacy is POD truth, not the
        display phase: a gang recreated after a restart/preemption
        reads phase Running while its pods sit Pending, and evicting
        it would burn the fleet's rate-limit interval to free zero
        chips. Ties break youngest-first (the gang that has had the
        least time to make progress loses, k8s-style), then name for
        determinism."""
        me = (job["metadata"].get("namespace", "default"),
              job["metadata"]["name"])

        def holds_chips(other: Dict[str, Any]) -> bool:
            ons = other["metadata"].get("namespace", "default")
            oname = other["metadata"]["name"]
            return any(
                p.get("status", {}).get("phase") == "Running"
                for p in self.reader.list("Pod", ons,
                                          {JOB_LABEL: oname}))

        def prefer(a: Dict[str, Any], b: Dict[str, Any]) -> bool:
            """a is the better victim than b."""
            pa, pb = job_priority(a), job_priority(b)
            if pa != pb:
                return pa < pb
            # Shrink-first (r16): at equal priority, an elastic gang
            # that can still shrink is the cheaper victim — it loses
            # one worker and reshards, where a rigid gang dies whole.
            sa, sb = _shrinkable(a), _shrinkable(b)
            if sa != sb:
                return sa
            ca = a["metadata"].get("creationTimestamp", "")
            cb = b["metadata"].get("creationTimestamp", "")
            if ca != cb:
                return ca > cb  # youngest loses its slot first
            return a["metadata"]["name"] < b["metadata"]["name"]

        best = None
        for other in self.reader.list(KIND):
            meta = other.get("metadata", {})
            if (meta.get("namespace", "default"), meta.get("name")) == me:
                continue
            if other.get("status", {}).get("phase") != "Running":
                continue
            if job_priority(other) >= priority:
                continue  # the invariant: never equal-or-higher
            if not holds_chips(other):
                continue  # display-Running, chip-less: nothing to free
            if best is None or prefer(other, best):
                best = other
        return best

    def _maybe_preempt(self, job: Dict[str, Any],
                       priority: int) -> bool:
        """One preemption decision for a deadline-pressured
        high-priority Pending gang: pick the single victim and
        consume the global rate-limit token. Shrink-first (r16): an
        elastic victim above its minReplicas is SHRUNK one worker
        (GangShrunk + Resizing conditions, Warning Event, gang rolled
        to the smaller size — it keeps Running) instead of killed;
        only rigid victims (or elastic ones already at min) get the
        r12 teardown (Preempted condition + Warning Event, no restart
        budget burned). Both actions share the rate limiter and the
        PreemptedVictim one-per-episode latch."""
        name = job["metadata"]["name"]
        ns = job["metadata"].get("namespace", "default")
        self.preemption.eligible += 1
        victim = self._select_victim(job, priority)
        if victim is None:
            self.preemption.no_victim += 1
            return False
        token = self.preemption.try_acquire()
        if token is None:
            return False  # rate-limited: re-observed at requeue/relist
        vmeta = victim["metadata"]
        vns = vmeta.get("namespace", "default")
        vname = vmeta["name"]
        vpriority = job_priority(victim)
        restarts = int(victim.get("status", {}).get("restartCount", 0))
        if _shrinkable(victim):
            return self._shrink_victim(job, victim, token, priority)
        logger.warning(
            "preempting %s/%s (priority %d) for %s/%s (priority %d)",
            vns, vname, vpriority, ns, name, priority)
        detail = (f"preempted by higher-priority {ns}/{name} "
                  f"(priority {vpriority} < {priority})")
        # Status BEFORE teardown, preconditioned on the victim still
        # being the gang we decided to evict: the cache read may
        # trail the server, and a victim that meanwhile Succeeded (or
        # Failed, or was itself preempted) must NOT be flipped back
        # to Restarting and rerun. A lost optimistic-concurrency race
        # or a moved phase aborts the whole decision — never delete a
        # gang the record doesn't mark Preempted.
        try:
            self._set_status(
                victim, "Restarting", restart_count=restarts,
                reason=f"{detail}; gang torn down, restart budget "
                       f"{restarts}/{self.max_restarts} unchanged",
                extra_condition=(PREEMPTED_CONDITION, detail),
                event_reason=PREEMPTED_CONDITION,
                require_phase="Running")
        except (Conflict, _StateMoved) as err:
            # Nothing was evicted: hand the interval token back so
            # the retry (or another starving gang) isn't refused for
            # a preemption that never happened.
            self.preemption.rollback(token)
            logger.info("preemption of %s/%s aborted (%s); "
                        "will re-evaluate", vns, vname,
                        type(err).__name__)
            return False
        self.preemption.commit()
        for m in expected_members(victim):
            try:
                self.api.delete("Pod", vns, m.pod_name(vname))
            except NotFound:
                pass
        self._record_preemptor_latch(
            job, f"preempted {vns}/{vname} "
                 f"(priority {vpriority} < {priority})")
        return True

    def _shrink_victim(self, job: Dict[str, Any],
                       victim: Dict[str, Any], token: float,
                       priority: int) -> bool:
        """The shrink-first action: take one worker off an elastic
        victim (currentReplicas - 1, never below min) and roll its
        gang to the smaller size — it keeps Running. Status lands
        BEFORE teardown with the same phase precondition as the kill
        path; an aborted write refunds the rate-limit token."""
        name = job["metadata"]["name"]
        ns = job["metadata"].get("namespace", "default")
        vmeta = victim["metadata"]
        vns = vmeta.get("namespace", "default")
        vname = vmeta["name"]
        vpriority = job_priority(victim)
        current = elastic_current_replicas(victim)
        bounds = job_elastic_bounds(victim)
        assert current is not None and bounds is not None
        new_size = max(bounds[0], current - 1)
        vrestarts = int(victim.get("status", {}).get("restartCount", 0))
        logger.warning(
            "shrinking %s/%s (priority %d) %d -> %d for %s/%s "
            "(priority %d)", vns, vname, vpriority, current, new_size,
            ns, name, priority)
        detail = (f"shrunk {current} -> {new_size} workers by "
                  f"higher-priority {ns}/{name} "
                  f"(priority {vpriority} < {priority}; "
                  f"minReplicas={bounds[0]})")
        try:
            self._set_status(
                victim, "Running", restart_count=vrestarts,
                reason=f"{detail}; gang rolling to {new_size} workers",
                extra_condition=[(SHRUNK_CONDITION, detail),
                                 (RESIZING_CONDITION, detail)],
                require_phase="Running",
                current_replicas=new_size, stamp_resize=True)
        except (Conflict, _StateMoved) as err:
            self.preemption.rollback(token)
            logger.info("shrink of %s/%s aborted (%s); will "
                        "re-evaluate", vns, vname, type(err).__name__)
            return False
        self.preemption.commit()
        self.preemption.shrunk += 1
        self._count_resize("shrink")
        # Warning Event on the victim (its phase stayed Running, so
        # the transition emitter is quiet).
        self._record_event(victim, f"{vname}.gangshrunk.{new_size}",
                           SHRUNK_CONDITION, f"TPUJob {detail}",
                           "Warning")
        # Tear the WHOLE old gang down (every surviving worker's env
        # must roll to the new size); the victim's own reconcile
        # recreates new_size pods. List-based teardown:
        # expected_members(victim) already reflects the NEW size and
        # would strand the highest old index.
        try:
            old_pods = self.reader.list("Pod", vns,
                                        {JOB_LABEL: vname})
        except Exception:  # noqa: BLE001 — the victim's own resize
            old_pods = []  # hold re-drives any missed teardown
        for pod in old_pods:
            try:
                self.api.delete("Pod", vns, pod["metadata"]["name"])
            except NotFound:
                pass
        self._record_preemptor_latch(
            job, f"shrank {vns}/{vname} to {new_size} workers "
                 f"(priority {vpriority} < {priority})")
        return True

    def _record_preemptor_latch(self, job: Dict[str, Any],
                                record: str) -> None:
        """The preemptor's side of the record, written DURABLY before
        the pass continues: the PreemptedVictim latch is what
        enforces one-victim-per-Pending-episode (kill AND shrink), so
        it must land even if the pass's own final status write later
        loses a race (a lost latch would evict a second victim on
        retry). Conflict-retried — read-modify-write converges."""
        name = job["metadata"]["name"]
        ns = job["metadata"].get("namespace", "default")
        for attempt in range(3):
            try:
                self.api.patch(
                    KIND, ns, name,
                    lambda o: _set_extra_condition(
                        o.setdefault("status", {}),
                        PREEMPTOR_CONDITION, "True", record))
                break
            except Conflict:
                if attempt == 2:
                    logger.warning(
                        "PreemptedVictim latch for %s/%s kept "
                        "losing races; the episode may preempt "
                        "again after the rate-limit interval",
                        ns, name)
            except NotFound:
                break  # preemptor deleted mid-pass
        self._record_event(job, f"{name}.preemptedvictim",
                           PREEMPTOR_CONDITION,
                           f"TPUJob {record} to make room for this "
                           f"gang", "Normal")

    # -- quarantine surface (driven by the watch controller) --------------

    def mark_stalled(self, namespace: str, name: str,
                     failures: int) -> None:
        """Surface a poison job: ReconcileStalled condition + Warning
        Event. Called by the controller when a key crosses the
        quarantine threshold; exceptions propagate (the caller treats
        this write as best-effort and retries at the capped
        interval)."""
        reason = (f"{failures} consecutive reconcile failures; "
                  f"retrying at the backoff cap")
        try:
            job = self.api.get(KIND, namespace, name)
        except NotFound:
            return
        self.api.patch(
            KIND, namespace, name,
            lambda o: _set_extra_condition(
                o.setdefault("status", {}), STALLED_CONDITION,
                "True", reason))
        # best_effort=False: a transient 429/500 on the Event create
        # propagates, so the caller's not-yet-latched bookkeeping
        # retries BOTH writes at the next capped attempt (the
        # condition patch is a no-op by then) — otherwise the Warning
        # Event is silently lost forever the one time the apiserver
        # sheds it.
        self._record_event(
            job, f"{name}.reconcilestalled", STALLED_CONDITION,
            f"TPUJob reconcile stalled: {reason}", "Warning",
            best_effort=False)

    def clear_stalled(self, namespace: str, name: str) -> None:
        """Reconcile succeeded again: flip ReconcileStalled to False
        (only if it was materialized)."""

        def mutate(obj: Dict[str, Any]) -> None:
            status = obj.get("status", {})
            if any(c.get("type") == STALLED_CONDITION
                   for c in status.get("conditions", [])):
                _set_extra_condition(status, STALLED_CONDITION,
                                     "False", "reconcile recovered")

        try:
            self.api.patch(KIND, namespace, name, mutate)
        except NotFound:
            pass

    def _record_event(self, job: Dict[str, Any], event_name: str,
                      reason: str, message: str,
                      event_type: str, *,
                      best_effort: bool = True) -> None:
        """Create-or-aggregate one k8s Event. Best-effort by default:
        an event that can't be written must never fail the reconcile
        pass. ``best_effort=False`` re-raises TRANSIENT failures
        (429/5xx) so a caller with retry machinery can re-attempt
        delivery. The deterministic name makes retries of the same
        transition dedupe via Conflict instead of piling up."""
        name = job["metadata"]["name"]
        ns = job["metadata"].get("namespace", "default")
        now = datetime.datetime.now(
            datetime.timezone.utc).isoformat()
        event = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {
                "name": event_name,
                "namespace": ns,
            },
            "involvedObject": {
                "apiVersion": f"{GROUP}/{VERSION}",
                "kind": KIND,
                "name": name,
                "namespace": ns,
                "uid": job["metadata"].get("uid", ""),
            },
            "reason": reason,
            "message": message,
            "type": event_type,
            "source": {"component": "tpujob-operator"},
            "firstTimestamp": now,
            "lastTimestamp": now,
            "count": 1,
        }
        uid = job["metadata"].get("uid", "")
        try:
            self.api.create(event)
        except Conflict:
            # Same transition recorded before. If it belongs to THIS
            # job incarnation, bump the aggregate count k8s-style; if
            # it's a leftover from a deleted same-name job (event TTL
            # outlives the object), record under a uid-suffixed name —
            # kubectl describe filters by involvedObject.uid, so the
            # new job must get its own event.
            try:
                existing = self.api.get("Event", ns,
                                        event["metadata"]["name"])
                if existing.get("involvedObject", {}).get("uid") == uid:
                    self.api.patch(
                        "Event", ns, event["metadata"]["name"],
                        lambda o: o.update({
                            "count": int(o.get("count", 1)) + 1,
                            "lastTimestamp": now,
                        }))
                else:
                    event["metadata"]["name"] += f".{uid[:8]}"
                    self.api.create(event)
            except Exception:  # noqa: BLE001
                pass
        except (TooManyRequests, ServerError):
            if not best_effort:
                raise
            logger.exception("event emission failed for %s/%s", ns, name)
        except Exception:  # noqa: BLE001 — events are best-effort
            logger.exception("event emission failed for %s/%s", ns, name)

    def _emit_event(self, job: Dict[str, Any], phase: str,
                    restart_count: int, reason: Optional[str],
                    event_reason: Optional[str] = None) -> None:
        """One k8s Event per phase transition (the tf-operator
        recorded lifecycle events; `kubectl describe tpujob` shows
        these). Name carries the phase + restart count so retries of
        the same transition aggregate. ``event_reason`` overrides the
        Event's reason field (e.g. DeadlineExceeded) while the name
        stays phase-keyed."""
        name = job["metadata"]["name"]
        self._record_event(
            job, f"{name}.{phase.lower()}.r{restart_count}",
            event_reason or phase,
            reason or f"TPUJob entered phase {phase}",
            "Warning" if phase in ("Restarting", "Failed") else "Normal")

    def _set_status(self, job: Dict[str, Any], phase: str, *,
                    restart_count: int = 0,
                    completion_skew: int = 0,
                    reason: Optional[str] = None,
                    extra_condition: Optional[Any] = None,
                    event_reason: Optional[str] = None,
                    pods_running: bool = False,
                    require_phase: Optional[str] = None,
                    current_replicas: Optional[int] = None,
                    stamp_resize: bool = False,
                    gang_complete: bool = False,
                    scheduling_pending: Optional[bool] = None) -> str:
        """``extra_condition`` is one (type, reason) tuple or a list
        of them (a preemptor shrink writes GangShrunk AND Resizing in
        the same pass). ``current_replicas`` writes the elastic gang
        size; ``stamp_resize`` stamps ``status.lastResizeTime`` (the
        admission-shrink pacing anchor). ``scheduling_pending`` True
        anchors ``status.schedulingSince`` (set-if-absent), False
        clears it, None leaves it alone — the stall-deadline clock."""
        name = job["metadata"]["name"]
        ns = job["metadata"].get("namespace", "default")
        previous_phase = job.get("status", {}).get("phase")
        extra_conditions = ([] if extra_condition is None
                            else [extra_condition]
                            if isinstance(extra_condition, tuple)
                            else list(extra_condition))
        desired_workers = _desired_workers(job)

        def mutate(obj):
            status = obj.setdefault("status", {})
            if (require_phase is not None
                    and status.get("phase", "Pending")
                    != require_phase):
                # Precondition check BEFORE any mutation: the write
                # was decided against a (possibly stale) read; if the
                # server object has moved on, abort cleanly on every
                # client (cross-job writes like preemption must never
                # stomp an advanced state).
                raise _StateMoved(
                    f"{ns}/{name} is {status.get('phase')!r}, "
                    f"decision required {require_phase!r}")
            status["phase"] = phase
            status["restartCount"] = restart_count
            # Any non-hold decision resets the skew counter (writes 0).
            status["completionSkewPasses"] = completion_skew
            if current_replicas is not None:
                status["currentReplicas"] = current_replicas
            if stamp_resize:
                status["lastResizeTime"] = datetime.datetime.now(
                    datetime.timezone.utc).isoformat()
                # New generation: pods created from here on belong to
                # the resized gang; anything older is a stale roll
                # target (see the reconcile resize hold).
                status["resizeGeneration"] = (
                    _resize_generation(status) + 1)
            if scheduling_pending is True:
                status.setdefault(
                    "schedulingSince",
                    datetime.datetime.now(
                        datetime.timezone.utc).isoformat())
            elif scheduling_pending is False:
                status.pop("schedulingSince", None)
            if reason:
                status["reason"] = reason
            else:
                # A reason describes THIS phase only: a recovered job
                # must not carry a stale 'slice fault' into Succeeded.
                status.pop("reason", None)
            _update_conditions(status, phase, reason)
            for cond_type, cond_reason in extra_conditions:
                _set_extra_condition(status, cond_type,
                                     "True", cond_reason)
            # Any completed pass IS recovery from a reconcile stall:
            # clear the condition from apiserver state here (not from
            # the controller's memory of having set it — that memory
            # dies with the process, and a job must not wear a stale
            # ReconcileStalled banner across operator restarts or
            # leader failovers).
            if any(c.get("type") == STALLED_CONDITION
                   and c.get("status") == "True"
                   for c in status.get("conditions", [])):
                _set_extra_condition(status, STALLED_CONDITION,
                                     "False", "reconcile recovered")
            # A preempted gang whose pods ACTUALLY run again has
            # rescheduled: lift the Preempted banner (it is an alert,
            # not a biography). Pod truth, not the phase — a
            # recreated-but-unschedulable gang reads phase Running by
            # the post-restart display convention while its pods sit
            # Pending, and ITS banner must stay up. Same for the
            # preemptor's PreemptedVictim latch — clearing it re-arms
            # preemption for a future Pending episode; the Events
            # keep history.
            if pods_running:
                for cond_type, note in (
                        (PREEMPTED_CONDITION,
                         "rescheduled after preemption"),
                        (PREEMPTOR_CONDITION,
                         "scheduled; victim record retired")):
                    if any(c.get("type") == cond_type
                           and c.get("status") == "True"
                           for c in status.get("conditions", [])):
                        _set_extra_condition(status, cond_type,
                                             "False", note)
                # Elastic resize settle: retire Resizing and record
                # Resized only once the WHOLE rolled gang runs (one
                # pod up is not a formed collective — and a partial
                # gang must stay in the stall machinery's sights).
                # GangShrunk stays up while the gang runs BELOW its
                # desired size (the dashboard's shrink banner) and
                # lifts only once a restart grew it back to full.
                if (gang_complete
                        and _condition_true(status, RESIZING_CONDITION)):
                    size = status.get("currentReplicas")
                    _set_extra_condition(
                        status, RESIZING_CONDITION, "False",
                        "resize complete")
                    _set_extra_condition(
                        status, RESIZED_CONDITION, "True",
                        f"gang running at {size} workers")
                if (gang_complete
                        and _condition_true(status, SHRUNK_CONDITION)):
                    try:
                        size = int(status.get("currentReplicas",
                                              desired_workers))
                    except (TypeError, ValueError):
                        size = desired_workers
                    if size >= desired_workers:
                        _set_extra_condition(
                            status, SHRUNK_CONDITION, "False",
                            "gang restored to desired size")

        # Steady-state suppression: if the mutation would change
        # nothing, skip the apiserver round trip entirely. The fake
        # already suppressed no-change PUTs server-side; doing it
        # client-side keeps a converged fleet's write QPS at ZERO
        # (with informer reads, a steady-state reconcile then touches
        # the apiserver not at all). Bounded-staleness caveat: `job`
        # may trail the server by the watch latency — a skipped write
        # is re-evaluated on the next event/relist, which is exactly
        # the level-triggered contract.
        probe = copy.deepcopy(job)
        mutate(probe)
        if probe == job:
            return phase

        try:
            self.api.patch(KIND, ns, name, mutate)
        except NotFound:
            # Job object deleted mid-pass: nothing to record — and no
            # Event either (an event for a nonexistent job would
            # orphan in the namespace until its TTL).
            mutate(job)
            return phase
        mutate(job)
        if phase != previous_phase:
            self._emit_event(job, phase, restart_count, reason,
                             event_reason)
        return phase
