# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Lease-based leader election for the TPUJob controller.

Two controller replicas (rolling updates overlap even at replicas=1)
must not reconcile the same jobs concurrently — the Conflict-tolerant
create/patch paths keep that SAFE, but every race costs a wasted pass
and a retry. The reference's Go operator got election from
client-go's leaderelection package (resource-lock contention); this is
the same protocol on ``coordination.k8s.io/v1 Lease`` objects through
whichever apiserver client the controller runs with (fake, kubectl,
or the stdlib HTTP client):

- acquire: create the Lease (Conflict → someone else holds it), or
  take over when ``renewTime + leaseDurationSeconds`` has passed;
- renew: re-write ``renewTime`` under optimistic concurrency — a
  Conflict means another holder won, and leadership is dropped
  immediately (never assume leadership through a failed write);
- followers re-check at ``retry_seconds``; the controller only
  reconciles while ``is_leader()``.
"""

from __future__ import annotations

import datetime
import logging
import os
import threading
from typing import Any, Dict, Optional

from kubeflow_tpu.operator.fake import Conflict, NotFound

logger = logging.getLogger(__name__)

LEASE_API_VERSION = "coordination.k8s.io/v1"


class _LostRace(Exception):
    """Raised inside the patch mutation when the freshly-read lease is
    held live by someone else — the read-modify-write client re-reads
    the object, so the _tick-time holder check alone is a TOCTOU."""


def _now() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def default_identity() -> str:
    return f"{os.environ.get('HOSTNAME', 'tpujob-operator')}_{os.getpid()}"


class LeaderElector:
    """Run :meth:`loop` in a thread; gate work on :meth:`is_leader`."""

    # Consecutive lease-path ERRORS (not lost races — real apiserver
    # failures like an unmapped 403 from stale RBAC) before the
    # elector declares itself broken. Followership is a normal state,
    # so an elector that can never even TALK to the lease must not
    # masquerade as a follower forever — that is a silent outage.
    MAX_CONSECUTIVE_ERRORS = 20

    def __init__(self, api, *, namespace: str = "default",
                 name: str = "tpujob-operator",
                 identity: Optional[str] = None,
                 lease_seconds: float = 15.0,
                 retry_seconds: Optional[float] = None):
        self.api = api
        self.namespace = namespace
        self.name = name
        self.identity = identity or default_identity()
        self.lease_seconds = lease_seconds
        self.retry_seconds = retry_seconds or max(lease_seconds / 3, 0.05)
        self.stop = threading.Event()
        self._leader = threading.Event()
        # Set when the lease path errored MAX_CONSECUTIVE_ERRORS times
        # in a row; the controller treats it as fatal (crash-loop the
        # pod — visible — instead of idling forever).
        self.broken = threading.Event()

    def is_leader(self) -> bool:
        return self._leader.is_set()

    # -- lease protocol ---------------------------------------------------

    def _lease_body(self, transitions: int) -> Dict[str, Any]:
        now = _now().isoformat()
        return {
            "apiVersion": LEASE_API_VERSION,
            "kind": "Lease",
            "metadata": {"name": self.name, "namespace": self.namespace},
            "spec": {
                "holderIdentity": self.identity,
                "leaseDurationSeconds": int(self.lease_seconds) or 1,
                "acquireTime": now,
                "renewTime": now,
                "leaseTransitions": transitions,
            },
        }

    @staticmethod
    def _expired(spec: Dict[str, Any]) -> bool:
        renew = spec.get("renewTime")
        if not renew:
            return True
        try:
            # client-go writes RFC3339 with a trailing 'Z', which
            # Python 3.10's fromisoformat (this package's floor)
            # rejects — and "unparseable" means "expired", i.e. a LIVE
            # Go-held lease would be stolen every tick (two leaders).
            # Map it to the +00:00 spelling 3.10 accepts.
            if isinstance(renew, str) and renew.endswith(("Z", "z")):
                renew = renew[:-1] + "+00:00"
            renewed = datetime.datetime.fromisoformat(renew)
        except (TypeError, ValueError):
            # Unparseable renewTime (or a non-string) = no live renewal.
            return True
        if renewed.tzinfo is None:
            # Non-Python holders (client-go writes RFC3339, but other
            # writers exist) may store an offset-less timestamp; k8s
            # times are UTC by convention. Normalize instead of letting
            # the aware-vs-naive comparison raise TypeError below —
            # which the loop would count toward MAX_CONSECUTIVE_ERRORS
            # and eventually declare the elector broken over a peer's
            # formatting.
            renewed = renewed.replace(tzinfo=datetime.timezone.utc)
        duration = float(spec.get("leaseDurationSeconds", 15))
        return _now() >= renewed + datetime.timedelta(seconds=duration)

    def _tick(self) -> bool:
        """One acquire-or-renew attempt; returns leadership."""
        try:
            lease = self.api.get("Lease", self.namespace, self.name)
        except NotFound:
            try:
                self.api.create(self._lease_body(transitions=0))
                logger.info("lease %s acquired by %s (created)",
                            self.name, self.identity)
                return True
            except Conflict:
                return False  # lost the create race
        spec = lease.get("spec", {})
        holder = spec.get("holderIdentity")
        if holder != self.identity and not self._expired(spec):
            return False  # someone else holds a live lease

        def take(obj: Dict[str, Any]) -> None:
            s = obj.setdefault("spec", {})
            # Re-validate against the object the PATCH actually read:
            # the client is read-modify-write, so between _tick's GET
            # and this mutation another elector may have renewed or
            # taken over (r5 review: without this, an expired-then-
            # renewed lease could be overwritten and two leaders
            # coexist for a retry period). Raising BEFORE any mutation
            # aborts the write cleanly on every client.
            current = s.get("holderIdentity")
            if (current and current != self.identity
                    and not self._expired(s)):
                raise _LostRace(current)
            now = _now().isoformat()
            if current != self.identity:
                s["leaseTransitions"] = int(
                    s.get("leaseTransitions", 0)) + 1
                s["acquireTime"] = now
            s["holderIdentity"] = self.identity
            s["leaseDurationSeconds"] = int(self.lease_seconds) or 1
            s["renewTime"] = now

        try:
            self.api.patch("Lease", self.namespace, self.name, take)
        except (_LostRace, Conflict, NotFound):
            # A concurrent writer won (or the lease vanished): NEVER
            # keep leadership through a failed renewal.
            return False
        if holder != self.identity:
            logger.info("lease %s taken over by %s (was %s)",
                        self.name, self.identity, holder)
        return True

    # -- loop -------------------------------------------------------------

    def loop(self) -> None:
        errors = 0
        while not self.stop.is_set():
            try:
                leading = self._tick()
                errors = 0
            except Exception:  # noqa: BLE001 — apiserver hiccup
                logger.exception("lease tick failed")
                leading = False
                errors += 1
                if errors >= self.MAX_CONSECUTIVE_ERRORS:
                    logger.critical(
                        "lease path failed %d consecutive times "
                        "(RBAC for coordination.k8s.io/leases "
                        "missing?); declaring the elector broken",
                        errors)
                    self._leader.clear()
                    self.broken.set()
                    return
            was = self._leader.is_set()
            if leading and not was:
                self._leader.set()
            elif not leading and was:
                logger.warning("lease %s lost by %s", self.name,
                               self.identity)
                self._leader.clear()
            self.stop.wait(self.retry_seconds)
        # On clean shutdown, release so a peer takes over immediately
        # instead of waiting out the lease duration.
        if self._leader.is_set():
            self._leader.clear()

            def release(obj: Dict[str, Any]) -> None:
                # Guarded like take(): leadership may have been lost
                # between the last tick and shutdown (lease expired, a
                # peer took over) — releasing unconditionally would
                # zero the LIVE peer's lease and hand a second
                # follower an instant takeover (brief two-leader
                # window). Raising before any mutation aborts the
                # write cleanly on every client.
                s = obj.setdefault("spec", {})
                if s.get("holderIdentity") != self.identity:
                    raise _LostRace(s.get("holderIdentity"))
                s["holderIdentity"] = ""
                s["renewTime"] = None

            try:
                self.api.patch("Lease", self.namespace, self.name,
                               release)
            except Exception:  # noqa: BLE001 — best-effort release
                pass
