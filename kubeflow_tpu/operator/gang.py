# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Gang decision kernel binding (C++ kft_gang_decide via ctypes)."""

from __future__ import annotations

import ctypes
import enum
from typing import Optional, Sequence

from kubeflow_tpu.serving._native import _LIB  # shared runtime library


class PodPhase(enum.IntEnum):
    MISSING = 0
    PENDING = 1
    RUNNING = 2
    SUCCEEDED = 3
    FAILED = 4

    @staticmethod
    def from_k8s(phase: Optional[str]) -> "PodPhase":
        return {
            None: PodPhase.MISSING,
            "Pending": PodPhase.PENDING,
            "Running": PodPhase.RUNNING,
            "Succeeded": PodPhase.SUCCEEDED,
            "Failed": PodPhase.FAILED,
            # Unknown node → treat as failed: the slice collective is
            # broken either way.
            "Unknown": PodPhase.FAILED,
        }[phase]


class Decision(enum.IntEnum):
    NONE = 0
    CREATE_MISSING = 1
    RESTART_SLICE = 2
    SUCCEED = 3
    FAIL = 4
    # Non-chief Succeeded while the chief is still non-terminal and no
    # pod Failed: pod-status propagation skew on a normally-finishing
    # job looks exactly like this, so re-observe instead of burning a
    # slice restart. The reconciler counts consecutive holds and
    # passes completion_grace=False once patience runs out.
    HOLD_COMPLETION = 5


if _LIB is not None:
    _LIB.kft_gang_decide.restype = ctypes.c_int
    _LIB.kft_gang_decide.argtypes = [
        ctypes.POINTER(ctypes.c_int), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
    ]


def decide(phases: Sequence[PodPhase], chief_index: int, *,
           allow_restart: bool, restarts: int,
           max_restarts: int, completion_grace: bool = True) -> Decision:
    """Native gang decision; Python mirror if the .so isn't built."""
    if _LIB is not None:
        arr = (ctypes.c_int * len(phases))(*[int(p) for p in phases])
        return Decision(_LIB.kft_gang_decide(
            arr, len(phases), chief_index, int(allow_restart), restarts,
            max_restarts, int(completion_grace)))
    # Pure-Python mirror of native/kft_runtime.cc kft_gang_decide.
    if not phases or not (0 <= chief_index < len(phases)):
        return Decision.FAIL
    if phases[chief_index] == PodPhase.SUCCEEDED:
        return Decision.SUCCEED
    any_failed = any(p == PodPhase.FAILED for p in phases)
    nonchief_succeeded = any(
        i != chief_index and p == PodPhase.SUCCEEDED
        for i, p in enumerate(phases))
    if nonchief_succeeded and not any_failed and completion_grace:
        return Decision.HOLD_COMPLETION
    if any_failed or nonchief_succeeded:
        if allow_restart and restarts < max_restarts:
            return Decision.RESTART_SLICE
        return Decision.FAIL
    if any(p == PodPhase.MISSING for p in phases):
        return Decision.CREATE_MISSING
    return Decision.NONE
