# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Rate-limited workqueue — client-go semantics for the controller.

The r6 controller retried a failing job at a flat 0.5 s forever from
one worker thread: a poison job (say, a status endpoint that always
500s) hot-looped the apiserver at 2 QPS per job, and every retry
blocked every other job's reconcile. This module is the sanctioned
wait path for the operator (scripts/lint.py enforces that no other
``time.sleep``/except-block ``wait`` exists under
``kubeflow_tpu/operator/``):

- :class:`WorkQueue` — per-key deduplication (an enqueued key is held
  once however many events name it; a key being processed is never
  handed to a second worker — it is marked dirty and re-queued on
  ``done``), a delay heap for backoff-scheduled retries, and
  enqueue→dequeue latency sampling for the load benchmark.
- :class:`ExponentialBackoff` — per-key failure counts mapped to
  jittered exponential delays (base ~50 ms doubling to a cap of
  ~5 min), reset on success via :meth:`WorkQueue.forget`.
- :class:`TokenBucket` — the global limiter: however many workers and
  however deep the queue, reconcile admission never exceeds
  ``qps`` sustained (``burst`` headroom for event storms).

Quarantine is a threshold on the same failure counter: once a key
fails ``quarantine_after`` consecutive times it parks at the cap
interval (the controller additionally surfaces a ``ReconcileStalled``
condition + Event). One success forgets everything.

Modeled on client-go's ``workqueue`` package (the reference operator
consumed it via the informer machinery); "Runtime Concurrency Control
and Operation Scheduling" (PAPERS.md) motivates prioritized,
rate-limited scheduling over naive FIFO retry.
"""

from __future__ import annotations

import collections
import heapq
import random
import threading
import time
from typing import Any, Dict, Hashable, List, Optional

__all__ = ["ExponentialBackoff", "TokenBucket", "WorkQueue"]


class ExponentialBackoff:
    """failures → jittered delay: ``base * 2**(failures-1)``, capped.

    Jitter is a symmetric ±``jitter`` fraction — a conflict storm that
    fails N jobs in the same pass must not re-dispatch them as one
    synchronized thundering herd at every subsequent power of two.
    """

    def __init__(self, base: float = 0.05, cap: float = 300.0,
                 jitter: float = 0.2,
                 rng: Optional[random.Random] = None):
        if base <= 0 or cap < base:
            raise ValueError(f"need 0 < base <= cap, got {base}, {cap}")
        self.base = base
        self.cap = cap
        self.jitter = jitter
        self._rng = rng or random.Random()

    def delay(self, failures: int) -> float:
        """Delay before retry number ``failures`` (1-based)."""
        if failures <= 0:
            return 0.0
        # Exponent bounded before the multiply: 2**large is bignum-
        # slow and pointless past the cap.
        exp = min(failures - 1, 32)
        raw = min(self.cap, self.base * (2.0 ** exp))
        if not self.jitter:
            return raw
        spread = self._rng.uniform(-self.jitter, self.jitter)
        return max(self.base, raw * (1.0 + spread))


class TokenBucket:
    """Global reconcile-admission limiter (``qps`` sustained,
    ``burst`` instantaneous). ``acquire`` blocks until a token or the
    stop event; it never busy-waits — the wait is exactly the refill
    deficit."""

    def __init__(self, qps: float = 50.0, burst: int = 100,
                 clock=time.monotonic):
        if qps <= 0 or burst < 1:
            raise ValueError(f"need qps > 0, burst >= 1: {qps}, {burst}")
        self.qps = qps
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._last = clock()
        self._lock = threading.Lock()

    def _refill_locked(self) -> None:
        now = self._clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.qps)
        self._last = now

    def try_acquire(self) -> bool:
        with self._lock:
            self._refill_locked()
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def acquire(self, stop: Optional[threading.Event] = None,
                timeout: Optional[float] = None) -> bool:
        deadline = None if timeout is None else self._clock() + timeout
        while True:
            with self._lock:
                self._refill_locked()
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return True
                need = (1.0 - self._tokens) / self.qps
            if deadline is not None:
                remaining = deadline - self._clock()
                if remaining <= 0:
                    return False
                need = min(need, remaining)
            if stop is not None:
                if stop.wait(need):
                    return False
            else:
                time.sleep(need)


class WorkQueue:
    """Deduplicating delay queue with per-key failure accounting.

    Lifecycle per key (client-go semantics):

    - :meth:`add` — enqueue, deduplicated. If the key is mid-process
      it is marked dirty and re-queued when the worker calls ``done``
      (the same job is never reconciled concurrently, and an event
      arriving mid-pass is never lost).
    - :meth:`get` — block for a ready key, mark it processing.
    - :meth:`done` — processing finished (success or not); re-adds if
      dirty.
    - :meth:`retry` — record one failure, schedule the key after its
      backoff delay (cap interval once quarantined), return the delay.
    - :meth:`forget` — success: zero the failure count, lift
      quarantine.
    """

    #: enqueue→dequeue latency samples kept for the load benchmark.
    LATENCY_WINDOW = 4096

    def __init__(self, *, backoff: Optional[ExponentialBackoff] = None,
                 limiter: Optional[TokenBucket] = None,
                 quarantine_after: int = 6,
                 clock=time.monotonic):
        if quarantine_after < 1:
            raise ValueError("quarantine_after must be >= 1")
        self.backoff = backoff or ExponentialBackoff()
        self.limiter = limiter
        self.quarantine_after = quarantine_after
        self._clock = clock
        self._cond = threading.Condition()
        self._ready: collections.deque = collections.deque()
        self._ready_set: set = set()
        self._processing: set = set()
        self._dirty: set = set()
        # Delay heap: (due, seq, key). A key may appear multiple
        # times; the earliest due wins, later entries are skipped via
        # _delayed_due bookkeeping.
        self._heap: List[Any] = []
        self._delayed_due: Dict[Hashable, float] = {}
        self._seq = 0
        self._failures: Dict[Hashable, int] = {}
        self._enqueued_at: Dict[Hashable, float] = {}
        self._latencies: collections.deque = collections.deque(
            maxlen=self.LATENCY_WINDOW)
        # Counters for the stats surface.
        self._adds = 0
        self._gets = 0
        self._retries = 0

    # -- enqueue ----------------------------------------------------------

    def add(self, key: Hashable) -> None:
        with self._cond:
            self._add_locked(key)

    def _add_locked(self, key: Hashable, track: bool = True) -> None:
        self._adds += 1
        if key in self._processing:
            self._dirty.add(key)
            return
        if key in self._ready_set:
            return
        # An explicit add supersedes any scheduled retry of the same
        # key: events beat timers.
        self._delayed_due.pop(key, None)
        self._ready.append(key)
        self._ready_set.add(key)
        if track:
            # Latency sampling is EVENT-path only (track=False on
            # relist sweeps): "event→reconcile latency" must measure
            # reaction to new information, not the amortized drain of
            # a level-triggered sweep that enqueues the whole fleet.
            self._enqueued_at.setdefault(key, self._clock())
        self._cond.notify()

    def add_unless_delayed(self, key: Hashable) -> None:
        """Relist semantics: enqueue unless the key is already backing
        off. A watch event carries new information and supersedes
        backoff (plain :meth:`add`); a periodic relist carries none —
        re-admitting a parked poison job every relist period would
        defeat quarantine. That includes a failing key whose capped
        attempt is mid-flight (its timer entry is consumed while it
        processes): marking it dirty here would make ``done`` cancel
        the retry the attempt is about to schedule and re-admit the
        key immediately — one unthrottled extra attempt per relist."""
        with self._cond:
            if key in self._delayed_due:
                return
            if key in self._processing and self._failures.get(key, 0):
                return  # its own retry/forget will decide what's next
            self._add_locked(key, track=False)

    def add_after(self, key: Hashable, delay: float) -> None:
        if delay <= 0:
            return self.add(key)
        with self._cond:
            due = self._clock() + delay
            held = self._delayed_due.get(key)
            if held is not None and held <= due:
                return  # an earlier retry is already scheduled
            self._delayed_due[key] = due
            self._seq += 1
            heapq.heappush(self._heap, (due, self._seq, key))
            self._cond.notify()

    # -- dequeue ----------------------------------------------------------

    def _promote_due_locked(self) -> Optional[float]:
        """Move due delayed keys to ready; return seconds until the
        next due key (None if the heap is drained)."""
        now = self._clock()
        while self._heap:
            due, _, key = self._heap[0]
            held = self._delayed_due.get(key)
            if held is None or held != due:
                heapq.heappop(self._heap)  # superseded entry
                continue
            if due > now:
                return due - now
            heapq.heappop(self._heap)
            del self._delayed_due[key]
            if key in self._processing:
                self._dirty.add(key)
            elif key not in self._ready_set:
                self._ready.append(key)
                self._ready_set.add(key)
                self._enqueued_at.setdefault(key, now)
        return None

    def get(self, timeout: Optional[float] = None,
            stop: Optional[threading.Event] = None) -> Optional[Hashable]:
        """Next ready key (marked processing), or None on timeout/stop.

        Admission is limited by the global token bucket: the key is
        only returned once a token is held. If the bucket can't admit
        within the timeout the key stays queued for the next call."""
        deadline = (None if timeout is None
                    else self._clock() + max(0.0, timeout))
        key = None
        with self._cond:
            while True:
                if stop is not None and stop.is_set():
                    return None
                next_due = self._promote_due_locked()
                if self._ready:
                    key = self._ready.popleft()
                    self._ready_set.discard(key)
                    self._processing.add(key)
                    self._gets += 1
                    started = self._enqueued_at.pop(key, None)
                    if started is not None:
                        self._latencies.append(self._clock() - started)
                    break
                wait = next_due
                if deadline is not None:
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        return None
                    wait = (remaining if wait is None
                            else min(wait, remaining))
                self._cond.wait(wait if wait is not None else 0.5)
        if self.limiter is not None:
            remaining = (None if deadline is None
                         else max(0.0, deadline - self._clock()))
            if not self.limiter.acquire(stop=stop, timeout=remaining):
                # No token in time: hand the key back for a later
                # get() instead of reconciling over budget.
                with self._cond:
                    self._processing.discard(key)
                    if key not in self._ready_set:
                        self._ready.appendleft(key)
                        self._ready_set.add(key)
                        self._enqueued_at.setdefault(key, self._clock())
                    self._cond.notify()
                return None
        return key

    def done(self, key: Hashable) -> None:
        with self._cond:
            self._processing.discard(key)
            if key in self._dirty:
                self._dirty.discard(key)
                self._add_locked(key)

    # -- failure accounting ----------------------------------------------

    def retry(self, key: Hashable) -> float:
        """Record one failure and schedule the retry; returns the
        delay. Quarantined keys park at the backoff cap exactly."""
        with self._cond:
            self._failures[key] = self._failures.get(key, 0) + 1
            failures = self._failures[key]
            self._retries += 1
        delay = (self.backoff.cap if failures >= self.quarantine_after
                 else self.backoff.delay(failures))
        self.add_after(key, delay)
        return delay

    def forget(self, key: Hashable) -> None:
        with self._cond:
            self._failures.pop(key, None)

    def failures(self, key: Hashable) -> int:
        with self._cond:
            return self._failures.get(key, 0)

    def is_quarantined(self, key: Hashable) -> bool:
        return self.failures(key) >= self.quarantine_after

    # -- introspection ----------------------------------------------------

    def counts(self) -> Dict[str, float]:
        """Scalar snapshot for the metrics surface — the numeric subset
        of :meth:`stats` without the per-key string maps (a /metrics
        scrape every few seconds must not build a dict per failing
        key)."""
        with self._cond:
            quarantined = sum(1 for v in self._failures.values()
                              if v >= self.quarantine_after)
            return {
                "depth": len(self._ready),
                "delayed": len(self._delayed_due),
                "processing": len(self._processing),
                "adds": self._adds,
                "gets": self._gets,
                "retries": self._retries,
                "quarantined": quarantined,
            }

    def latencies(self) -> List[float]:
        """Recent enqueue→dequeue latency samples (seconds)."""
        with self._cond:
            return list(self._latencies)

    def drain_latencies(self) -> List[float]:
        """Return AND clear the sample window — phase-segmented
        measurement (the scale bench drains before a churn wave so
        the churn percentiles can never be contaminated by converge
        backlog, wrapped window or not)."""
        with self._cond:
            out = list(self._latencies)
            self._latencies.clear()
            return out

    def latency_percentiles(self) -> Dict[str, float]:
        """p50/p90/p99 of the recent enqueue→dequeue window, in
        milliseconds — the event→reconcile latency the scale bench
        and the metrics ConfigMap report."""
        samples = sorted(self.latencies())
        if not samples:
            return {"p50": 0.0, "p90": 0.0, "p99": 0.0}

        def pct(p: float) -> float:
            idx = min(len(samples) - 1,
                      max(0, round(p / 100.0 * (len(samples) - 1))))
            return round(samples[idx] * 1e3, 2)

        return {"p50": pct(50), "p90": pct(90), "p99": pct(99)}

    def stats(self) -> Dict[str, Any]:
        """Snapshot for the metrics surface: depth, in-flight, per-key
        retry counts, per-key seconds-until-retry, quarantined keys,
        lifetime counters."""
        with self._cond:
            now = self._clock()
            return {
                "depth": len(self._ready),
                "delayed": len(self._delayed_due),
                "processing": len(self._processing),
                "adds": self._adds,
                "gets": self._gets,
                "retries": self._retries,
                "failing": {self._key_str(k): v
                            for k, v in self._failures.items()},
                "backoff": {self._key_str(k): round(max(0.0, due - now), 1)
                            for k, due in self._delayed_due.items()},
                "quarantined": sorted(
                    self._key_str(k) for k, v in self._failures.items()
                    if v >= self.quarantine_after),
            }

    @staticmethod
    def _key_str(key: Hashable) -> str:
        if isinstance(key, tuple):
            return "/".join(str(p) for p in key)
        return str(key)
