# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Informer-style shared cache: list+watch → indexed local store.

The r7 controller was event-DRIVEN but read-HEAVY: every reconcile
pass issued a job GET, a pod LIST, and Service/PDB GETs against the
apiserver, so steady-state QPS grew linearly with fleet size (each
relist period re-read every job ~5 times over). The reference
tf-operator was built on client-go informers for exactly this reason
(SURVEY §4); this module is that machinery, client-agnostic (fake,
HTTP, kubectl-shaped):

- :class:`Store` — a thread-safe, per-kind object cache keyed by
  (namespace, name), resourceVersion-tracked (updates apply
  forward-only, so a stale watch echo can never roll back a newer
  optimistic write), with an optional label index for O(1) gang-pod
  lookups at 1000-job scale.
- :class:`Informer` — one resumable list+watch loop feeding a Store:
  initial list at a revision horizon, watch from there, BOOKMARK
  frames advance the resume point without touching the store, 410
  Gone triggers an immediate relist-and-resync (never counted as an
  error), transport errors back off exponentially, and a periodic
  full resync bounds the damage of any silently-dropped event.
  Handlers run AFTER the store reflects the event — a consumer woken
  by an event always reads a cache at least as new as that event.
- :class:`CachedApiClient` — the read/write splitter handed to the
  reconciler: reads of informed kinds come from the local stores
  (zero apiserver requests), reads of everything else and ALL writes
  pass through to the real api client, and write RESULTS are absorbed
  into the stores immediately (forward-only), so a pass can see its
  own writes without waiting for the watch echo.

Staleness contract: reads may trail the apiserver by the watch
delivery latency (bounded by the informer resync period in the worst
case of a wedged stream). The controller is level-triggered, so a
stale read costs at most one wasted-then-corrected pass — writes are
never based on blind state (status writes go through optimistic
concurrency; creates tolerate Conflict).
"""

from __future__ import annotations

import copy
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubeflow_tpu.operator.fake import (
    Gone,
    NotFound,
    _fields_match,
    _labels_match,
)
from kubeflow_tpu.operator.workqueue import ExponentialBackoff

logger = logging.getLogger(__name__)

StoreKey = Tuple[str, str]  # (namespace, name)

#: handler(kind, event_type, obj, relisted) — relisted=True marks
#: deliveries that carry no new information (initial sync / resync
#: replays), so consumers can apply relist (non-backoff-resetting)
#: enqueue semantics.
Handler = Callable[[str, str, Dict[str, Any], bool], None]


def _rv(obj: Dict[str, Any]) -> int:
    """Numeric resourceVersion, 0 when absent/opaque. k8s declares rv
    opaque but every apiserver (and the fake) emits monotone integers;
    an unparseable value reads as 0 = always-apply."""
    try:
        return int(obj.get("metadata", {}).get("resourceVersion", 0) or 0)
    except (TypeError, ValueError):
        return 0


class Store:
    """Thread-safe object cache for ONE kind, forward-only by
    resourceVersion, optionally label-indexed."""

    def __init__(self, kind: str, *, index_label: Optional[str] = None):
        self.kind = kind
        self.index_label = index_label
        self._objects: Dict[StoreKey, Dict[str, Any]] = {}
        # label value → set of keys (only when index_label is set).
        self._index: Dict[str, set] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _key(obj: Dict[str, Any]) -> StoreKey:
        meta = obj.get("metadata", {})
        return (meta.get("namespace", "default"), meta.get("name", ""))

    def _index_value(self, obj: Dict[str, Any]) -> Optional[str]:
        if self.index_label is None:
            return None
        return obj.get("metadata", {}).get("labels", {}).get(
            self.index_label)

    def _unindex_locked(self, key: StoreKey,
                        obj: Dict[str, Any]) -> None:
        value = self._index_value(obj)
        if value is not None:
            bucket = self._index.get(value)
            if bucket is not None:
                bucket.discard(key)
                if not bucket:
                    del self._index[value]

    def _set_locked(self, key: StoreKey, obj: Dict[str, Any]) -> None:
        old = self._objects.get(key)
        if old is not None:
            self._unindex_locked(key, old)
        self._objects[key] = obj
        value = self._index_value(obj)
        if value is not None:
            self._index.setdefault(value, set()).add(key)

    def _delete_locked(self, key: StoreKey) -> None:
        old = self._objects.pop(key, None)
        if old is not None:
            self._unindex_locked(key, old)

    # -- mutation (informer loop + write-result absorption) ---------------

    def upsert(self, obj: Dict[str, Any]) -> bool:
        """Forward-only insert/update; returns whether applied. An
        object older than (or as old as) the stored copy is a stale
        echo of a write already absorbed — skipped."""
        key = self._key(obj)
        with self._lock:
            held = self._objects.get(key)
            if held is not None and _rv(obj) <= _rv(held):
                return False
            self._set_locked(key, copy.deepcopy(obj))
            return True

    def remove(self, obj: Dict[str, Any]) -> bool:
        """Apply a deletion; returns whether a stored object was
        removed. Guarded forward-only: a DELETED echo older than the
        stored copy means the object was deleted AND recreated since —
        the newer incarnation must survive the late echo."""
        key = self._key(obj)
        with self._lock:
            held = self._objects.get(key)
            if held is None:
                return False
            if _rv(held) > _rv(obj) > 0:
                return False  # late echo of a previous incarnation
            self._delete_locked(key)
            return True

    def discard(self, namespace: str, name: str) -> None:
        """Unconditional removal (our OWN delete succeeded — the
        server state is authoritative regardless of versions)."""
        with self._lock:
            self._delete_locked((namespace, name))

    def replace(self, items: List[Dict[str, Any]], list_version: int
                ) -> List[Dict[str, Any]]:
        """Resync from an authoritative list at revision
        ``list_version``; returns the objects DROPPED (deleted while
        the watch was down — the informer dispatches those as DELETED).
        A stored object newer than the list horizon (an optimistic
        absorb racing the list) is retained."""
        listed = {self._key(obj): obj for obj in items}
        dropped: List[Dict[str, Any]] = []
        with self._lock:
            for key in list(self._objects):
                if key in listed:
                    continue
                held = self._objects[key]
                if _rv(held) > list_version:
                    continue  # newer than the list snapshot: keep
                dropped.append(held)
                self._delete_locked(key)
            for obj in listed.values():
                held = self._objects.get(self._key(obj))
                if held is not None and _rv(obj) <= _rv(held):
                    continue
                self._set_locked(self._key(obj), copy.deepcopy(obj))
        return dropped

    # -- reads ------------------------------------------------------------

    def get(self, namespace: str, name: str) -> Dict[str, Any]:
        with self._lock:
            try:
                return copy.deepcopy(self._objects[(namespace, name)])
            except KeyError:
                raise NotFound(
                    f"{self.kind} {namespace}/{name} (cache)") from None

    def list(self, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, Optional[str]]] = None,
             field_selector: Optional[Dict[str, str]] = None
             ) -> List[Dict[str, Any]]:
        with self._lock:
            # Fast path: a single-key equality selector on the index
            # label — the reconciler's per-gang pod list. O(gang), not
            # O(fleet).
            if (self.index_label is not None and label_selector
                    and list(label_selector) == [self.index_label]
                    and label_selector[self.index_label] is not None):
                keys = sorted(self._index.get(
                    label_selector[self.index_label], ()))
                out = [self._objects[k] for k in keys
                       if namespace is None or k[0] == namespace]
            else:
                out = [obj for key, obj in sorted(self._objects.items())
                       if (namespace is None or key[0] == namespace)
                       and _labels_match(obj, label_selector)]
            if field_selector:
                out = [o for o in out
                       if _fields_match(o, field_selector)]
            return [copy.deepcopy(o) for o in out]

    def keys(self) -> List[StoreKey]:
        with self._lock:
            return sorted(self._objects)

    def __len__(self) -> int:
        with self._lock:
            return len(self._objects)


class Informer:
    """One list+watch loop feeding a :class:`Store` and a handler.

    The loop mirrors the r7 controller's watch semantics exactly
    (tests monkeypatch ``api.watch`` and rely on them): a clean
    server-side watch timeout re-watches from the last seen version;
    BOOKMARK frames advance the version without a store write; 410
    Gone relists immediately (counted in ``gone``, never ``errors``,
    never backoff-delayed); transport errors count + back off. A
    periodic full resync (``resync_seconds``) bounds the staleness of
    any silently-lost event; :meth:`request_resync` forces one at the
    next loop turn (leadership takeovers)."""

    def __init__(self, api, kind: str, *,
                 namespace: Optional[str] = None,
                 label_selector: Optional[Dict[str, Optional[str]]] = None,
                 index_label: Optional[str] = None,
                 handler: Optional[Handler] = None,
                 watch_timeout: float = 30.0,
                 resync_seconds: float = 300.0,
                 backoff: Optional[ExponentialBackoff] = None,
                 clock=time.monotonic):
        self.api = api
        self.kind = kind
        self.namespace = namespace
        self.label_selector = label_selector
        self.store = Store(kind, index_label=index_label)
        self.handler = handler
        self.watch_timeout = watch_timeout
        self.resync_seconds = resync_seconds
        self._backoff = backoff or ExponentialBackoff(base=0.2, cap=30.0)
        self._clock = clock
        self._resync_requested = threading.Event()
        # Health counters (the controller aggregates these into its
        # watchGone/watchErrors surfaces and the metrics ConfigMap).
        self.gone = 0
        self.errors = 0
        self.relists = 0
        self.bookmarks = 0
        self.events = 0
        self.synced = threading.Event()

    def request_resync(self) -> None:
        """Force a full relist at the next loop turn (e.g. fresh
        leadership: anything a previous leader half-finished must be
        re-observed from the server, not trusted to the cache)."""
        self._resync_requested.set()

    def stats(self) -> Dict[str, Any]:
        return {
            "objects": len(self.store),
            "events": self.events,
            "bookmarks": self.bookmarks,
            "relists": self.relists,
            "gone": self.gone,
            "errors": self.errors,
        }

    def _dispatch(self, event_type: str, obj: Dict[str, Any],
                  relisted: bool) -> None:
        if self.handler is None:
            return
        try:
            self.handler(self.kind, event_type, obj, relisted)
        except Exception:  # noqa: BLE001 — a handler bug must not
            # kill the sync loop (the cache would silently freeze).
            logger.exception("%s informer handler failed", self.kind)

    def _relist(self) -> int:
        """Authoritative list → store resync; dispatches relisted
        upserts + DELETED for objects dropped while the watch was
        down. Returns the watch resume version."""
        items, version = self.api.list_with_version(
            self.kind, self.namespace, self.label_selector)
        dropped = self.store.replace(items, version)
        self.relists += 1
        for obj in dropped:
            self._dispatch("DELETED", obj, True)
        for obj in items:
            self._dispatch("SYNC", obj, True)
        self.synced.set()
        return version

    def run(self, stop: threading.Event) -> None:
        version = 0
        consecutive_errors = 0
        last_list = float("-inf")
        while not stop.is_set():
            delay = 0.0
            try:
                if (version == 0 or self._resync_requested.is_set()
                        or self._clock() - last_list
                        >= self.resync_seconds):
                    self._resync_requested.clear()
                    version = self._relist()
                    last_list = self._clock()
                for event_type, obj in self.api.watch(
                        self.kind, self.namespace,
                        resource_version=version, stop=stop,
                        timeout=self.watch_timeout,
                        label_selector=self.label_selector):
                    version = max(version, _rv(obj))
                    consecutive_errors = 0
                    if event_type == "BOOKMARK":
                        # The payload IS the fresh resume point; no
                        # object rides a bookmark.
                        self.bookmarks += 1
                        continue
                    self.events += 1
                    if event_type == "DELETED":
                        self.store.remove(obj)
                    else:
                        self.store.upsert(obj)
                    self._dispatch(event_type, obj, False)
                    if self._resync_requested.is_set():
                        break  # tear the stream down for the resync
                consecutive_errors = 0
            except Gone:
                # 410: our resume point fell out of the server's watch
                # window. The sanctioned reaction is an immediate
                # relist — not an error, never backoff-delayed
                # (backing off would punish the controller for the
                # server's compaction cadence).
                logger.info("%s informer compacted (410); relisting",
                            self.kind)
                self.gone += 1
                version = 0
            except Exception:  # noqa: BLE001 — watch transport
                logger.exception("%s informer watch failed; relisting",
                                 self.kind)
                self.errors += 1
                consecutive_errors += 1
                version = 0
                delay = self._backoff.delay(consecutive_errors)
            if delay:
                stop.wait(delay)


class CachedApiClient:
    """Same store surface as the api clients, reads served from
    informer stores for informed kinds.

    Writes always go through the underlying client; their RESULTS are
    absorbed into the stores immediately (forward-only), so the watch
    echo of our own write is a no-op by the time it arrives and a
    reconcile pass can read-back what it just wrote. Reads of kinds
    with no informer (Event, ConfigMap, Lease, ...) pass through."""

    def __init__(self, api, stores: Dict[str, Store]):
        self.api = api
        self._stores = stores

    # -- reads (store-backed for informed kinds) --------------------------

    def get(self, kind: str, namespace: str, name: str) -> Dict[str, Any]:
        store = self._stores.get(kind)
        if store is not None:
            return store.get(namespace, name)
        return self.api.get(kind, namespace, name)

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, Optional[str]]] = None,
             field_selector: Optional[Dict[str, str]] = None
             ) -> List[Dict[str, Any]]:
        store = self._stores.get(kind)
        if store is not None:
            return store.list(namespace, label_selector, field_selector)
        return self.api.list(kind, namespace, label_selector,
                             field_selector)

    # -- writes (pass through + absorb the echo) --------------------------

    def _absorb(self, obj: Optional[Dict[str, Any]]) -> None:
        if not isinstance(obj, dict):
            return
        store = self._stores.get(obj.get("kind", ""))
        if store is not None:
            store.upsert(obj)

    def create(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        created = self.api.create(obj)
        self._absorb(created)
        return created

    def patch(self, kind: str, namespace: str, name: str,
              mutate: Callable[[Dict[str, Any]], None]) -> Dict[str, Any]:
        updated = self.api.patch(kind, namespace, name, mutate)
        if isinstance(updated, dict):
            updated.setdefault("kind", kind)
        self._absorb(updated)
        return updated

    def replace(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        updated = self.api.replace(obj)
        self._absorb(updated)
        return updated

    def delete(self, kind: str, namespace: str, name: str) -> None:
        store = self._stores.get(kind)
        try:
            self.api.delete(kind, namespace, name)
        except NotFound:
            # The server is authoritative: it has no such object, so
            # neither should the cache.
            if store is not None:
                store.discard(namespace, name)
            raise
        if store is not None:
            store.discard(namespace, name)

    # -- everything else (watch, scale, logs, ...) ------------------------

    def __getattr__(self, name: str):
        return getattr(self.api, name)
