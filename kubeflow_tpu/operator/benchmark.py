# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Controller load benchmark: M jobs × injected fault rates.

The control plane had never been measured under load (VERDICT r5):
this drives the REAL WatchController — watchers, workqueue, worker
threads, reconciler — against the fake apiserver with chaos faults
enabled (409 conflict storms, 429/500 bursts, dropped watch streams)
and reports, per worker count:

- convergence: seconds until every job's gang is Running,
- reconcile throughput (successful reconciles / second to converge),
- requeue latency percentiles (workqueue enqueue → dequeue),
- steady-state apiserver QPS (request-log rate after convergence —
  the hot-loop detector: a converged controller should be near-idle).

Run via ``python bench.py --controller`` (PERF.md records the
numbers) or pytest's smoke test (tests/test_controller_chaos.py).
No jax, no accelerator — this is a pure control-plane benchmark.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from kubeflow_tpu.manifests.tpujob import (
    KIND,
    replica_spec,
    termination_policy,
    tpu_job,
)
from kubeflow_tpu.operator.controller import WatchController
from kubeflow_tpu.operator.fake import (
    Conflict,
    FakeApiServer,
    ServerError,
    TooManyRequests,
)
from kubeflow_tpu.operator.reconciler import JOB_LABEL
from kubeflow_tpu.operator.workqueue import ExponentialBackoff, TokenBucket


def _percentile(samples: List[float], pct: float) -> float:
    if not samples:
        return 0.0
    ordered = sorted(samples)
    idx = min(len(ordered) - 1,
              max(0, round(pct / 100.0 * (len(ordered) - 1))))
    return ordered[idx]


def _bench_job(name: str) -> Dict[str, Any]:
    spec = replica_spec(
        "TPU_WORKER", 1, image="bench:img",
        tpu_accelerator="tpu-v5-lite-podslice", tpu_topology="1x1",
        chips_per_worker=1)
    job = tpu_job(name, "default", [spec],
                  termination=termination_policy("TPU_WORKER", 0))
    job["metadata"]["uid"] = f"uid-{name}"
    return job


def _install_faults(api: FakeApiServer, *, conflict_rate: float,
                    throttle_rate: float, error_rate: float,
                    watch_drop_events: Optional[int],
                    latency: float = 0.0) -> None:
    writes = ("create", "patch", "replace", "delete")
    if conflict_rate:
        api.faults.add_rule(lambda: Conflict("injected conflict storm"),
                            verbs=writes, rate=conflict_rate)
    if throttle_rate:
        api.faults.add_rule(
            lambda: TooManyRequests("injected 429 burst"),
            rate=throttle_rate)
    if error_rate:
        api.faults.add_rule(lambda: ServerError("injected 500"),
                            rate=error_rate)
    api.faults.watch_max_events = watch_drop_events
    api.faults.latency = latency


def run_controller_load_bench(
        *, jobs: int = 50,
        workers_list: Sequence[int] = (1, 4),
        conflict_rate: float = 0.05,
        throttle_rate: float = 0.03,
        error_rate: float = 0.02,
        watch_drop_events: Optional[int] = 40,
        latency: float = 0.002,
        converge_timeout: float = 60.0,
        steady_window: float = 3.0,
        relist_seconds: float = 1.0,
        backoff: Optional[ExponentialBackoff] = None,
        qps: float = 200.0) -> Dict[str, Any]:
    """One row per worker count; see the module docstring for the
    metrics. ``backoff`` defaults to a fast test-scale curve (base
    25 ms, cap 2 s) so the bench converges in seconds — production
    keeps the 50 ms → 5 min defaults. ``latency`` (default 2 ms) is
    per-request apiserver RTT: without it the in-memory store answers
    at GIL speed and worker parallelism has nothing to overlap. Note
    steady-state QPS scales with ``relist_seconds``: the relist
    safety net IS the converged controller's remaining traffic."""
    with _quiet_operator_logs():
        return _run(jobs=jobs, workers_list=workers_list,
                    conflict_rate=conflict_rate,
                    throttle_rate=throttle_rate,
                    error_rate=error_rate,
                    watch_drop_events=watch_drop_events,
                    latency=latency,
                    converge_timeout=converge_timeout,
                    steady_window=steady_window,
                    relist_seconds=relist_seconds,
                    backoff=backoff, qps=qps)


@contextlib.contextmanager
def _quiet_operator_logs():
    """Injected faults are the POINT of this bench: the controller's
    exception logging would drown the one JSON output line."""
    targets = [logging.getLogger("kubeflow_tpu.operator." + mod)
               for mod in ("controller", "reconciler", "fake")]
    levels = [t.level for t in targets]
    for t in targets:
        t.setLevel(logging.CRITICAL)
    try:
        yield
    finally:
        for t, level in zip(targets, levels):
            t.setLevel(level)


def _run(*, jobs, workers_list, conflict_rate, throttle_rate,
         error_rate, watch_drop_events, latency, converge_timeout,
         steady_window, relist_seconds, backoff, qps) -> Dict[str, Any]:
    rows = []
    for workers in workers_list:
        api = FakeApiServer()
        _install_faults(api, conflict_rate=conflict_rate,
                        throttle_rate=throttle_rate,
                        error_rate=error_rate,
                        watch_drop_events=watch_drop_events,
                        latency=latency)
        ctl = WatchController(
            api, relist_seconds=relist_seconds, workers=workers,
            backoff=backoff or ExponentialBackoff(base=0.025, cap=2.0),
            limiter=TokenBucket(qps=qps, burst=int(qps)))
        thread = threading.Thread(target=ctl.run, daemon=True)
        t0 = time.monotonic()
        thread.start()
        names = [f"load-{i:03d}" for i in range(jobs)]
        for name in names:
            with api.as_kubelet():
                api.create(_bench_job(name))

        def _running() -> int:
            done = 0
            with api.as_kubelet():
                for name in names:
                    # Kubelet stand-in: any created pod starts Running.
                    for pod in api._list("Pod", "default",
                                         {JOB_LABEL: name}):
                        if (pod.get("status", {}).get("phase")
                                != "Running"):
                            api.set_pod_phase(
                                "default", pod["metadata"]["name"],
                                "Running")
                    job = api.get(KIND, "default", name)
                    if job.get("status", {}).get("phase") == "Running":
                        done += 1
            return done

        converged_at = None
        deadline = t0 + converge_timeout
        while time.monotonic() < deadline:
            if _running() == jobs:
                converged_at = time.monotonic()
                break
            time.sleep(0.05)
        converge_seconds = ((converged_at or time.monotonic()) - t0)

        # Steady state: converged controller vs the apiserver.
        steady_start = time.monotonic()
        time.sleep(steady_window)
        steady_requests = api.request_count(since=steady_start)
        stats = ctl.stats()
        latencies = ctl.queue.latencies()
        ctl.stop.set()
        thread.join(timeout=10)
        rows.append({
            "workers": workers,
            "jobs": jobs,
            "relist_seconds": relist_seconds,
            "converged": converged_at is not None,
            "converge_seconds": round(converge_seconds, 2),
            "reconciles": stats["reconciles"],
            "reconcile_failures": stats["reconcileFailures"],
            "reconciles_per_sec": round(
                stats["reconciles"] / max(converge_seconds, 1e-9), 1),
            "requeue_latency_ms": {
                "p50": round(_percentile(latencies, 50) * 1e3, 1),
                "p90": round(_percentile(latencies, 90) * 1e3, 1),
                "p99": round(_percentile(latencies, 99) * 1e3, 1),
            },
            "steady_state_qps": round(
                steady_requests / steady_window, 2),
            "watch_gone": sum(stats["watchGone"].values()),
            "watch_errors": sum(stats["watchErrors"].values()),
            "total_apiserver_requests": len(api.request_log()),
        })
    return {
        "bench": "controller_load",
        "fault_rates": {"conflict": conflict_rate,
                        "throttle429": throttle_rate,
                        "error500": error_rate,
                        "watch_drop_events": watch_drop_events,
                        "latency_ms": round(latency * 1e3, 2)},
        "rows": rows,
    }
