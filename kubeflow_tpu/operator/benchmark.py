# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Controller load benchmarks: chaos (r7) and cluster scale (r12).

The control plane had never been measured under load (VERDICT r5):
this drives the REAL WatchController — watchers, workqueue, worker
threads, reconciler — against the fake apiserver with chaos faults
enabled (409 conflict storms, 429/500 bursts, dropped watch streams)
and reports, per worker count:

- convergence: seconds until every job's gang is Running,
- reconcile throughput (successful reconciles / second to converge),
- requeue latency percentiles (workqueue enqueue → dequeue),
- steady-state apiserver QPS (request-log rate after convergence —
  the hot-loop detector: a converged controller should be near-idle).

Run via ``python bench.py --controller`` (PERF.md records the
numbers) or pytest's smoke test (tests/test_controller_chaos.py).
No jax, no accelerator — this is a pure control-plane benchmark.

The r12 scale bench (:func:`run_controller_scale_bench`) is the
informer acceptance harness: 500–1000 jobs with spot churn (drained
pod kills mid-run) and a poison-job storm, run once with informer
reads and once direct, reporting per mode:

- p99 event→reconcile latency (workqueue enqueue→dequeue),
- steady-state apiserver requests PER RECONCILE (the informer win:
  reads come from the cache and no-op status writes are suppressed,
  so a converged fleet's request rate is flat in job count),
- churn reaction (re-convergence seconds after the kill wave),
- fairness: the poison storm must not keep healthy jobs from
  converging, and quarantine must hold all poison keys.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from kubeflow_tpu.manifests.tpujob import (
    KIND,
    replica_spec,
    termination_policy,
    tpu_job,
)
from kubeflow_tpu.operator.controller import WatchController
from kubeflow_tpu.operator.fake import (
    Conflict,
    FakeApiServer,
    ServerError,
    TooManyRequests,
)
from kubeflow_tpu.operator.reconciler import JOB_LABEL
from kubeflow_tpu.operator.workqueue import ExponentialBackoff, TokenBucket


def _percentile(samples: List[float], pct: float) -> float:
    if not samples:
        return 0.0
    ordered = sorted(samples)
    idx = min(len(ordered) - 1,
              max(0, round(pct / 100.0 * (len(ordered) - 1))))
    return ordered[idx]


def _bench_job(name: str) -> Dict[str, Any]:
    spec = replica_spec(
        "TPU_WORKER", 1, image="bench:img",
        tpu_accelerator="tpu-v5-lite-podslice", tpu_topology="1x1",
        chips_per_worker=1)
    job = tpu_job(name, "default", [spec],
                  termination=termination_policy("TPU_WORKER", 0))
    job["metadata"]["uid"] = f"uid-{name}"
    return job


def _install_faults(api: FakeApiServer, *, conflict_rate: float,
                    throttle_rate: float, error_rate: float,
                    watch_drop_events: Optional[int],
                    latency: float = 0.0) -> None:
    writes = ("create", "patch", "replace", "delete")
    if conflict_rate:
        api.faults.add_rule(lambda: Conflict("injected conflict storm"),
                            verbs=writes, rate=conflict_rate)
    if throttle_rate:
        api.faults.add_rule(
            lambda: TooManyRequests("injected 429 burst"),
            rate=throttle_rate)
    if error_rate:
        api.faults.add_rule(lambda: ServerError("injected 500"),
                            rate=error_rate)
    api.faults.watch_max_events = watch_drop_events
    api.faults.latency = latency


def run_controller_load_bench(
        *, jobs: int = 50,
        workers_list: Sequence[int] = (1, 4),
        conflict_rate: float = 0.05,
        throttle_rate: float = 0.03,
        error_rate: float = 0.02,
        watch_drop_events: Optional[int] = 40,
        latency: float = 0.002,
        converge_timeout: float = 60.0,
        steady_window: float = 3.0,
        relist_seconds: float = 1.0,
        backoff: Optional[ExponentialBackoff] = None,
        qps: float = 200.0) -> Dict[str, Any]:
    """One row per worker count; see the module docstring for the
    metrics. ``backoff`` defaults to a fast test-scale curve (base
    25 ms, cap 2 s) so the bench converges in seconds — production
    keeps the 50 ms → 5 min defaults. ``latency`` (default 2 ms) is
    per-request apiserver RTT: without it the in-memory store answers
    at GIL speed and worker parallelism has nothing to overlap. Note
    steady-state QPS scales with ``relist_seconds``: the relist
    safety net IS the converged controller's remaining traffic."""
    with _quiet_operator_logs():
        return _run(jobs=jobs, workers_list=workers_list,
                    conflict_rate=conflict_rate,
                    throttle_rate=throttle_rate,
                    error_rate=error_rate,
                    watch_drop_events=watch_drop_events,
                    latency=latency,
                    converge_timeout=converge_timeout,
                    steady_window=steady_window,
                    relist_seconds=relist_seconds,
                    backoff=backoff, qps=qps)


@contextlib.contextmanager
def _quiet_operator_logs():
    """Injected faults are the POINT of this bench: the controller's
    exception logging would drown the one JSON output line."""
    targets = [logging.getLogger("kubeflow_tpu.operator." + mod)
               for mod in ("controller", "reconciler", "fake")]
    levels = [t.level for t in targets]
    for t in targets:
        t.setLevel(logging.CRITICAL)
    try:
        yield
    finally:
        for t, level in zip(targets, levels):
            t.setLevel(level)


def run_controller_scale_bench(
        *, jobs: int = 500,
        workers: int = 4,
        churn_kills: int = 50,
        poison_jobs: int = 5,
        informer_modes: Sequence[bool] = (True, False),
        relist_seconds: float = 5.0,
        latency: float = 0.002,
        converge_timeout: float = 180.0,
        churn_timeout: float = 120.0,
        steady_window: float = 6.0,
        qps: float = 2000.0) -> Dict[str, Any]:
    """The r12 informer/preemption-era scale bench; see the module
    docstring. ``latency`` is per-apiserver-request RTT — the knob
    that makes read-path traffic COST something, so the informer
    contrast measures architecture, not GIL luck. Spot churn kills
    ``churn_kills`` running pods with the DRAIN exit code (the spot
    preemption signature: restart without burning budget).
    ``steady_window`` should cover at least one ``relist_seconds``
    sweep — direct-read traffic is bursty at the relist cadence, and
    a window that misses the sweep understates the contrast."""
    with _quiet_operator_logs():
        rows = [_run_scale_row(
                    jobs=jobs, workers=workers, churn_kills=churn_kills,
                    poison_jobs=poison_jobs, informer=mode,
                    relist_seconds=relist_seconds, latency=latency,
                    converge_timeout=converge_timeout,
                    churn_timeout=churn_timeout,
                    steady_window=steady_window, qps=qps)
                for mode in informer_modes]
    return {
        "bench": "controller_scale",
        "jobs": jobs,
        "workers": workers,
        "churn_kills": churn_kills,
        "poison_jobs": poison_jobs,
        "latency_ms": round(latency * 1e3, 2),
        "rows": rows,
    }


def _run_scale_row(*, jobs, workers, churn_kills, poison_jobs,
                   informer, relist_seconds, latency, converge_timeout,
                   churn_timeout, steady_window, qps) -> Dict[str, Any]:
    import random

    from kubeflow_tpu.training.launcher import DRAIN_EXIT_CODE

    api = FakeApiServer()
    api.faults.latency = latency
    # The poison storm: these jobs' pod creates always 500 — they
    # must quarantine while every healthy job converges regardless.
    if poison_jobs:
        api.faults.add_rule(
            lambda: ServerError("poison storm: pod create down"),
            verbs=("create",), kind="Pod", name="^poison")

    names = [f"load-{i:04d}" for i in range(jobs)]
    poison_names = [f"poison{i:02d}" for i in range(poison_jobs)]
    with api.as_kubelet():
        for name in names + poison_names:
            api.create(_bench_job(name))

    ctl = WatchController(
        api, relist_seconds=relist_seconds, workers=workers,
        backoff=ExponentialBackoff(base=0.025, cap=2.0),
        limiter=TokenBucket(qps=qps, burst=int(qps)),
        quarantine_after=3, informer_reads=informer)
    thread = threading.Thread(target=ctl.run, daemon=True)

    # A background "kubelet/scheduler": any created healthy pod goes
    # Running shortly after (bypasses fault latency + the request
    # log, like a real kubelet writing through its own channel).
    kubelet_stop = threading.Event()

    def kubelet_loop():
        while not kubelet_stop.is_set():
            with api.as_kubelet():
                for pod in api._list("Pod", "default",
                                     {JOB_LABEL: None}):
                    pname = pod["metadata"]["name"]
                    if pname.startswith("poison"):
                        continue  # scarce world for the storm jobs
                    if pod.get("status", {}).get("phase") in (
                            None, "Pending"):
                        api.set_pod_phase("default", pname, "Running")
            kubelet_stop.wait(0.02)

    kubelet = threading.Thread(target=kubelet_loop, daemon=True)

    def healthy_running() -> int:
        with api.as_kubelet():
            return sum(
                1 for n in names
                if api.get(KIND, "default", n)
                .get("status", {}).get("phase") == "Running")

    def wait_converged(timeout: float) -> Optional[float]:
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            if healthy_running() == jobs:
                return time.monotonic() - t0
            time.sleep(0.05)
        return None

    t0 = time.monotonic()
    thread.start()
    kubelet.start()
    try:
        converge_seconds = wait_converged(converge_timeout)
        converge_latency = ctl.queue.latency_percentiles()

        # Steady state: a converged fleet vs the apiserver, measured
        # per RECONCILE (the flatness claim) and per second.
        mark = api.mark()
        stats0 = ctl.stats()
        time.sleep(steady_window)
        counts = api.request_counts(mark)
        stats1 = ctl.stats()
        reconciles = max(1, stats1["reconciles"] - stats0["reconciles"])
        steady = {
            "window_s": steady_window,
            "requests": counts["total"],
            "reconciles": reconciles,
            "requests_per_reconcile": round(
                counts["total"] / reconciles, 3),
            "qps": round(counts["total"] / steady_window, 2),
            "verbs": {k: v for k, v in sorted(counts.items())
                      if k != "total"},
        }

        # Spot churn: a kill wave of drained pods (SIGTERM → finish
        # step → checkpoint → exit 77). Slice restarts must ride the
        # event path and not burn restart budget.
        rng = random.Random(0)
        with api.as_kubelet():
            running = [p["metadata"]["name"]
                       for p in api._list("Pod", "default",
                                          {JOB_LABEL: None})
                       if not p["metadata"]["name"].startswith("poison")
                       and p.get("status", {}).get("phase") == "Running"]
        victims = rng.sample(running, min(churn_kills, len(running)))
        # Segment the latency window: churn percentiles must cover
        # ONLY churn-phase samples (a wrapped deque would otherwise
        # fall back to converge-backlog contamination).
        ctl.queue.drain_latencies()
        churn_t0 = time.monotonic()
        for victim in victims:
            api.set_pod_terminated("default", victim, DRAIN_EXIT_CODE)

        # Re-convergence is POD truth, not job phase: a drained gang's
        # phase barely leaves Running (Restarting → recreate →
        # display-Running), so the only honest signal is every healthy
        # gang's pod existing AND Running again — which requires the
        # full teardown/recreate/reschedule cycle to complete.
        def pods_reconverged() -> bool:
            with api.as_kubelet():
                healthy = [
                    p for p in api._list("Pod", "default",
                                         {JOB_LABEL: None})
                    if not p["metadata"]["name"].startswith("poison")]
                return (len(healthy) == jobs and all(
                    p.get("status", {}).get("phase") == "Running"
                    for p in healthy))

        churn_seconds = None
        churn_deadline = time.monotonic() + churn_timeout
        while time.monotonic() < churn_deadline:
            if pods_reconverged():
                churn_seconds = time.monotonic() - churn_t0
                break
            time.sleep(0.05)
        fresh = ctl.queue.latencies()
        churn_latency = {
            p: round(_percentile(fresh, pct) * 1e3, 2)
            for p, pct in (("p50", 50), ("p90", 90), ("p99", 99))}

        final = ctl.stats()
        return {
            "informer": informer,
            "jobs": jobs,
            "workers": workers,
            "converged": converge_seconds is not None,
            "converge_seconds": round(converge_seconds or -1.0, 2),
            "event_to_reconcile_ms": converge_latency,
            "steady": steady,
            "churn": {
                "kills": len(victims),
                "reconverged": churn_seconds is not None,
                "reconverge_seconds": round(churn_seconds or -1.0, 2),
                "event_to_reconcile_ms": churn_latency,
            },
            "poison_quarantined": len(final["queue"]["quarantined"]),
            "reconciles": final["reconciles"],
            "reconcile_failures": final["reconcileFailures"],
            "informer_stats": final["informers"],
        }
    finally:
        kubelet_stop.set()
        ctl.stop.set()
        thread.join(timeout=15)
        kubelet.join(timeout=5)


def run_elastic_churn_bench(
        *, elastic_jobs: int = 6,
        rigid_jobs: int = 6,
        workers_per_gang: int = 4,
        min_replicas: int = 2,
        survivors: int = 2,
        deadline_seconds: float = 3.0,
        relist_seconds: float = 0.3,
        controller_workers: int = 4,
        converge_timeout: float = 30.0,
        storm_timeout: float = 45.0) -> Dict[str, Any]:
    """The r16 elastic acceptance phase: a spot storm that halves
    every gang's schedulable hosts. Elastic jobs (minReplicas) must
    RIDE THROUGH — resize to the survivors, stay Running, burn zero
    restart budget, never materialize a Restarting condition — while
    rigid gangs restart into a pool that can no longer hold them and
    deadline-fail (the post-restart scheduling-stall deadline),
    releasing their chips. Real WatchController + informer reads +
    workqueue settle timers; per-job capacity is enforced by a
    kubelet stand-in that only schedules replica indices below the
    job's surviving host count."""
    from kubeflow_tpu.operator.reconciler import (
        DEADLINE_CONDITION,
        REPLICA_INDEX_LABEL,
        RESIZED_CONDITION,
    )
    from kubeflow_tpu.training.launcher import DRAIN_EXIT_CODE

    with _quiet_operator_logs():
        api = FakeApiServer()
        e_names = [f"elastic-{i:02d}" for i in range(elastic_jobs)]
        r_names = [f"rigid-{i:02d}" for i in range(rigid_jobs)]

        def make(name: str, elastic: bool) -> Dict[str, Any]:
            spec = replica_spec(
                "TPU_WORKER", workers_per_gang, image="bench:img",
                tpu_accelerator="tpu-v5-lite-podslice",
                tpu_topology="1x1", chips_per_worker=1)
            job = tpu_job(
                name, "default", [spec],
                termination=termination_policy("TPU_WORKER", 0),
                scheduling_deadline_seconds=max(
                    1, int(deadline_seconds)),
                min_replicas=min_replicas if elastic else None)
            job["metadata"]["uid"] = f"uid-{name}"
            return job

        with api.as_kubelet():
            for name in e_names:
                api.create(make(name, True))
            for name in r_names:
                api.create(make(name, False))

        # Per-job host capacity: the kubelet stand-in schedules only
        # replica indices below it. The storm halves it.
        capacity = {n: workers_per_gang for n in e_names + r_names}
        capacity_lock = threading.Lock()
        kubelet_stop = threading.Event()

        def kubelet_loop():
            while not kubelet_stop.is_set():
                with api.as_kubelet():
                    for pod in api._list("Pod", "default",
                                         {JOB_LABEL: None}):
                        if pod.get("status", {}).get("phase") not in (
                                None, "Pending"):
                            continue
                        labels = pod["metadata"].get("labels", {})
                        job_name = labels.get(JOB_LABEL, "")
                        try:
                            index = int(labels.get(
                                REPLICA_INDEX_LABEL, "0"))
                        except ValueError:
                            index = 0
                        with capacity_lock:
                            cap = capacity.get(job_name, 0)
                        if index < cap:
                            api.set_pod_phase(
                                "default", pod["metadata"]["name"],
                                "Running")
                kubelet_stop.wait(0.02)

        ctl = WatchController(
            api, relist_seconds=relist_seconds,
            workers=controller_workers,
            backoff=ExponentialBackoff(base=0.02, cap=0.5),
            limiter=TokenBucket(qps=2000.0, burst=2000))
        ctl_thread = threading.Thread(target=ctl.run, daemon=True)
        kubelet = threading.Thread(target=kubelet_loop, daemon=True)
        ctl_thread.start()
        kubelet.start()
        try:
            def job_status(name):
                with api.as_kubelet():
                    return api.get(KIND, "default", name).get(
                        "status", {})

            def all_running(names, count):
                for name in names:
                    status = job_status(name)
                    if status.get("phase") != "Running":
                        return False
                    with api.as_kubelet():
                        pods = api._list("Pod", "default",
                                         {JOB_LABEL: name})
                    if len(pods) != count or any(
                            p.get("status", {}).get("phase")
                            != "Running" for p in pods):
                        return False
                return True

            def wait_for(predicate, timeout):
                t0 = time.monotonic()
                while time.monotonic() - t0 < timeout:
                    if predicate():
                        return time.monotonic() - t0
                    time.sleep(0.03)
                return None

            converged = wait_for(
                lambda: all_running(e_names + r_names,
                                    workers_per_gang),
                converge_timeout)

            # The spot storm: every gang loses its top half — the
            # lost hosts drain (exit 77) and NEVER come back (the
            # pool shrank).
            with capacity_lock:
                for name in capacity:
                    capacity[name] = survivors
            storm_t0 = time.monotonic()
            with api.as_kubelet():
                for pod in api._list("Pod", "default",
                                     {JOB_LABEL: None}):
                    labels = pod["metadata"].get("labels", {})
                    if int(labels.get(REPLICA_INDEX_LABEL,
                                      "0")) >= survivors:
                        api.set_pod_terminated(
                            "default", pod["metadata"]["name"],
                            DRAIN_EXIT_CODE)

            elastic_at = wait_for(
                lambda: all_running(e_names, survivors),
                storm_timeout)

            def rigid_failed():
                for name in r_names:
                    status = job_status(name)
                    if status.get("phase") != "Failed":
                        return False
                    conds = {c.get("type"): c.get("status")
                             for c in status.get("conditions", [])}
                    if conds.get(DEADLINE_CONDITION) != "True":
                        return False
                return True

            rigid_at = wait_for(rigid_failed, storm_timeout)

            elastic_rows = []
            for name in e_names:
                status = job_status(name)
                conds = {c.get("type"): c.get("status")
                         for c in status.get("conditions", [])}
                elastic_rows.append({
                    "name": name,
                    "phase": status.get("phase"),
                    "currentReplicas": status.get("currentReplicas"),
                    "restartCount": int(
                        status.get("restartCount", 0)),
                    "resized": conds.get(RESIZED_CONDITION) == "True",
                    # Never even ENTERED Restarting: the condition
                    # was never materialized.
                    "never_restarting": "Restarting" not in conds,
                })
            stats = ctl.stats()
            return {
                "bench": "elastic_churn",
                "elastic_jobs": elastic_jobs,
                "rigid_jobs": rigid_jobs,
                "workers_per_gang": workers_per_gang,
                "min_replicas": min_replicas,
                "survivors": survivors,
                "deadline_seconds": deadline_seconds,
                "converged": converged is not None,
                "converge_seconds": round(converged or -1.0, 2),
                "elastic_rode_through": sum(
                    1 for r in elastic_rows
                    if r["phase"] == "Running" and r["resized"]
                    and r["restartCount"] == 0
                    and r["never_restarting"]),
                "elastic_reconverge_seconds": round(
                    elastic_at if elastic_at is not None else -1.0, 2),
                "rigid_deadline_failed": sum(
                    1 for name in r_names
                    if job_status(name).get("phase") == "Failed"),
                "rigid_failed_seconds": round(
                    rigid_at if rigid_at is not None else -1.0, 2),
                "gang_resizes": stats["gangResizes"],
                "elastic_rows": elastic_rows,
            }
        finally:
            kubelet_stop.set()
            ctl.stop.set()
            ctl_thread.join(timeout=15)
            kubelet.join(timeout=5)


def _run(*, jobs, workers_list, conflict_rate, throttle_rate,
         error_rate, watch_drop_events, latency, converge_timeout,
         steady_window, relist_seconds, backoff, qps) -> Dict[str, Any]:
    rows = []
    for workers in workers_list:
        api = FakeApiServer()
        _install_faults(api, conflict_rate=conflict_rate,
                        throttle_rate=throttle_rate,
                        error_rate=error_rate,
                        watch_drop_events=watch_drop_events,
                        latency=latency)
        ctl = WatchController(
            api, relist_seconds=relist_seconds, workers=workers,
            backoff=backoff or ExponentialBackoff(base=0.025, cap=2.0),
            limiter=TokenBucket(qps=qps, burst=int(qps)))
        thread = threading.Thread(target=ctl.run, daemon=True)
        t0 = time.monotonic()
        thread.start()
        names = [f"load-{i:03d}" for i in range(jobs)]
        for name in names:
            with api.as_kubelet():
                api.create(_bench_job(name))

        def _running() -> int:
            done = 0
            with api.as_kubelet():
                for name in names:
                    # Kubelet stand-in: any created pod starts Running.
                    for pod in api._list("Pod", "default",
                                         {JOB_LABEL: name}):
                        if (pod.get("status", {}).get("phase")
                                != "Running"):
                            api.set_pod_phase(
                                "default", pod["metadata"]["name"],
                                "Running")
                    job = api.get(KIND, "default", name)
                    if job.get("status", {}).get("phase") == "Running":
                        done += 1
            return done

        converged_at = None
        deadline = t0 + converge_timeout
        while time.monotonic() < deadline:
            if _running() == jobs:
                converged_at = time.monotonic()
                break
            time.sleep(0.05)
        converge_seconds = ((converged_at or time.monotonic()) - t0)

        # Steady state: converged controller vs the apiserver.
        steady_start = time.monotonic()
        time.sleep(steady_window)
        steady_requests = api.request_count(since=steady_start)
        stats = ctl.stats()
        latencies = ctl.queue.latencies()
        ctl.stop.set()
        thread.join(timeout=10)
        rows.append({
            "workers": workers,
            "jobs": jobs,
            "relist_seconds": relist_seconds,
            "converged": converged_at is not None,
            "converge_seconds": round(converge_seconds, 2),
            "reconciles": stats["reconciles"],
            "reconcile_failures": stats["reconcileFailures"],
            "reconciles_per_sec": round(
                stats["reconciles"] / max(converge_seconds, 1e-9), 1),
            "requeue_latency_ms": {
                "p50": round(_percentile(latencies, 50) * 1e3, 1),
                "p90": round(_percentile(latencies, 90) * 1e3, 1),
                "p99": round(_percentile(latencies, 99) * 1e3, 1),
            },
            "steady_state_qps": round(
                steady_requests / steady_window, 2),
            "watch_gone": sum(stats["watchGone"].values()),
            "watch_errors": sum(stats["watchErrors"].values()),
            "total_apiserver_requests": len(api.request_log()),
        })
    return {
        "bench": "controller_load",
        "fault_rates": {"conflict": conflict_rate,
                        "throttle429": throttle_rate,
                        "error500": error_rate,
                        "watch_drop_events": watch_drop_events,
                        "latency_ms": round(latency * 1e3, 2)},
        "rows": rows,
    }
