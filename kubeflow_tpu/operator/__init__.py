# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""TPUJob operator — the tf-operator replacement.

A level-triggered reconciler over TPUJob custom resources (CRD in
kubeflow_tpu.manifests.tpujob). Core differences from the reference's
parameter-server controller (external Go tf-operator, reference
``kubeflow/core/tf-job.libsonnet:31-95``):

- **Gang semantics**: a TPU_WORKER replica set is a pod slice that is
  created, restarted, and torn down as one unit (decision kernel in
  C++, native/kft_runtime.cc kft_gang_decide).
- **Bootstrap env**: pods get ``KFT_COORDINATOR_ADDRESS`` /
  ``KFT_NUM_PROCESSES`` / ``KFT_PROCESS_ID`` (+ ``TPU_WORKER_*``) for
  ``jax.distributed.initialize`` instead of ``TF_CONFIG``.
- **Recovery**: ``restart-slice`` restarts the whole gang (from the
  job's checkpoint dir) instead of individual pod restarts.
- **Hermetic testing**: a fake apiserver (kubeflow_tpu.operator.fake)
  — the layer the reference never had (its operator was only tested
  against a live GKE cluster, SURVEY §4) — with injectable faults
  (conflict storms, 429/500 bursts, dropped watches, latency) and a
  request log for asserting apiserver load under chaos.
- **Work scheduling**: a rate-limited workqueue
  (kubeflow_tpu.operator.workqueue) — per-key exponential backoff
  with jitter, a global token bucket, N workers with per-key dedup,
  and poison-job quarantine surfaced as a ReconcileStalled condition.
- **Informer cache** (kubeflow_tpu.operator.informer): list+watch-fed
  indexed local stores for every hot-path kind; reconciles read
  locally and steady-state apiserver QPS stays flat as the fleet
  grows (the reference's client-go informer pattern, SURVEY §4).
- **Priority & gang preemption**: ``spec.priority`` + the scheduling
  deadline machinery let a starving high-priority gang evict the
  lowest-priority running gang — one victim per decision, globally
  rate-limited, Preempted/PreemptedVictim conditions + Events on
  both sides (docs/operator.md).
"""

from kubeflow_tpu.operator.reconciler import (  # noqa: F401
    PreemptionPolicy,
    Reconciler,
)
from kubeflow_tpu.operator.fake import FakeApiServer  # noqa: F401
from kubeflow_tpu.operator.informer import (  # noqa: F401
    CachedApiClient,
    Informer,
    Store,
)
from kubeflow_tpu.operator.workqueue import (  # noqa: F401
    ExponentialBackoff,
    TokenBucket,
    WorkQueue,
)
