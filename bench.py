# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Headline benchmark: ResNet-50 training throughput (tpu-cnn) + LM.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Baseline choice: the reference publishes no numbers (BASELINE.md) —
its benchmark harness is tf_cnn_benchmarks ResNet-50, whose
contemporaneous published figure for the reference's era/config
(single P100, batch 32, parameter_server) is ~219 images/sec
(tensorflow.org/performance/benchmarks, 2018). vs_baseline is
images/sec/chip divided by that figure, i.e. "one v5e chip vs the
reference's one-GPU worker".

"extra" carries the secondary BASELINE.md targets measured on the same
run: MFU for the headline model (XLA-counted FLOPs / step time / peak),
and the BERT-base pretraining step time + MFU (the LM target the
reference never had). See PERF.md for the profiling analysis behind
these numbers.
"""

from __future__ import annotations

import json
import sys

REFERENCE_GPU_IMAGES_PER_SEC = 219.0


def controller_main() -> int:
    """`python bench.py --controller`: the operator control-plane
    scale benchmark (no accelerator — pure fake-apiserver; see
    kubeflow_tpu/operator/benchmark.py). 500 jobs with spot churn and
    a poison-job storm, informer reads at two fleet sizes plus the
    direct-read contrast. Asserts the r12 acceptance: churn-phase p99
    event→reconcile latency bounded, and steady-state apiserver
    requests/reconcile FLAT in job count (the informer win). Prints
    ONE JSON line shaped like the headline bench."""
    from kubeflow_tpu.operator.benchmark import (
        run_controller_scale_bench,
        run_elastic_churn_bench,
    )

    jobs = 500
    full = run_controller_scale_bench(
        jobs=jobs, workers=4, churn_kills=50, poison_jobs=5,
        informer_modes=(True, False))
    half = run_controller_scale_bench(
        jobs=jobs // 2, workers=4, churn_kills=25, poison_jobs=5,
        informer_modes=(True,))
    inf_full = next(r for r in full["rows"] if r["informer"])
    inf_half = half["rows"][0]
    direct = next(r for r in full["rows"] if not r["informer"])

    for row in (inf_full, inf_half, direct):
        assert row["converged"], row
        assert row["churn"]["reconverged"], row
    # Poison-storm quarantine + the p99 claim hold on the INFORMER
    # rows. The direct row is the contrast, not the contract: at 500
    # jobs × ~5 reads × 2 ms RTT the 4 workers cannot drain a relist
    # period's enqueues, the queue never empties, and even the poison
    # keys' capped retries starve — the saturation the informer
    # rebuild removes (its latency column records the collapse).
    for row in (inf_full, inf_half):
        assert row["poison_quarantined"] >= 1, row
    # p99 event→reconcile under churn at 500 jobs: the operational
    # reaction-latency claim. Latency samples are EVENT-path only
    # (relist sweeps excluded by the workqueue), so this measures
    # reaction to the kill wave; the 3 s bound leaves room for this
    # box's cgroup throttle while sitting an order of magnitude under
    # the direct-read row's saturated tail.
    p99 = inf_full["churn"]["event_to_reconcile_ms"]["p99"]
    assert p99 < 3000.0, f"churn p99 event->reconcile {p99}ms"
    # QPS flatness: requests/reconcile must NOT grow with job count
    # under informer reads, and must undercut direct reads by a wide
    # margin (direct pays ~4-5 reads+writes per pass).
    rpr_full = inf_full["steady"]["requests_per_reconcile"]
    rpr_half = inf_half["steady"]["requests_per_reconcile"]
    rpr_direct = direct["steady"]["requests_per_reconcile"]
    assert rpr_full < 1.0 and rpr_half < 1.0, (rpr_half, rpr_full)
    assert rpr_full <= rpr_half + 0.5, (rpr_half, rpr_full)
    assert rpr_direct >= 2.0, rpr_direct

    # Elastic churn row (r16 acceptance): under a spot storm that
    # halves every gang's hosts, EVERY elastic job rides through —
    # resized to the survivors, Running, zero restart budget, never
    # even entering Restarting — while every rigid gang deadline-
    # fails and releases its chips. Three runs (the PERF.md r16
    # table records each).
    elastic_runs = []
    for _ in range(3):
        row = run_elastic_churn_bench()
        assert row["converged"], row
        assert row["elastic_rode_through"] == row["elastic_jobs"], row
        assert row["rigid_deadline_failed"] == row["rigid_jobs"], row
        assert row["elastic_reconverge_seconds"] >= 0.0, row
        # Elastic reconvergence beats the rigid deadline by
        # construction: the resize is event-latency, the rigid
        # failure waits out the full scheduling deadline.
        assert (row["elastic_reconverge_seconds"]
                < row["rigid_failed_seconds"]), row
        elastic_runs.append({
            "elastic_rode_through": row["elastic_rode_through"],
            "elastic_reconverge_s": row["elastic_reconverge_seconds"],
            "rigid_deadline_failed": row["rigid_deadline_failed"],
            "rigid_failed_s": row["rigid_failed_seconds"],
            "gang_resizes": row["gang_resizes"],
        })

    print(json.dumps({
        "metric": "controller_churn_p99_event_to_reconcile_ms",
        "value": p99,
        "unit": f"ms p99 at {jobs} jobs + 50-pod drain wave "
                f"(informer reads, 4 workers)",
        "vs_baseline": None,  # the reference never measured its operator
        "extra": {
            "informer_500": {
                "converge_s": inf_full["converge_seconds"],
                "churn_reconverge_s":
                    inf_full["churn"]["reconverge_seconds"],
                "steady_requests_per_reconcile": rpr_full,
                "steady_qps": inf_full["steady"]["qps"],
            },
            "informer_250": {
                "converge_s": inf_half["converge_seconds"],
                "steady_requests_per_reconcile": rpr_half,
                "steady_qps": inf_half["steady"]["qps"],
            },
            "direct_500": {
                "converge_s": direct["converge_seconds"],
                "churn_p99_ms":
                    direct["churn"]["event_to_reconcile_ms"]["p99"],
                "steady_requests_per_reconcile": rpr_direct,
                "steady_qps": direct["steady"]["qps"],
            },
            "poison_quarantined": inf_full["poison_quarantined"],
            "elastic_churn": elastic_runs,
        },
    }))
    return 0


def serving_overload_main() -> int:
    """`python bench.py --serving-overload`: offered-load sweep past
    capacity with deadline-aware shedding on vs off (ISSUE 3
    acceptance: goodput ≈ capacity at 2× offered load with shedding,
    collapse without). Pure serving stack — runs the same on CPU and
    chip; prints ONE JSON line shaped like the headline bench."""
    from kubeflow_tpu.utils.platform import sync_platform_from_env

    sync_platform_from_env()

    from kubeflow_tpu.serving.benchmark import (
        OverloadBenchConfig,
        run_overload_benchmark,
    )

    result = run_overload_benchmark(OverloadBenchConfig())
    worst = max(OverloadBenchConfig().offered_x)
    on = [r for r in result["phases"] if r["shedding"]]
    off = [r for r in result["phases"] if not r["shedding"]]
    print(json.dumps({
        "metric": "serving_overload_goodput_vs_capacity",
        "value": result["goodput_overload_on_vs_capacity"],
        "unit": (f"goodput/capacity at {worst}x offered load, "
                 f"shedding on (ceiling "
                 f"{result['goodput_ceiling_rps']} rps)"),
        "vs_baseline": None,  # the reference had no overload story
        "extra": {
            "capacity_rps": result["capacity_rps"],
            "goodput_ceiling_rps": result["goodput_ceiling_rps"],
            "deadline_ms": result["deadline_ms"],
            "never_dispatched_ok": result["never_dispatched_ok"],
            "goodput_off_vs_capacity": result[
                "goodput_overload_off_vs_capacity"],
            **{f"on_x{r['offered_x']}_{k}": r[k]
               for r in on for k in ("goodput_rps", "shed", "expired",
                                     "ok_p50_ms", "ok_p99_ms")
               if k in r},
            **{f"off_x{r['offered_x']}_{k}": r[k]
               for r in off
               for k in ("goodput_rps", "client_timeout", "ok_p50_ms",
                         "ok_p99_ms")
               if k in r},
        },
    }))
    return 0


def router_main() -> int:
    """`python bench.py --router`: pooled-proxy scaling sweep over
    1→3 in-process stub backends + a mid-load backend kill (ISSUE 5
    acceptance: ≥2.5× aggregate throughput at 3 replicas, no
    in-deadline request lost on failover). Sleep-based service times,
    so the scaling ratio survives this box's CPU throttling (see
    kubeflow_tpu/scaling/benchmark.py + PERF.md r10); prints ONE JSON
    line shaped like the headline bench."""
    from kubeflow_tpu.scaling.benchmark import (
        RouterBenchConfig,
        run_role_split_benchmark,
        run_router_benchmark,
    )

    result = run_router_benchmark(RouterBenchConfig())
    rows = {r["replicas"]: r for r in result["rows"]}
    failover = result.get("failover", {})
    scaling = result.get("throughput_scaling", 0.0)
    # Mixed prompt/decode load over a specialized fleet (ISSUE 10):
    # role-split routing must beat role-blind on goodput at the SAME
    # offered load. Sleep-based service rates, so the ratio survives
    # this box's CPU throttling like the scaling phase does.
    role = run_role_split_benchmark()
    print(json.dumps({
        "metric": "router_throughput_scaling",
        "value": scaling,
        "unit": (f"aggregate rps at {result.get('top_replicas')} "
                 f"replicas vs 1, pooled proxy "
                 f"({result['config']['balancer']}, "
                 f"{result['config']['clients']} closed-loop clients, "
                 f"{result['config']['service_time_s'] * 1e3:.0f} ms "
                 f"simulated service)"),
        "vs_baseline": None,  # the reference never measured its fleet
        "extra": {
            **{f"r{n}_{k}": row[k]
               for n, row in sorted(rows.items())
               for k in ("rps", "p50_ms", "p99_ms", "errors",
                         "utilization", "router_overhead_p50_ms",
                         "speedup_vs_1")
               if k in row},
            **{f"failover_{k}": v for k, v in failover.items()},
            "role_split_goodput_rps":
                role["phases"]["role_split"]["goodput_rps"],
            "role_blind_goodput_rps":
                role["phases"]["role_blind"]["goodput_rps"],
            "role_goodput_ratio": role["goodput_ratio"],
            "role_offered_rps": role["config"]["offered_rps"],
        },
    }))
    return 0 if scaling >= 2.5 and role["role_split_wins"] else 1


def chaos_main() -> int:
    """`python bench.py --chaos`: gray-failure resilience sweep
    (ISSUE 13 acceptance). A 3-replica stub fleet behind the pooled
    proxy, clean vs gray — one replica browned out to 10× latency
    (its /healthz stays green) and one severing every first-leg token
    stream after 5 events. Asserts, 3 runs in a row: brownout
    soft-eject engages within 2 probe-equivalent windows, gray-fleet
    goodput ≥0.9× clean, gray p99-of-successes within the deadline,
    and every surviving stream's stitched token sequence bitwise
    correct (resume legs included — the ok_stream count only admits
    exact sequences). Sleep-based service so the ratios survive this
    box's CPU throttling (PERF.md r9 policy); prints ONE JSON line
    shaped like the headline bench."""
    from kubeflow_tpu.scaling.benchmark import (
        ChaosBenchConfig,
        run_chaos_benchmark,
    )

    runs = []
    for _ in range(3):
        result = run_chaos_benchmark(ChaosBenchConfig())
        det = result["detection"]
        assert det["soft_ejected"], result
        assert det["eject_probe_windows"] <= 2.0, det
        assert result["goodput_ratio"] >= 0.9, result
        assert result["p99_within_deadline"], result
        assert result["gray"]["ok_stream"] > 0, result
        assert det["stream_kills"] > 0, det  # chaos actually bit
        runs.append(result)
    last = runs[-1]
    print(json.dumps({
        "metric": "chaos_goodput_ratio",
        "value": min(r["goodput_ratio"] for r in runs),
        "unit": (f"worst gray/clean goodput over 3 runs "
                 f"({last['config']['replicas']} replicas, one at "
                 f"{last['config']['brownout_multiplier']}x latency "
                 f"+ one killing streams after "
                 f"{last['config']['kill_after_events']} events, "
                 f"{last['config']['offered_fraction']}x capacity "
                 f"open-loop)"),
        "vs_baseline": None,  # r10's fleet had no gray-failure story
        "extra": {
            "runs": [{
                "goodput_ratio": r["goodput_ratio"],
                "eject_probe_windows":
                    r["detection"]["eject_probe_windows"],
                "stream_kills": r["detection"]["stream_kills"],
                "gray_ok_stream": r["gray"]["ok_stream"],
                "gray_p99_ms": r["gray"]["ok_p99_ms"],
                "clean_p99_ms": r["clean"]["ok_p99_ms"],
                "gray_goodput_rps": r["gray"]["goodput_rps"],
                "clean_goodput_rps": r["clean"]["goodput_rps"],
            } for r in runs],
            "deadline_ms": last["config"]["deadline_ms"],
        },
    }))
    return 0


def tenants_main() -> int:
    """`python bench.py --tenants`: the noisy-neighbor isolation
    sweep (ISSUE 14 acceptance, ROADMAP #6 criterion). One tenant
    offers 4× its quota against three compliant tenants at 0.8×,
    isolation off vs on over the same sleep-priced stub model
    (ratios survive box throttling — the r17 chaos-bench policy).
    Asserts, 3 runs in a row: with isolation ON no compliant
    tenant's p99 crosses its deadline, compliant tenants see ZERO
    quota sheds (never a global shed for someone else's burst),
    ≥95% of compliant requests are served, and the noisy tenant's
    excess bounces as ITS OWN structured 429s. Hermetic — no
    cluster, no accelerator; this is also the ci-e2e
    `serving-tenancy` gate. Prints ONE JSON line shaped like the
    headline bench."""
    from kubeflow_tpu.serving.benchmark import (
        TenantBenchConfig,
        run_tenant_benchmark,
    )

    runs = []
    for _ in range(3):
        result = run_tenant_benchmark(TenantBenchConfig())
        assert result["isolation_ok"], result
        assert result["noisy_quota_sheds"] > 0, result
        # The contrast phase really was an overload: without
        # isolation the same offered load cost compliant tenants
        # real failures.
        assert result["compliant_failed_off"] > 0, result
        runs.append(result)
    last = runs[-1]
    print(json.dumps({
        "metric": "tenant_compliant_p99_ms",
        "value": max(r["compliant_p99_on_ms"] for r in runs),
        "unit": (f"worst compliant-tenant p99 over 3 runs with "
                 f"isolation on (noisy tenant at "
                 f"{last['config']['noisy_x']}x quota, "
                 f"{last['config']['compliant_tenants']} compliant "
                 f"at {last['config']['compliant_x']}x, deadline "
                 f"{last['config']['deadline_ms']:.0f} ms)"),
        "vs_baseline": None,  # r17 shed globally: no per-tenant story
        "extra": {
            "runs": [{
                "compliant_p99_on_ms": r["compliant_p99_on_ms"],
                "compliant_p99_off_ms": r["compliant_p99_off_ms"],
                "compliant_failed_off": r["compliant_failed_off"],
                "compliant_failed_on": r["compliant_failed_on"],
                "noisy_quota_sheds": r["noisy_quota_sheds"],
                "noisy_ok": r["phases"]["isolation_on"]["tenants"][
                    "noisy"]["ok"],
            } for r in runs],
            "capacity_rps": last["capacity_rps"],
            "fair_share_rps": last["fair_share_rps"],
            "offered_rates_rps": last["offered_rates_rps"],
            "deadline_ms": last["config"]["deadline_ms"],
        },
    }))
    return 0


def obs_overhead_main() -> int:
    """`python bench.py --obs-overhead`: serving-throughput cost of
    leaving metrics + tracing ON (ISSUE 4 acceptance: <2%; since
    ISSUE 15 the measurement runs WITH span shipping enabled — the
    export-queue append rides the hot path, the rate-capped shipper
    pushes to a real in-process collector SpanStore). Drives the
    micro-batcher directly with interleaved obs-off/obs-on phases
    (socket jitter would drown a 2% effect); prints ONE JSON line
    shaped like the headline bench."""
    from kubeflow_tpu.utils.platform import sync_platform_from_env

    sync_platform_from_env()

    from kubeflow_tpu.serving.benchmark import (
        ObsOverheadConfig,
        run_obs_overhead_benchmark,
    )

    result = run_obs_overhead_benchmark(ObsOverheadConfig())
    print(json.dumps({
        "metric": "serving_obs_overhead_pct",
        "value": result["overhead_pct"],
        "unit": (f"% of per-request service CPU spent on "
                 f"metrics+tracing ({result['model']}, "
                 f"{result['concurrency']} clients; component cost / "
                 f"median service cost — see ObsOverheadConfig)"),
        "vs_baseline": None,  # the reference had no metrics at all
        "extra": {k: result[k] for k in
                  ("obs_cost_per_request_us", "obs_cost_breakdown_us",
                   "request_cpu_us", "rps_obs_off", "rps_obs_on",
                   "rps_off_rounds", "rps_on_rounds",
                   "ab_wall_overhead_pct", "under_2pct",
                   "requests_per_phase", "span_shipping")},
    }))
    return 0 if result["under_2pct"] else 1


def slo_main() -> int:
    """`python bench.py --slo`: the r8 overload sweep with the fleet
    telemetry pipeline attached (ISSUE 9 acceptance): the collector
    scrapes the serving registry every 250 ms, the deadline SLO's
    compressed fast-burn window fires during the 2× phase and
    resolves after recovery (Event + kft-alerts ConfigMap published),
    and the collector's component-timed cycle cost stays ≤2% (the r9
    obs budget). Prints ONE JSON line shaped like the headline
    bench."""
    from kubeflow_tpu.utils.platform import sync_platform_from_env

    sync_platform_from_env()

    from kubeflow_tpu.serving.benchmark import (
        SloBenchConfig,
        run_slo_benchmark,
    )

    result = run_slo_benchmark(SloBenchConfig())
    ok = (result["alert_fired_during_overload"]
          and result["alert_resolved_after"]
          and result["alerts_configmap_published"]
          and result["under_2pct"])
    print(json.dumps({
        "metric": "slo_collector_overhead_pct",
        "value": result["collector_overhead_pct"],
        "unit": (f"% of one core at a "
                 f"{result['collector_interval_ms']:.0f} ms scrape "
                 f"interval (cycle {result['collector_cycle_ms']} ms: "
                 f"fetch + strict parse + ingest + burn-rate "
                 f"evaluation)"),
        "vs_baseline": None,  # the reference had no alerting at all
        "extra": {
            "alert_fired_during_overload":
                result["alert_fired_during_overload"],
            "alert_resolved_after": result["alert_resolved_after"],
            "alert_events": result["alert_events"],
            "alerts_configmap_published":
                result["alerts_configmap_published"],
            "alert_timeline": [
                {k: h[k] for k in ("to", "window")}
                for h in result["alert_timeline"]],
            "capacity_rps": result["capacity_rps"],
            "store_series": result["store_series"],
            "scrape_cycles": result["scrape_cycles"],
            "under_2pct": result["under_2pct"],
            **{f"{r['phase']}_{k}": r[k] for r in result["phases"]
               for k in ("goodput_rps", "shed", "expired", "ok")
               if k in r},
        },
    }))
    return 0 if ok else 1


def continuous_main() -> int:
    """`python bench.py --continuous`: mixed-length open-loop sweep,
    r6 static coalescer vs the continuous-batching engine at the same
    offered load (ISSUE 6 acceptance: the engine wins goodput AND p50,
    streamed rows bitwise-equal to B=1 greedy+sampled, and a short
    request's time-to-first-token mid-decode is well under its long
    neighbor's full decode). Back-to-back phases + component numbers
    per the box-throttle policy (PERF.md r9); prints ONE JSON line
    shaped like the headline bench."""
    from kubeflow_tpu.utils.platform import sync_platform_from_env

    sync_platform_from_env()

    from kubeflow_tpu.serving.benchmark import (
        ContinuousBenchConfig,
        run_continuous_benchmark,
    )

    result = run_continuous_benchmark(ContinuousBenchConfig())
    print(json.dumps({
        "metric": "continuous_batching_goodput_vs_static",
        "value": result["goodput_ratio_at_top"],
        "unit": (f"requested-tokens/s ratio at "
                 f"{max(result['config']['rates_x'])}x static "
                 f"capacity ({result['config']['short_tokens']}/"
                 f"{result['config']['long_tokens']}-token mixed "
                 f"open-loop, {result['config']['slots']} slots)"),
        "vs_baseline": None,  # the r6 coalescer IS the baseline here
        "extra": {
            "static_capacity_rps": result["static_capacity_rps"],
            "static_batch_decode_ms": result["static_batch_decode_ms"],
            "p50_ratio_at_top": result["p50_ratio_at_top"],
            "ttft_short_ms": result["ttft_short_ms"],
            "long_decode_ms": result["long_decode_ms"],
            "ttft_vs_long_decode": result["ttft_vs_long_decode"],
            "bitwise_greedy_ok": result["bitwise_greedy_ok"],
            "bitwise_sampled_ok": result["bitwise_sampled_ok"],
            **{f"x{r['offered_x']}_{stack}_{k}": r[stack][k]
               for r in result["rows"]
               for stack in ("static", "continuous")
               for k in ("goodput_tokens_per_s", "p50_ms",
                         "short_p50_ms", "p99_ms")
               if k in r[stack]},
            **{f"x{r['offered_x']}_{k}": r[k]
               for r in result["rows"]
               for k in ("goodput_ratio", "p50_ratio")},
        },
    }))
    return 0 if result["continuous_wins"] else 1


def prefix_main() -> int:
    """`python bench.py --prefix`: open-loop chat replay with a
    shared system prompt, r14 cold-prefill baseline vs the prefix-
    cache engine at the same offered load (ISSUE 11 acceptance: ≥70%
    hit rate cuts mean TTFT ≥3×, bitwise greedy+sampled). Prints ONE
    JSON line shaped like the headline bench.

    With ``--working-set-multiple`` (ISSUE 20 acceptance): a chat
    replay whose prefix working set is 4× the HBM page pool, r15
    HBM-only engine vs the tiered engine (host-RAM spill). Tiering
    must hold ≥70% effective hit rate where the baseline collapses,
    bitwise greedy+sampled throughout."""
    from kubeflow_tpu.utils.platform import sync_platform_from_env

    sync_platform_from_env()

    if "--working-set-multiple" in sys.argv:
        return tiered_prefix_main()

    from kubeflow_tpu.serving.benchmark import (
        PrefixBenchConfig,
        run_prefix_benchmark,
    )

    result = run_prefix_benchmark(PrefixBenchConfig())
    cfg = result["config"]
    print(json.dumps({
        "metric": "prefix_cache_mean_ttft_ratio",
        "value": result["mean_ttft_ratio"],
        "unit": (f"cold/warm mean TTFT at {result['offered_rps']} "
                 f"rps open-loop ({cfg['system_prompt_len']}-token "
                 f"shared prefix + {cfg['suffix_len']}-token "
                 f"suffixes, {cfg['num_prefixes']} conversations x "
                 f"{cfg['num_requests']} requests)"),
        "vs_baseline": None,  # the cold-prefill engine IS the baseline
        "extra": {
            "hit_rate": result["hit_rate"],
            "cold_mean_ttft_ms": result["cold"]["mean_ttft_ms"],
            "warm_mean_ttft_ms": result["warm"]["mean_ttft_ms"],
            "cold_p99_ttft_ms": result["cold"]["p99_ttft_ms"],
            "warm_p99_ttft_ms": result["warm"]["p99_ttft_ms"],
            "cold_request_ms": result["cold_request_ms"],
            "saved_prefill_tokens":
                result["prefix_stats"]["saved_prefill_tokens"],
            "evicted_pages": result["prefix_stats"]["evicted_pages"],
            "bitwise_greedy_ok": result["bitwise_greedy_ok"],
            "bitwise_sampled_ok": result["bitwise_sampled_ok"],
            "prefill_role_hits": result["prefill_role_hits"],
            "bitwise_handoff_ok": result["bitwise_handoff_ok"],
        },
    }))
    return 0 if result["prefix_wins"] else 1


def tiered_prefix_main() -> int:
    """`python bench.py --prefix --working-set-multiple`: tiered KV
    memory acceptance (ISSUE 20). Prints ONE JSON line; also drops
    the tier-stats calibration document under $KFT_OBS_DIR for the
    CI artifact sweep (collect-obs) and the fleet simulator's
    prefix-hit service class (`bench.py --sim` phase 3)."""
    import os

    from kubeflow_tpu.serving.benchmark import (
        TieredPrefixBenchConfig,
        run_tiered_prefix_benchmark,
    )

    result = run_tiered_prefix_benchmark(TieredPrefixBenchConfig())
    # Same default root as citests/artifacts.py collect_obs(), so the
    # CI artifact sweep picks the document up with or without the env
    # var set.
    obs_dir = os.environ.get("KFT_OBS_DIR", "/tmp/kft-obs")
    os.makedirs(obs_dir, exist_ok=True)
    with open(os.path.join(obs_dir, "kv_tier_stats.json"), "w") as f:
        json.dump(result["tier_stats"], f, indent=1, sort_keys=True)
    host = result["host_tier"]
    print(json.dumps({
        "metric": "tiered_kv_effective_hit_rate",
        "value": result["tiered"]["effective_hit_rate"],
        "unit": (f"measured-phase prefix hit rate at a "
                 f"{result['working_set_multiple']}x working-set/"
                 f"HBM-pool multiple ({result['working_set_pages']} "
                 f"prefix pages over {result['hbm_pool_pages']} "
                 f"usable pages, {result['config']['cycles']} cyclic "
                 f"revisit cycles; acceptance >= 0.70 where the "
                 f"HBM-only baseline collapses)"),
        "vs_baseline": result["baseline"]["effective_hit_rate"],
        "extra": {
            "baseline_hit_rate":
                result["baseline"]["effective_hit_rate"],
            "baseline_mean_request_ms":
                result["baseline"]["mean_request_ms"],
            "tiered_mean_request_ms":
                result["tiered"]["mean_request_ms"],
            "host_spilled_blocks": host["spilled_blocks"],
            "host_readopted_blocks": host["readopted_blocks"],
            "host_evicted_blocks": host["evicted_blocks"],
            "host_resident_blocks": host["resident_blocks"],
            "sampled_readopted_blocks":
                result["sampled_readopted_blocks"],
            "bitwise_greedy_ok": result["bitwise_greedy_ok"],
            "bitwise_sampled_ok": result["bitwise_sampled_ok"],
        },
    }))
    return 0 if result["tiering_holds"] else 1


def speculative_main() -> int:
    """`python bench.py --speculative`: vanilla vs strong-draft vs
    weak-draft decode engines over one request set (ISSUE 16
    acceptance: bitwise greedy+sampled under speculation, nonzero
    acceptance, and < 1 verifier forwards per emitted token). Prints
    ONE JSON line shaped like the headline bench."""
    from kubeflow_tpu.utils.platform import sync_platform_from_env

    sync_platform_from_env()

    from kubeflow_tpu.serving.benchmark import (
        SpeculativeBenchConfig,
        run_speculative_benchmark,
    )

    result = run_speculative_benchmark(SpeculativeBenchConfig())
    cfg = result["config"]
    print(json.dumps({
        "metric": "spec_decode_verify_forwards_per_token",
        "value": result["verify_forwards_per_token"],
        "unit": (f"verifier forwards per emitted token, strong draft "
                 f"k={cfg['draft_tokens']} "
                 f"({cfg['num_requests']} requests x "
                 f"{cfg['new_tokens']} tokens; vanilla = 1.0)"),
        "vs_baseline": None,  # the vanilla engine IS the baseline
        "extra": {
            "acceptance_rate": result["acceptance_rate"],
            "weak_acceptance_rate":
                result["rows"]["weak"]["acceptance_rate"],
            "sampled_acceptance_rate":
                result["sampled_acceptance_rate"],
            "wall_ratio_vs_vanilla": result["wall_ratio_vs_vanilla"],
            "vanilla_tokens_per_s":
                result["rows"]["vanilla"]["tokens_per_s"],
            "strong_tokens_per_s":
                result["rows"]["strong"]["tokens_per_s"],
            "bitwise_greedy_ok": result["bitwise_greedy_ok"],
            "bitwise_sampled_ok": result["bitwise_sampled_ok"],
        },
    }))
    return 0 if result["speculative_wins"] else 1


def sim_main() -> int:
    """`python bench.py --sim`: trace-calibrated fleet-simulator
    validation (ISSUE 19 acceptance). Phase 1 records three
    closed-loop workloads (1/2/3 stub replicas behind the real
    router), calibrates the sim's service distribution from each
    recording by Little's law, replays them, and asserts sim p99
    within 10% of measured p99 on every workload. Phase 2 replays a
    ramped traffic spike through the PRODUCTION autoscaler twice —
    reactive vs predictive — and asserts predictive beats reactive on
    time-over-SLO without exceeding the replica budget. Phase 2 is a
    pure deterministic sim; phase 1's assertion is a ratio of numbers
    measured in the same recording, so CPU throttling cancels
    (PERF.md r9 policy). Prints ONE JSON line; also drops the full
    validation document under $KFT_OBS_DIR for the CI artifact sweep
    (collect-obs)."""
    import os

    from kubeflow_tpu.scaling.benchmark import (
        SimBenchConfig,
        run_sim_benchmark,
    )

    result = run_sim_benchmark(SimBenchConfig())
    assert result["sim_matches"], result["validation"]
    assert result["predictive_wins"], result["bursty"]
    # Same default root as citests/artifacts.py collect_obs(), so the
    # CI artifact sweep picks the document up with or without the env
    # var set.
    obs_dir = os.environ.get("KFT_OBS_DIR", "/tmp/kft-obs")
    os.makedirs(obs_dir, exist_ok=True)
    with open(os.path.join(obs_dir, "sim_validation.json"), "w") as f:
        json.dump(result, f, indent=1, sort_keys=True)
    worst = max(r["p99_delta_pct"] for r in result["validation"])
    bursty = result["bursty"]
    print(json.dumps({
        "metric": "sim_p99_delta_pct",
        "value": worst,
        "unit": ("worst |sim p99 - measured p99| / measured p99 over "
                 "3 recorded closed-loop workloads (1/2/3 replicas, "
                 "Little's-law service calibration; acceptance "
                 "<= 10%)"),
        "vs_baseline": None,  # first release with a fleet simulator
        "extra": {
            **{f"r{row['replicas']}_{k}": row[k]
               for row in result["validation"]
               for k in ("measured_p99_ms", "sim_p99_ms",
                         "p99_delta_pct")},
            "reactive_time_over_slo_s":
                bursty["reactive"]["time_over_slo_s"],
            "predictive_time_over_slo_s":
                bursty["predictive"]["time_over_slo_s"],
            "reactive_p99_ms": bursty["reactive"]["p99_ms"],
            "predictive_p99_ms": bursty["predictive"]["p99_ms"],
            "predictive_max_replicas":
                bursty["predictive"]["max_replicas"],
            "replica_budget": result["config"]["replica_budget"],
            "slo_ms": result["config"]["slo_ms"],
            # Prefix-hit service class (ROADMAP #7a / ISSUE 20):
            # hit/miss-conditioned service draws calibrated from
            # per-tier hit metrics, vs a flat model at the same mean.
            "prefix_class_hit_rate":
                result["prefix_class"]["hit_rate"],
            "prefix_class_p99_ms":
                result["prefix_class"]["conditioned_p99_ms"],
            "prefix_flat_same_mean_p99_ms":
                result["prefix_class"]["flat_same_mean_p99_ms"],
            "prefix_class_stats_source":
                result["prefix_class"]["stats_source"],
        },
    }))
    return 0 if result["sim_holds"] else 1


def main() -> int:
    if "--controller" in sys.argv:
        return controller_main()
    if "--serving-overload" in sys.argv:
        return serving_overload_main()
    if "--obs-overhead" in sys.argv:
        return obs_overhead_main()
    if "--router" in sys.argv:
        return router_main()
    if "--continuous" in sys.argv:
        return continuous_main()
    if "--prefix" in sys.argv:
        return prefix_main()
    if "--speculative" in sys.argv:
        return speculative_main()
    if "--slo" in sys.argv:
        return slo_main()
    if "--chaos" in sys.argv:
        return chaos_main()
    if "--tenants" in sys.argv:
        return tenants_main()
    if "--sim" in sys.argv:
        return sim_main()
    from kubeflow_tpu.utils.platform import sync_platform_from_env

    # Honor JAX_PLATFORMS from the caller (the session preset pins the
    # tunnel TPU; a JAX_PLATFORMS=cpu bench run must actually get the
    # CPU-smoke path).
    sync_platform_from_env()

    from kubeflow_tpu.training.benchmark import (
        BenchConfig,
        LMBenchConfig,
        LoRABenchConfig,
        run_benchmark,
        run_lm_benchmark,
        run_lora_benchmark,
    )

    import jax

    n = len(jax.devices())
    on_tpu = jax.devices()[0].platform not in ("cpu",)
    config = BenchConfig(
        model="resnet50" if on_tpu else "resnet-test",
        batch_size=256 * n if on_tpu else 32,
        steps=20 if on_tpu else 3,
        warmup_steps=3 if on_tpu else 1,
        # Ghost-batch BN statistics (32 of 256 shuffled rows): the
        # step is BN-stat-HBM-bound; measured 103.7 → 97.2 ms/step
        # (ops/batch_norm.py, PERF.md). Single-chip-only lever — the
        # bench mesh here is one device.
        model_kwargs={"bn_stat_rows": 32} if (on_tpu and n == 1) else None,
    )
    result = run_benchmark(config)
    per_chip = result["images_per_sec_per_chip"]

    extra = {}
    if "mfu_pct" in result:
        extra[f"{result['model']}_mfu_pct"] = result["mfu_pct"]
        extra[f"{result['model']}_step_time_ms"] = round(
            result["step_time_ms"], 2)
    # ViT-B/16: the tree's highest-MFU model (42% nominal measured,
    # PERF.md) — recorded alongside the CNN headline as the
    # transformer-vision row.
    try:
        vit = run_benchmark(BenchConfig(
            model="vit-b16" if on_tpu else "vit-test",
            # Scale with device count like the headline row so the
            # per-chip batch (256) matches the PERF.md measurement.
            batch_size=256 * n if on_tpu else 16,
            steps=15 if on_tpu else 2,
            warmup_steps=2 if on_tpu else 1,
        ))
        extra[f"{vit['model']}_images_per_sec_per_chip"] = round(
            vit["images_per_sec_per_chip"], 1)
        extra[f"{vit['model']}_step_time_ms"] = round(
            vit["step_time_ms"], 2)
        if "mfu_pct" in vit:
            extra[f"{vit['model']}_mfu_pct"] = vit["mfu_pct"]
    except Exception as e:  # secondary line; never sink the bench
        extra["vit_bench_error"] = str(e)[:200]

    lm_config = LMBenchConfig(
        model="bert-base" if on_tpu else "bert-test",
        batch_size=32 if on_tpu else 8,  # CPU: divisible by the 8-dev mesh
        seq_len=512 if on_tpu else 64,
        steps=10 if on_tpu else 2,
        warmup_steps=2 if on_tpu else 1,
    )
    try:
        lm = run_lm_benchmark(lm_config)
        extra[f"{lm['model']}_step_time_ms"] = round(lm["step_time_ms"], 2)
        extra[f"{lm['model']}_tokens_per_sec"] = round(lm["tokens_per_sec"])
        if "mfu_pct" in lm:
            extra[f"{lm['model']}_mfu_pct"] = lm["mfu_pct"]
    except Exception as e:  # LM line is secondary; never sink the bench
        extra["lm_bench_error"] = str(e)[:200]

    # Expert parallelism priced (VERDICT-r4 next #6): llama-moe-bench
    # (8 experts, top-2) vs its FLOP-matched dense twin — the
    # tokens/s ratio IS the router+dispatch+extra-HBM cost. Measured
    # r5: 84.6 vs 83.0 ms/step (2% — dispatch effectively free at
    # 8k tokens/step on one chip; the delta matches the extra HBM
    # traffic of the 3.4× larger resident FFN parameter set, not
    # router compute). PERF.md has the analysis.
    try:
        if on_tpu:
            moe = run_lm_benchmark(LMBenchConfig(
                model="llama-moe-bench", batch_size=8, seq_len=1024,
                steps=8, warmup_steps=2, objective="causal"))
            twin = run_lm_benchmark(LMBenchConfig(
                model="llama-moe-dense-twin", batch_size=8,
                seq_len=1024, steps=8, warmup_steps=2,
                objective="causal"))
            extra["moe_step_time_ms"] = round(moe["step_time_ms"], 2)
            extra["moe_dense_twin_step_time_ms"] = round(
                twin["step_time_ms"], 2)
            extra["moe_dispatch_overhead_x"] = round(
                moe["step_time_ms"] / twin["step_time_ms"], 3)
            if "mfu_pct" in moe:
                extra["moe_mfu_pct"] = moe["mfu_pct"]
        else:
            # CPU smoke only: llama-moe-test has no FLOP-matched twin
            # registered, so no ratio — a non-matched ratio under the
            # chip row's key would read as "dispatch costs 2×" in the
            # artifact of record.
            moe = run_lm_benchmark(LMBenchConfig(
                model="llama-moe-test", batch_size=8, seq_len=64,
                steps=2, warmup_steps=1, objective="causal"))
            extra["moe_smoke_step_time_ms"] = round(
                moe["step_time_ms"], 2)
    except Exception as e:  # secondary line; never sink the bench
        extra["moe_bench_error"] = str(e)[:200]

    # BASELINE.md stretch row: Llama-2-7B LoRA fine-tune on one chip
    # (frozen bf16 base + rank-16 adapters + remat fits 16 GB HBM).
    # Measured r2: 312 ms/step at B=1/L=1024 → ~3.3k tokens/s/chip.
    lora_config = LoRABenchConfig(
        model="llama2-7b" if on_tpu else "llama-test",
        lora_rank=16,
        batch_size=1 if on_tpu else 8,
        seq_len=1024 if on_tpu else 32,
        steps=5 if on_tpu else 2,
        warmup_steps=1,
    )
    try:
        ft = run_lora_benchmark(lora_config)
        extra[f"{ft['model']}_lora_step_time_ms"] = round(
            ft["step_time_ms"], 2)
        extra[f"{ft['model']}_lora_tokens_per_sec"] = round(
            ft["tokens_per_sec"])
        if "mfu_pct" in ft:
            extra[f"{ft['model']}_lora_mfu_pct"] = ft["mfu_pct"]
    except Exception as e:  # stretch line; never sink the bench
        extra["lora_bench_error"] = str(e)[:200]

    # Decode throughput (generation serving): 7B KV-cache decode is
    # HBM-bound; measured r2 at 20.1 ms/token ≈ 82% of peak HBM bw.
    # The B=1/4/8 sweep prices batched decode (the serving batcher's
    # coalescing lever): each step streams the whole weight set
    # whatever the batch, so aggregate tokens/s should scale ~B until
    # KV-cache traffic or matmul compute catches up.
    try:
        from kubeflow_tpu.inference.benchmark import (
            DecodeBenchConfig,
            run_decode_batch_sweep,
        )

        # 128 decode steps: short decode segments drown in tunnel
        # timing noise (a 64-token run once measured "1150 GB/s",
        # above physical HBM peak — pure jitter in the differencing).
        sweep = run_decode_batch_sweep(DecodeBenchConfig(
            model="llama2-7b" if on_tpu else "llama-test",
            prompt_len=64 if on_tpu else 8,
            max_new_tokens=128 if on_tpu else 8,
        ), batch_sizes=(1, 4, 8))
        m = sweep["model"]
        for row in sweep["rows"]:
            b = row["batch_size"]
            suffix = "" if b == 1 else f"_b{b}"
            extra[f"{m}_decode_tokens_per_sec{suffix}"] = round(
                row["decode_tokens_per_sec"], 1)
            if b == 1:
                extra[f"{m}_decode_ms_per_token"] = round(
                    row["per_token_ms"], 2)
        extra[f"{m}_decode_batch_speedup_b8"] = sweep[
            "speedup_vs_b1"].get("8")
    except Exception as e:  # secondary line; never sink the bench
        extra["decode_bench_error"] = str(e)[:200]

    try:
        from kubeflow_tpu.serving.benchmark import (
            ServingBenchConfig,
            run_serving_benchmark,
        )

        serving = run_serving_benchmark(ServingBenchConfig(
            model="inception-v3" if on_tpu else "resnet-test",
            image_hw=299 if on_tpu else 32,
            clients=2, requests_per_client=16, warmup_requests=4,
            transport="both",
        ))
        m = serving["model"]
        extra[f"{m}_serving_p50_ms"] = serving["http_p50_ms"]
        extra[f"{m}_serving_p99_ms"] = serving["http_p99_ms"]
        extra[f"{m}_serving_rps"] = serving["http_throughput_rps"]
        extra[f"{m}_serving_grpc_p50_ms"] = serving["grpc_p50_ms"]
        extra[f"{m}_serving_grpc_p99_ms"] = serving["grpc_p99_ms"]
        extra[f"{m}_serving_grpc_rps"] = serving["grpc_throughput_rps"]
    except Exception as e:  # serving line is secondary too
        extra["serving_bench_error"] = str(e)[:200]

    # LM generation serving (r4): a generate-signature export driven
    # through :generate / gRPC Predict — the serve-side counterpart
    # of the decode row above (llama-test isolates stack overhead;
    # weight streaming is the decode bench's job). The client sweep
    # (r6) measures generate COALESCING through the real server: the
    # micro-batcher folds N concurrent decodes into one KV-cache
    # dispatch, so batches < requests and rps scales with fill.
    try:
        lm_serving = run_serving_benchmark(ServingBenchConfig(
            model="llama-test", clients=2, requests_per_client=8,
            warmup_requests=2, transport="grpc", max_batch=8,
            prompt_len=32, new_tokens=16,
            sweep_clients=(1, 4, 8)))
        extra["llama-test_generate_serving_p50_ms"] = (
            lm_serving["p50_ms"])
        extra["llama-test_generate_serving_rps"] = (
            lm_serving["throughput_rps"])
        extra["llama-test_generate_direct_ms"] = (
            lm_serving["direct_model_ms"])
        for row in lm_serving.get("sweep", ()):
            n = row["clients"]
            extra[f"llama-test_generate_rps_c{n}"] = (
                row["throughput_rps"])
            extra[f"llama-test_generate_batch_fill_c{n}"] = (
                row["mean_batch_fill"])
    except Exception as e:  # secondary line; never sink the bench
        extra["lm_serving_bench_error"] = str(e)[:200]

    print(
        json.dumps(
            {
                "metric": f"{result['model']}_train_images_per_sec_per_chip",
                "value": round(per_chip, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(per_chip / REFERENCE_GPU_IMAGES_PER_SEC, 3),
                "extra": extra,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
