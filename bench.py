"""Headline benchmark: ResNet-50 training throughput (tpu-cnn).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline choice: the reference publishes no numbers (BASELINE.md) —
its benchmark harness is tf_cnn_benchmarks ResNet-50, whose
contemporaneous published figure for the reference's era/config
(single P100, batch 32, parameter_server) is ~219 images/sec
(tensorflow.org/performance/benchmarks, 2018). vs_baseline is
images/sec/chip divided by that figure, i.e. "one v5e chip vs the
reference's one-GPU worker".
"""

from __future__ import annotations

import json
import sys

REFERENCE_GPU_IMAGES_PER_SEC = 219.0


def main() -> int:
    from kubeflow_tpu.training.benchmark import BenchConfig, run_benchmark

    import jax

    n = len(jax.devices())
    on_tpu = jax.devices()[0].platform not in ("cpu",)
    config = BenchConfig(
        model="resnet50" if on_tpu else "resnet-test",
        batch_size=256 * n if on_tpu else 32,
        steps=20 if on_tpu else 3,
        warmup_steps=3 if on_tpu else 1,
    )
    result = run_benchmark(config)
    per_chip = result["images_per_sec_per_chip"]
    print(
        json.dumps(
            {
                "metric": f"{result['model']}_train_images_per_sec_per_chip",
                "value": round(per_chip, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(per_chip / REFERENCE_GPU_IMAGES_PER_SEC, 3),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
