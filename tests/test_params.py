# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Param system tests: overlay precedence, coercion, required params."""

import pytest

from kubeflow_tpu.params import Param, ParamSet, REQUIRED


def specs():
    return [
        Param("name", REQUIRED, "string", "component name"),
        Param("replicas", 1, "int"),
        Param("report_usage", "false", "bool"),
        Param("disks", "", "array"),
    ]


def test_defaults_resolve():
    ps = ParamSet(specs()).overlay({"name": "x"})
    out = ps.resolve()
    assert out == {"name": "x", "replicas": 1, "report_usage": False, "disks": []}


def test_missing_required_raises():
    with pytest.raises(ValueError, match="name"):
        ParamSet(specs()).resolve()


def test_overlay_precedence():
    ps = (
        ParamSet(specs())
        .overlay({"name": "x", "replicas": "2"})
        .overlay({"replicas": "3"})
    )
    assert ps.resolve()["replicas"] == 3


def test_string_coercion_at_boundary():
    out = (
        ParamSet(specs())
        .overlay({"name": "x", "report_usage": "true", "disks": "d1,d2"})
        .resolve()
    )
    assert out["report_usage"] is True
    assert out["disks"] == ["d1", "d2"]


def test_unknown_param_rejected():
    with pytest.raises(KeyError, match="bogus"):
        ParamSet(specs()).overlay({"bogus": 1})


def test_duplicate_param_rejected():
    with pytest.raises(ValueError, match="duplicate"):
        ParamSet([Param("a", 1, "int"), Param("a", 2, "int")])


def test_none_overlay_cannot_bypass_required():
    with pytest.raises(ValueError, match="name"):
        ParamSet(specs()).overlay({"name": None}).resolve()


def test_nullable_param_allows_none():
    ps = ParamSet([Param("opt", None, "string")])
    assert ps.resolve()["opt"] is None
    assert ps.overlay({"opt": None}).resolve()["opt"] is None


def test_overlay_immutable():
    base = ParamSet(specs())
    base.overlay({"name": "x"})
    with pytest.raises(ValueError):
        base.resolve()  # original unchanged, still missing required
