"""TPUJob dashboard served against the fake apiserver (the hermetic
equivalent of the reference's TFJob UI tier, tf-job.libsonnet:271-458)."""

import json

import tornado.testing

from kubeflow_tpu.dashboard.server import make_app
from kubeflow_tpu.manifests.tpujob import KIND
from kubeflow_tpu.operator.fake import FakeApiServer
from kubeflow_tpu.operator.reconciler import JOB_LABEL


def _job(name, namespace="default", phase="Running", restarts=1):
    return {
        "apiVersion": "kubeflow.org/v1alpha1",
        "kind": KIND,
        "metadata": {"name": name, "namespace": namespace},
        "spec": {"replicaSpecs": [
            {"replicaType": "COORDINATOR", "replicas": 1},
            {"replicaType": "TPU_WORKER", "replicas": 4},
        ]},
        "status": {"phase": phase, "restartCount": restarts},
    }


class DashboardTest(tornado.testing.AsyncHTTPTestCase):
    def get_app(self):
        self.api = FakeApiServer()
        self.api.create(_job("mnist", phase="Running"))
        self.api.create(_job("bert", namespace="research",
                             phase="Restarting", restarts=2))
        self.api.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "mnist-tpu-worker-0",
                         "namespace": "default",
                         "labels": {JOB_LABEL: "mnist"}},
            "status": {"phase": "Running"},
        })
        return make_app(self.api)

    def test_health(self):
        resp = self.fetch("/healthz")
        assert resp.code == 200

    def test_list_jobs(self):
        resp = self.fetch("/tpujobs/api/tpujob")
        assert resp.code == 200
        items = json.loads(resp.body)["items"]
        assert {i["name"] for i in items} == {"mnist", "bert"}
        bert = next(i for i in items if i["name"] == "bert")
        assert bert["phase"] == "Restarting"
        assert bert["restartCount"] == 2
        assert bert["replicas"] == {"COORDINATOR": 1, "TPU_WORKER": 4}

    def test_job_detail_includes_gang_pods(self):
        resp = self.fetch("/tpujobs/api/tpujob/default/mnist")
        assert resp.code == 200
        detail = json.loads(resp.body)
        assert detail["summary"]["phase"] == "Running"
        assert detail["pods"] == [
            {"name": "mnist-tpu-worker-0", "phase": "Running"}]

    def test_job_detail_404(self):
        resp = self.fetch("/tpujobs/api/tpujob/default/nope")
        assert resp.code == 404

    def test_ui_renders_table(self):
        resp = self.fetch("/tpujobs/ui/")
        assert resp.code == 200
        page = resp.body.decode()
        assert "mnist" in page and "bert" in page
        assert "Restarting" in page
        assert "TPU_WORKER×4" in page

    def test_root_redirects_to_ui(self):
        resp = self.fetch("/", follow_redirects=False)
        assert resp.code in (301, 302)
        assert resp.headers["Location"] == "/tpujobs/ui/"
