# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""TPUJob dashboard served against the fake apiserver (the hermetic
equivalent of the reference's TFJob UI tier, tf-job.libsonnet:271-458)."""

import json

import tornado.testing

from kubeflow_tpu.dashboard.server import make_app
from kubeflow_tpu.manifests.tpujob import KIND
from kubeflow_tpu.operator.fake import FakeApiServer
from kubeflow_tpu.operator.reconciler import JOB_LABEL


def _job(name, namespace="default", phase="Running", restarts=1):
    return {
        "apiVersion": "kubeflow.org/v1alpha1",
        "kind": KIND,
        "metadata": {"name": name, "namespace": namespace},
        "spec": {"replicaSpecs": [
            {"replicaType": "COORDINATOR", "replicas": 1},
            {"replicaType": "TPU_WORKER", "replicas": 4},
        ]},
        "status": {"phase": phase, "restartCount": restarts},
    }


class DashboardTest(tornado.testing.AsyncHTTPTestCase):
    def get_app(self):
        import tempfile

        self.api = FakeApiServer()
        self.api.create(_job("mnist", phase="Running"))
        self.api.create(_job("bert", namespace="research",
                             phase="Restarting", restarts=2))
        self.api.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "mnist-tpu-worker-0",
                         "namespace": "default",
                         "labels": {JOB_LABEL: "mnist"}},
            "status": {"phase": "Running"},
        })
        self.trace_root = tempfile.mkdtemp()
        return make_app(self.api, trace_root=self.trace_root)

    def test_health(self):
        resp = self.fetch("/healthz")
        assert resp.code == 200

    def test_list_jobs(self):
        resp = self.fetch("/tpujobs/api/tpujob")
        assert resp.code == 200
        items = json.loads(resp.body)["items"]
        assert {i["name"] for i in items} == {"mnist", "bert"}
        bert = next(i for i in items if i["name"] == "bert")
        assert bert["phase"] == "Restarting"
        assert bert["restartCount"] == 2
        assert bert["replicas"] == {"COORDINATOR": 1, "TPU_WORKER": 4}

    def test_job_detail_includes_gang_pods(self):
        resp = self.fetch("/tpujobs/api/tpujob/default/mnist")
        assert resp.code == 200
        detail = json.loads(resp.body)
        assert detail["summary"]["phase"] == "Running"
        assert [(p["name"], p["phase"]) for p in detail["pods"]] == [
            ("mnist-tpu-worker-0", "Running")]

    def test_job_detail_404(self):
        resp = self.fetch("/tpujobs/api/tpujob/default/nope")
        assert resp.code == 404

    def test_per_pod_drilldown_fields_and_conditions(self):
        """VERDICT-r4 #8: the detail view carries per-replica
        phase/slice/exit-code/drained plus the job's conditions, and
        the summary exposes the last transition."""
        from kubeflow_tpu.operator.reconciler import (
            REPLICA_INDEX_LABEL,
            REPLICA_TYPE_LABEL,
            SLICE_INDEX_LABEL,
        )
        from kubeflow_tpu.training.launcher import DRAIN_EXIT_CODE

        self.api.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "mnist-s1-tpu-worker-0",
                         "namespace": "default",
                         "labels": {JOB_LABEL: "mnist",
                                    REPLICA_TYPE_LABEL: "TPU_WORKER",
                                    REPLICA_INDEX_LABEL: "0",
                                    SLICE_INDEX_LABEL: "1"}},
        })
        self.api.set_pod_terminated("default", "mnist-s1-tpu-worker-0",
                                    DRAIN_EXIT_CODE)
        self.api.patch(KIND, "default", "mnist",
                       lambda o: o["status"].update({"conditions": [
                           {"type": "Running", "status": "True",
                            "lastTransitionTime": "2026-07-31T00:00:00",
                            "reason": "all pods up"}]}))
        resp = self.fetch("/tpujobs/api/tpujob/default/mnist")
        detail = json.loads(resp.body)
        drained = next(p for p in detail["pods"]
                       if p["name"] == "mnist-s1-tpu-worker-0")
        assert drained["slice"] == "1"
        assert drained["replicaType"] == "TPU_WORKER"
        assert drained["exitCode"] == DRAIN_EXIT_CODE
        assert drained["drained"] is True
        assert detail["conditions"][0]["type"] == "Running"
        assert detail["summary"]["lastTransitionTime"] == \
            "2026-07-31T00:00:00"
        # HTML drill-down renders the same rows + a log link.
        resp = self.fetch("/tpujobs/ui/job/default/mnist")
        page = resp.body.decode()
        assert "mnist-s1-tpu-worker-0" in page
        assert "(drained)" in page
        assert "logs/mnist-s1-tpu-worker-0" in page
        assert "all pods up" in page
        assert self.fetch("/tpujobs/ui/job/default/nope").code == 404

    def test_job_events_surface_in_detail_and_ui(self):
        """The operator's lifecycle Events ride the detail API and
        the HTML page, filtered to THIS job incarnation (uid) —
        kubectl-describe semantics."""
        from kubeflow_tpu.manifests.tpujob import (
            replica_spec,
            termination_policy,
            tpu_job,
        )
        from kubeflow_tpu.operator.reconciler import Reconciler

        job = tpu_job("evjob", "default", [replica_spec(
            "TPU_WORKER", 1, image="img",
            tpu_accelerator="tpu-v5-lite-podslice",
            tpu_topology="2x4")],
            termination=termination_policy("TPU_WORKER", 0))
        job["metadata"]["uid"] = "uid-ev"
        self.api.create(job)
        r = Reconciler(self.api)
        r.reconcile(self.api.get(KIND, "default", "evjob"))
        self.api.set_pod_phase("default", "evjob-tpu-worker-0",
                               "Failed")
        r.reconcile(self.api.get(KIND, "default", "evjob"))
        # A stale same-name event from a PREVIOUS incarnation must
        # not surface.
        self.api.create({
            "apiVersion": "v1", "kind": "Event",
            "metadata": {"name": "evjob.old", "namespace": "default"},
            "involvedObject": {"kind": KIND, "name": "evjob",
                               "uid": "uid-OLD"},
            "reason": "Pending", "type": "Normal",
            "message": "stale incarnation", "count": 1,
            "lastTimestamp": "2020-01-01T00:00:00"})

        detail = json.loads(
            self.fetch("/tpujobs/api/tpujob/default/evjob").body)
        reasons = [e["reason"] for e in detail["events"]]
        assert "Pending" in reasons and "Restarting" in reasons
        assert all(e["message"] != "stale incarnation"
                   for e in detail["events"])
        warn = next(e for e in detail["events"]
                    if e["reason"] == "Restarting")
        assert warn["type"] == "Warning"
        page = self.fetch("/tpujobs/ui/job/default/evjob").body.decode()
        assert "slice fault" in page
        assert "stale incarnation" not in page

    def test_event_listing_uses_field_selector(self):
        """ADVICE r5: each detail-page click must NOT list every Event
        in the namespace — the name filter runs server-side via
        fieldSelector; clients without the parameter fall back to a
        capped client-side filter."""
        import kubeflow_tpu.dashboard.server as dash

        job = {"metadata": {"name": "mnist", "namespace": "default",
                            "uid": "u1"}}
        for i in range(3):
            self.api.create({
                "apiVersion": "v1", "kind": "Event",
                "metadata": {"name": f"mnist.{i}",
                             "namespace": "default"},
                "involvedObject": {"kind": KIND, "name": "mnist",
                                   "uid": "u1"},
                "reason": f"Mine{i}", "type": "Normal", "message": "",
                "count": 1,
                "lastTimestamp": f"2026-08-01T00:00:0{i}"})
        for i in range(6):
            self.api.create({
                "apiVersion": "v1", "kind": "Event",
                "metadata": {"name": f"noise.{i}",
                             "namespace": "default"},
                "involvedObject": {"kind": KIND, "name": "other",
                                   "uid": "u2"},
                "reason": "Noise", "type": "Normal", "message": "",
                "count": 1,
                "lastTimestamp": f"2020-01-01T00:00:0{i}"})

        api = self.api
        selectors = []

        class Spy:
            def list(self, kind, namespace=None, label_selector=None,
                     field_selector=None):
                selectors.append(field_selector)
                return api.list(kind, namespace, label_selector,
                                field_selector)

        events = dash._job_events(Spy(), "default", "mnist", job)
        assert [e["reason"] for e in events] == [
            "Mine0", "Mine1", "Mine2"]
        assert selectors == [{"involvedObject.name": "mnist"}]

        class Legacy:
            """A client predating field_selector: the fallback filters
            client-side over a CAPPED, newest-first slice."""

            def list(self, kind, namespace=None, label_selector=None):
                return api.list(kind, namespace, label_selector)

        events = dash._job_events(Legacy(), "default", "mnist", job)
        assert [e["reason"] for e in events] == [
            "Mine0", "Mine1", "Mine2"]
        # Cap: with 9 events and a cap of 4, only the NEWEST 4 are
        # scanned — the job's (recent) events survive, ancient noise
        # is never shuttled.
        old_cap = dash._EVENT_FALLBACK_CAP
        dash._EVENT_FALLBACK_CAP = 4
        try:
            events = dash._job_events(Legacy(), "default", "mnist", job)
            assert [e["reason"] for e in events] == [
                "Mine0", "Mine1", "Mine2"]
        finally:
            dash._EVENT_FALLBACK_CAP = old_cap

    def test_pod_log_tail_proxied(self):
        """Log tails flow through the apiserver client; pods outside
        the job 404 even if they exist (route contract narrower than
        the dashboard's RBAC)."""
        self.api.set_pod_log(
            "default", "mnist-tpu-worker-0",
            "\n".join(f"line {i}" for i in range(200)))
        resp = self.fetch("/tpujobs/api/tpujob/default/mnist/logs/"
                          "mnist-tpu-worker-0?tail=5")
        assert resp.code == 200
        lines = resp.body.decode().strip().splitlines()
        assert lines == [f"line {i}" for i in range(195, 200)]
        # A pod that is NOT part of this job: 404.
        self.api.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "other", "namespace": "default",
                         "labels": {}}})
        self.api.set_pod_log("default", "other", "secret")
        resp = self.fetch("/tpujobs/api/tpujob/default/mnist/logs/other")
        assert resp.code == 404
        resp = self.fetch("/tpujobs/api/tpujob/default/mnist/logs/"
                          "mnist-tpu-worker-0?tail=bogus")
        assert resp.code == 400

    def test_ui_renders_table(self):
        resp = self.fetch("/tpujobs/ui/")
        assert resp.code == 200
        page = resp.body.decode()
        assert "mnist" in page and "bert" in page
        assert "Restarting" in page
        assert "TPU_WORKER×4" in page

    def test_create_validates_and_operator_reconciles(self):
        """Round-2 verdict #7: POST a CR through the dashboard, then
        the operator reconciles it into a gang (write-path parity with
        the reference UI, tf-job.libsonnet:271-458)."""
        from kubeflow_tpu.manifests.tpujob import replica_spec, tpu_job
        from kubeflow_tpu.operator.reconciler import Reconciler

        job = tpu_job(
            "fromui", "default",
            [replica_spec("TPU_WORKER", 2,
                          image="ghcr.io/kubeflow-tpu/trainer:v0.1.0",
                          tpu_accelerator="tpu-v5-lite-podslice",
                          tpu_topology="2x4")],
            termination={"chief": {"replicaName": "TPU_WORKER",
                                   "replicaIndex": 0}})
        resp = self.fetch("/tpujobs/api/tpujob", method="POST",
                          body=json.dumps(job))
        assert resp.code == 201, resp.body
        assert json.loads(resp.body)["created"]["name"] == "fromui"

        # The operator picks the created CR up and builds the gang.
        stored = self.api.get(KIND, "default", "fromui")
        Reconciler(self.api).reconcile(stored)
        pods = self.api.list("Pod", "default", {JOB_LABEL: "fromui"})
        assert len(pods) == 2

        # Duplicate create is a clean conflict, not a 500.
        resp = self.fetch("/tpujobs/api/tpujob", method="POST",
                          body=json.dumps(job))
        assert resp.code == 409

    def test_create_rejects_invalid_cr(self):
        bad = {"kind": "TPUJob", "apiVersion": "kubeflow.org/v1alpha1",
               "metadata": {"name": "bad"},
               "spec": {"replicaSpecs": [
                   {"tpuReplicaType": "NOT_A_TYPE", "replicas": 0}]}}
        resp = self.fetch("/tpujobs/api/tpujob", method="POST",
                          body=json.dumps(bad))
        assert resp.code == 400
        details = json.loads(resp.body)["details"]
        assert any("NOT_A_TYPE" in d for d in details)
        assert any("minimum" in d or "below" in d for d in details)
        resp = self.fetch("/tpujobs/api/tpujob", method="POST",
                          body=b"{nope")
        assert resp.code == 400

    def test_delete_removes_job_and_gang(self):
        from kubeflow_tpu.manifests.tpujob import replica_spec, tpu_job
        from kubeflow_tpu.operator.reconciler import Reconciler

        job = tpu_job(
            "togo", "default",
            [replica_spec("TPU_WORKER", 2,
                          image="ghcr.io/kubeflow-tpu/trainer:v0.1.0",
                          tpu_accelerator="tpu-v5-lite-podslice",
                          tpu_topology="2x4")],
            termination={"chief": {"replicaName": "TPU_WORKER",
                                   "replicaIndex": 0}})
        self.api.create(job)
        Reconciler(self.api).reconcile(
            self.api.get(KIND, "default", "togo"))
        assert len(self.api.list("Pod", "default",
                                 {JOB_LABEL: "togo"})) == 2

        resp = self.fetch("/tpujobs/api/tpujob/default/togo",
                          method="DELETE")
        assert resp.code == 200
        assert json.loads(resp.body)["pods_deleted"] == 2
        assert self.api.list("Pod", "default", {JOB_LABEL: "togo"}) == []
        resp = self.fetch("/tpujobs/api/tpujob/default/togo")
        assert resp.code == 404
        resp = self.fetch("/tpujobs/api/tpujob/default/togo",
                          method="DELETE")
        assert resp.code == 404

    def test_ui_form_create(self):
        body = ("name=formjob&namespace=default&workers=2"
                "&image=ghcr.io/kubeflow-tpu/trainer:v0.1.0"
                "&tpu_accelerator=tpu-v5-lite-podslice"
                "&tpu_topology=2x4&command=")
        resp = self.fetch("/tpujobs/ui/create", method="POST",
                          body=body, follow_redirects=False)
        assert resp.code == 302, resp.body
        created = self.api.get(KIND, "default", "formjob")
        assert created["spec"]["replicaSpecs"][0]["replicas"] == 2
        # The form is on the UI page.
        page = self.fetch("/tpujobs/ui/").body.decode()
        assert "/tpujobs/ui/create" in page

    def test_root_redirects_to_ui(self):
        resp = self.fetch("/", follow_redirects=False)
        assert resp.code in (301, 302)
        assert resp.headers["Location"] == "/tpujobs/ui/"


    def test_warning_conditions_surface_in_detail_and_ui(self):
        """ReconcileStalled / DeadlineExceeded (the operator's
        quarantine + gang-deadline surface, r7) ride the summary, the
        detail API's `warnings`, and an HTML banner — while NOT
        stealing the phase-condition transition anchor."""
        self.api.patch(KIND, "default", "mnist",
                       lambda o: o["status"].update({"conditions": [
                           {"type": "ReconcileStalled", "status": "True",
                            "reason": "6 consecutive reconcile failures",
                            "lastTransitionTime": "2026-08-01T00:00:01"},
                           {"type": "Running", "status": "True",
                            "lastTransitionTime": "2026-07-31T00:00:00"},
                       ]}))
        resp = self.fetch("/tpujobs/api/tpujob/default/mnist")
        detail = json.loads(resp.body)
        assert detail["warnings"] == [{
            "type": "ReconcileStalled",
            "reason": "6 consecutive reconcile failures",
            "since": "2026-08-01T00:00:01"}]
        assert detail["summary"]["warnings"] == detail["warnings"]
        # The timeline anchor stays on the PHASE condition.
        assert detail["summary"]["lastTransitionTime"] == \
            "2026-07-31T00:00:00"
        page = self.fetch("/tpujobs/ui/job/default/mnist").body.decode()
        assert "ReconcileStalled" in page
        assert "6 consecutive reconcile failures" in page
        # List view carries the warnings too (dashboards can badge).
        items = json.loads(
            self.fetch("/tpujobs/api/tpujob").body)["items"]
        mnist = next(i for i in items if i["name"] == "mnist")
        assert mnist["warnings"][0]["type"] == "ReconcileStalled"

    def test_deadline_exceeded_condition_in_detail(self):
        """A deadline-failed job shows the DeadlineExceeded banner
        alongside its Failed phase — straight from the reconciler's
        own writes, not hand-built conditions."""
        from kubeflow_tpu.operator import Reconciler
        from kubeflow_tpu.operator.reconciler import DEADLINE_CONDITION

        from tests.test_deadline import (
            _age_pending_condition,
            make_deadline_job,
        )

        self.api.create(make_deadline_job(name="dlweb", deadline=5))
        r = Reconciler(self.api)
        r.reconcile(self.api.get(KIND, "default", "dlweb"))
        _age_pending_condition(self.api, "dlweb", seconds=10)
        r.reconcile(self.api.get(KIND, "default", "dlweb"))

        resp = self.fetch("/tpujobs/api/tpujob/default/dlweb")
        detail = json.loads(resp.body)
        assert detail["summary"]["phase"] == "Failed"
        assert [w["type"] for w in detail["warnings"]] == \
            [DEADLINE_CONDITION]
        page = self.fetch(
            "/tpujobs/ui/job/default/dlweb").body.decode()
        assert DEADLINE_CONDITION in page
        # The deadline Event surfaces in the events table.
        assert any(e["reason"] == DEADLINE_CONDITION
                   for e in detail["events"]), detail["events"]

    def test_preemption_conditions_surface_in_detail_and_ui(self):
        """Preempted rides the warning banner on the victim;
        PreemptedVictim rides the detail `notices` + an info banner on
        the preemptor — both from the reconciler's own preemption
        writes (r12), and both Events in the events table."""
        from kubeflow_tpu.operator import PreemptionPolicy, Reconciler
        from kubeflow_tpu.operator.reconciler import (
            PREEMPTED_CONDITION,
            PREEMPTOR_CONDITION,
        )

        from tests.test_preemption import _age_pending, make_pjob

        r = Reconciler(self.api, preemption=PreemptionPolicy(
            min_interval_seconds=0.0))
        with self.api.as_kubelet():
            # Youngest-loses tie-break: a fresh creationTimestamp
            # makes THIS job the deterministic victim (the fixture's
            # Running "mnist" job carries none).
            self.api.create(make_pjob("victim", priority=0,
                                      created="2026-08-01T00:00:00Z"))
        r.reconcile(self.api.get(KIND, "default", "victim"))
        with self.api.as_kubelet():
            self.api.set_all_pod_phases("default", "Running",
                                        {JOB_LABEL: "victim"})
        r.reconcile(self.api.get(KIND, "default", "victim"))
        with self.api.as_kubelet():
            self.api.create(make_pjob("vip", priority=5, deadline=100))
        r.reconcile(self.api.get(KIND, "default", "vip"))
        _age_pending(self.api, "vip", seconds=60)
        r.reconcile(self.api.get(KIND, "default", "vip"))

        detail = json.loads(
            self.fetch("/tpujobs/api/tpujob/default/victim").body)
        assert [w["type"] for w in detail["warnings"]] == \
            [PREEMPTED_CONDITION]
        assert "vip" in detail["warnings"][0]["reason"]
        assert any(e["reason"] == PREEMPTED_CONDITION
                   for e in detail["events"]), detail["events"]
        page = self.fetch(
            "/tpujobs/ui/job/default/victim").body.decode()
        assert PREEMPTED_CONDITION in page

        detail = json.loads(
            self.fetch("/tpujobs/api/tpujob/default/vip").body)
        assert detail["warnings"] == []  # evicting is not an alert
        assert [n["type"] for n in detail["notices"]] == \
            [PREEMPTOR_CONDITION]
        assert "victim" in detail["notices"][0]["reason"]
        assert detail["summary"]["priority"] == 5
        assert any(e["reason"] == PREEMPTOR_CONDITION
                   for e in detail["events"]), detail["events"]
        page = self.fetch("/tpujobs/ui/job/default/vip").body.decode()
        assert PREEMPTOR_CONDITION in page

    def test_operator_metrics_endpoint(self):
        """GET /tpujobs/api/operator serves the metrics ConfigMap the
        controller publishes — the dashboard and the load bench read
        the same numbers."""
        from kubeflow_tpu.operator.controller import (
            METRICS_CONFIGMAP,
            METRICS_KEY,
        )

        resp = self.fetch("/tpujobs/api/operator")
        assert resp.code == 404  # not publishing yet
        assert json.loads(resp.body)["available"] is False

        metrics = {"workers": 4, "reconciles": 123,
                   "queue": {"depth": 1, "quarantined": ["default/p"]}}
        self.api.create({
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": METRICS_CONFIGMAP,
                         "namespace": "default"},
            "data": {METRICS_KEY: json.dumps(metrics)},
        })
        resp = self.fetch("/tpujobs/api/operator")
        assert resp.code == 200
        payload = json.loads(resp.body)
        assert payload["available"] is True
        assert payload["metrics"] == metrics

    def test_fleet_endpoint_and_ui_section(self):
        """GET /tpujobs/api/fleet serves the ConfigMap the serving
        autoscaler publishes (same pattern as /tpujobs/api/operator),
        and the HTML view renders the fleet section from it."""
        from kubeflow_tpu.scaling.autoscaler import (
            FLEET_CONFIGMAP,
            FLEET_KEY,
        )

        resp = self.fetch("/tpujobs/api/fleet")
        assert resp.code == 404  # autoscaler not publishing yet
        assert json.loads(resp.body)["available"] is False
        page = self.fetch("/tpujobs/ui").body.decode()
        assert "Serving fleet" in page
        assert "No fleet published" in page

        fleet = {
            "replicas": [
                {"address": "10.0.0.1:8500", "reachable": True,
                 "status": "ok", "queue_wait_ms": 80.0,
                 "shed_rate": 0.0, "expired_rate": 0.0,
                 "resident_models": ["llama"]},
                {"address": "10.0.0.2:8500", "reachable": False},
            ],
            "decision": {"action": "scale_up", "reason": "queue_wait",
                         "current": 2, "desired": 3,
                         "mean_queue_wait_ms": 180.0,
                         "target_queue_wait_ms": 100.0,
                         "ratio": 1.8, "replicas_reporting": 1,
                         "age_s": 2.5},
        }
        self.api.create({
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": FLEET_CONFIGMAP,
                         "namespace": "default"},
            "data": {FLEET_KEY: json.dumps(fleet)},
        })
        resp = self.fetch("/tpujobs/api/fleet")
        assert resp.code == 200
        payload = json.loads(resp.body)
        assert payload["available"] is True
        assert payload["fleet"] == fleet
        page = self.fetch("/tpujobs/ui").body.decode()
        assert "10.0.0.1:8500" in page
        assert "unreachable" in page  # the dead replica is visible
        assert "scale_up" in page and "2 → 3" in page

        # A malformed ConfigMap (version skew, hand edit — the RBAC
        # grants patch) must degrade the SECTION, not 500 the page.
        fleet["decision"]["current"] = None
        self.api.patch(
            "ConfigMap", "default", FLEET_CONFIGMAP,
            lambda o: o["data"].update({FLEET_KEY: json.dumps(fleet)}))
        resp = self.fetch("/tpujobs/ui")
        assert resp.code == 200
        assert "Fleet ConfigMap unreadable" in resp.body.decode()

    def test_fleet_table_pages_cell_breaks_down_kv_tiers(self):
        """The Pages cell shows the TIERED picture (ISSUE 20): HBM
        page occupancy, prefix hit rate, host-pool fill and fleet
        fetches — each fragment degrading independently on malformed
        values, and the whole page never 500ing."""
        from kubeflow_tpu.scaling.autoscaler import (
            FLEET_CONFIGMAP,
            FLEET_KEY,
        )

        fleet = {
            "replicas": [
                {"address": "10.0.0.1:8500", "reachable": True,
                 "status": "ok", "role": "decode",
                 "page_occupancy": 0.625, "prefix_hit_rate": 0.9,
                 "host_kv_occupancy": 0.4, "kv_fetch_hits": 12},
                # Host tier only (HBM occupancy not reported).
                {"address": "10.0.0.2:8500", "reachable": True,
                 "status": "ok", "host_kv_occupancy": 0.05},
                # Malformed tier values: the valid HBM fragment must
                # survive; the broken ones just drop out.
                {"address": "10.0.0.3:8500", "reachable": True,
                 "status": "ok", "page_occupancy": 0.5,
                 "host_kv_occupancy": "full",
                 "kv_fetch_hits": "lots"},
            ],
            "decision": {},
        }
        self.api.create({
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": FLEET_CONFIGMAP,
                         "namespace": "default"},
            "data": {FLEET_KEY: json.dumps(fleet)},
        })
        resp = self.fetch("/tpujobs/ui")
        assert resp.code == 200
        page = resp.body.decode()
        assert "62%" in page and "(90% prefix hits)" in page
        assert "host 40%" in page and "12 fleet fetches" in page
        assert "host 5%" in page
        assert "50%" in page
        assert "host full" not in page
        assert "lots fleet fetches" not in page


class TraceTabTest(tornado.testing.AsyncHTTPTestCase):
    """Profiler traces surfaced through the dashboard (SURVEY §5's
    stated rebuild target; VERDICT-r3 missing #3)."""

    def get_app(self):
        import pathlib
        import tempfile

        self.api = FakeApiServer()
        self.trace_root = tempfile.mkdtemp()
        # The jax profiler layout: <job>/plugins/profile/<run>/<host>.xplane.pb
        run = (pathlib.Path(self.trace_root) / "mnist-profile" / "plugins"
               / "profile" / "2026_07_31_05_00_00")
        run.mkdir(parents=True)
        (run / "host0.xplane.pb").write_bytes(b"\x00" * 128)
        (run / "host0.trace.json.gz").write_bytes(b"\x00" * 64)
        (run / "README.txt").write_text("not a trace artifact")
        return make_app(self.api, trace_root=self.trace_root)

    def test_trace_api_lists_runs(self):
        resp = self.fetch("/tpujobs/api/traces")
        assert resp.code == 200
        payload = json.loads(resp.body)
        assert payload["trace_root"] == self.trace_root
        (item,) = payload["items"]
        assert item["job"] == "mnist-profile"
        assert item["run"] == "2026_07_31_05_00_00"
        names = [f["name"] for f in item["files"]]
        assert names == ["host0.trace.json.gz", "host0.xplane.pb"]
        assert all(f["size_bytes"] > 0 for f in item["files"])

    def test_trace_api_empty_root_is_empty_list(self):
        import shutil

        shutil.rmtree(self.trace_root)
        resp = self.fetch("/tpujobs/api/traces")
        assert json.loads(resp.body)["items"] == []

    def test_ui_shows_trace_table(self):
        resp = self.fetch("/tpujobs/ui/")
        body = resp.body.decode()
        assert "Profiler traces" in body
        assert "mnist-profile" in body
        assert "tensorboard --logdir" in body
