# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Cross-request prefix KV cache (ISSUE 11).

The contract under test: with ``EngineConfig.prefix_cache`` on, every
request's output is BITWISE equal to the same request run alone
through ``inference.generate.generate`` at B=1 — greedy and sampled,
including mid-decode joins against shared pages, CoW forks at a
partially matched boundary page, eviction under page pressure, and
warm transfer through the wire handoff blob. Plus the host-side
machinery (ref-counted allocator, radix index) unit-tested and
fuzzed without a model: no FIFO deadlock, no ref-count leak, the
pool drains to zero resident pages after quiesce.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.inference.engine import (
    DecodeEngine,
    EngineConfig,
    PageAllocator,
    PrefixCache,
)
from kubeflow_tpu.inference.generate import generate
from kubeflow_tpu.models.llama import llama_test

CACHE = 64
MAX_PROMPT = 24
PAGE = 4


@pytest.fixture(scope="module")
def model():
    return llama_test(dtype=jnp.float32, cache_size=CACHE)


@pytest.fixture(scope="module")
def params(model):
    ids = jnp.zeros((1, 8), jnp.int32)
    return model.init(jax.random.PRNGKey(0), ids)["params"]


def _reference(model, params, prompt, key, max_new_tokens, **sampling):
    tokens, _ = generate(
        model, params, jnp.asarray(prompt)[None, :],
        max_new_tokens=max_new_tokens, rng=jnp.asarray(key)[None, :],
        prompt_lengths=jnp.asarray([len(prompt)]), **sampling)
    return np.asarray(tokens)[0]


def _prefixed_prompts(prefix_len, suffix_lens, seed=0):
    """Prompts sharing a common ``prefix_len``-token head (the shared
    system prompt) with per-request random suffixes."""
    rng = np.random.RandomState(seed)
    prefix = rng.randint(0, 512, (prefix_len,)).astype(np.int32)
    out = []
    for i, n in enumerate(suffix_lens):
        r = np.random.RandomState(1000 + seed * 100 + i)
        suffix = r.randint(0, 512, (n,)).astype(np.int32)
        out.append(np.concatenate([prefix, suffix]) if n else
                   prefix.copy())
    return out


def _keys(n, base=100):
    return [np.asarray(jax.random.PRNGKey(base + i)) for i in range(n)]


def _assert_drained(engine):
    """Quiesced engine: no slots, no queue, no reservations; cached
    pages are the only residents and a clear() releases them all."""
    st = engine.stats()
    assert st["active_slots"] == 0 and st["queue_depth"] == 0, st
    assert st["reserved_pages"] == 0, st
    engine.kv.allocator.check_invariants()
    if engine.prefix is not None:
        engine.prefix.check_invariants()
        assert st["free_pages"] + st["retained_pages"] == \
            st["total_pages"], f"leaked pages: {st}"
        engine.clear_prefix_cache()
        st = engine.stats()
    assert st["free_pages"] == st["total_pages"], f"leaked pages: {st}"
    engine.kv.allocator.check_invariants()


# -- engine: bitwise equality on shared pages ------------------------------


def test_prefix_hits_bitwise_equal_greedy_including_cow_fork(
        model, params):
    """A non-page-aligned shared prefix (11 tokens over 4-token pages
    = 2 full blocks + a partial boundary) exercised cold, then warm:
    full-block sharing, the CoW fork of the boundary page, and the
    full-prompt-cached case — every output bitwise equal to B=1."""
    cfg = EngineConfig(max_new_tokens=9, max_prompt_len=MAX_PROMPT,
                       temperature=0.0, num_slots=2, page_size=PAGE,
                       slice_tokens=4, prefix_cache=True)
    engine = DecodeEngine(model, params, cfg, name="px-greedy")
    try:
        prompts = _prefixed_prompts(11, [3, 5, 2, 0], seed=1)
        keys = _keys(4)
        cold = engine.submit(prompts[0], rng=keys[0])
        assert cold.next_event(timeout=120.0) is not None
        streams = [engine.submit(p, rng=k)
                   for p, k in zip(prompts[1:], keys[1:])]
        results = [cold.result(120.0)] + \
            [s.result(120.0) for s in streams]
        for i in range(4):
            np.testing.assert_array_equal(
                results[i],
                _reference(model, params, prompts[i], keys[i], 9),
                err_msg=f"prefix-shared row {i} diverged from B=1")
        st = engine.stats()["prefix_cache"]
        assert st["hits"] == 3 and st["misses"] == 1, st
        assert st["saved_prefill_tokens"] > 0
        _assert_drained(engine)
    finally:
        engine.stop()


def test_prefix_hits_bitwise_equal_sampled_mid_decode_join(
        model, params):
    """Sampled (temperature + top_k + top_p) requests joining a LIVE
    decode adopt shared pages without perturbing any rng stream —
    bitwise, not statistically. The donor is still mid-decode when
    the sharers pin its prompt pages (refcount > 1 while live)."""
    sampling = dict(temperature=0.8, top_k=50, top_p=0.95)
    cfg = EngineConfig(max_new_tokens=13, max_prompt_len=MAX_PROMPT,
                       num_slots=2, page_size=PAGE, slice_tokens=3,
                       prefix_cache=True, **sampling)
    engine = DecodeEngine(model, params, cfg, name="px-sampled")
    try:
        prompts = _prefixed_prompts(9, [4, 6, 2], seed=5)
        keys = _keys(3, base=500)
        donor = engine.submit(prompts[0], rng=keys[0])
        assert donor.next_event(timeout=120.0) is not None
        joiners = [engine.submit(p, rng=k)
                   for p, k in zip(prompts[1:], keys[1:])]
        results = [donor.result(120.0)] + \
            [s.result(120.0) for s in joiners]
        for i in range(3):
            np.testing.assert_array_equal(
                results[i],
                _reference(model, params, prompts[i], keys[i], 13,
                           **sampling),
                err_msg=f"sampled prefix-shared row {i} diverged")
        assert engine.stats()["prefix_cache"]["hits"] >= 1
        _assert_drained(engine)
    finally:
        engine.stop()


def test_eviction_under_page_pressure_stays_correct(model, params):
    """A pool too small to retain every prompt evicts LRU zero-ref
    cached pages to admit new work: admissions never deadlock, later
    DISTINCT-prefix requests still come out bitwise equal, and a
    re-run of an evicted prefix re-registers it."""
    # 9 usable pages; each request needs ceil((L+7)/4) pages — two
    # distinct 10+2-token prompts (5 pages each) cannot both stay
    # cached alongside a third's working set.
    cfg = EngineConfig(max_new_tokens=7, max_prompt_len=MAX_PROMPT,
                       temperature=0.0, num_slots=1, page_size=PAGE,
                       slice_tokens=3, num_pages=10, prefix_cache=True)
    engine = DecodeEngine(model, params, cfg, name="px-evict")
    try:
        groups = [_prefixed_prompts(10, [2, 1], seed=s)
                  for s in (11, 12, 13)]
        keys = _keys(6, base=900)
        k = 0
        for group in groups:
            for prompt in group:
                key = keys[k]
                got = engine.submit(prompt, rng=key).result(180.0)
                np.testing.assert_array_equal(
                    got, _reference(model, params, prompt, key, 7),
                    err_msg=f"request {k} diverged under eviction "
                            f"pressure")
                engine.kv.allocator.check_invariants()
                engine.prefix.check_invariants()
                k += 1
        st = engine.stats()["prefix_cache"]
        assert st["evicted_pages"] > 0, \
            f"pool was sized to force evictions: {st}"
        assert st["hits"] >= 1, st
        _assert_drained(engine)
    finally:
        engine.stop()


def test_cancel_storm_releases_pages_exactly_once(model, params):
    """Stream-cancel satellite: consumers that disconnect while
    QUEUED or MID-DECODE release reservations and ref-counted shared
    pages exactly once — allocator accounting is clean after a storm
    of interleaved submits/cancels, and every stream sees exactly one
    terminal event."""
    cfg = EngineConfig(max_new_tokens=9, max_prompt_len=MAX_PROMPT,
                       temperature=0.0, num_slots=2, page_size=PAGE,
                       slice_tokens=3, num_pages=12, prefix_cache=True)
    engine = DecodeEngine(model, params, cfg, name="px-cancel")
    try:
        rng = np.random.RandomState(17)
        prompts = _prefixed_prompts(9, [2, 3, 1, 4, 2, 3, 1, 2],
                                    seed=23)
        keys = _keys(len(prompts), base=1700)
        for round_i in range(3):
            streams = []
            for i, (p, k) in enumerate(zip(prompts, keys)):
                streams.append(engine.submit(p, rng=k))
                roll = rng.rand()
                if roll < 0.35:
                    streams[-1].cancel()  # often still queued
                elif roll < 0.55:
                    streams[-1].next_event(timeout=120.0)
                    streams[-1].cancel()  # mid-decode
            for s in streams:
                terminal = 0
                try:
                    s.result(timeout=180.0)
                    terminal += 1
                except Exception:  # noqa: BLE001 — cancelled is fine
                    terminal += 1
                assert terminal == 1
                assert s.done
            # Quiesce: the engine retires cancelled slots at slice
            # boundaries — wait for the pool to settle.
            deadline = time.monotonic() + 30.0
            while (engine.scheduler.occupancy()
                   or engine.scheduler.queue_depth()) \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            engine.kv.allocator.check_invariants()
            engine.prefix.check_invariants()
            assert engine.kv.allocator.reserved_pages == 0
        _assert_drained(engine)
    finally:
        engine.stop()


def test_warm_transfer_roundtrip_registers_and_stays_bitwise(
        model, params):
    """Fleet-wide warm transfer: engine A prefills once, the wire
    blob carries layout + prompt tokens, engine B adopts AND indexes
    the pages — B's next same-prefix request is a local hit. Outputs
    bitwise equal to B=1 on both hops; layout-mismatched blobs are
    rejected (mixed-rollout contract)."""
    from kubeflow_tpu.serving.wire import (
        decode_kv_handoff,
        encode_kv_handoff,
    )

    cfg = EngineConfig(max_new_tokens=9, max_prompt_len=MAX_PROMPT,
                       temperature=0.0, num_slots=2, page_size=PAGE,
                       slice_tokens=4, prefix_cache=True)
    a = DecodeEngine(model, params, cfg, name="px-warm-a")
    b = DecodeEngine(model, params, cfg, name="px-warm-b")
    try:
        prompts = _prefixed_prompts(10, [3, 2], seed=31)
        keys = _keys(2, base=2500)
        handoff = a.run_prefill(prompts[0], rng=keys[0])
        assert handoff.layout == "right"
        assert handoff.prompt_tokens is not None
        blob = encode_kv_handoff("m", 1, handoff)
        carried = decode_kv_handoff(blob, model="m", version=1)
        assert carried.layout == "right"
        np.testing.assert_array_equal(carried.prompt_tokens,
                                      prompts[0])
        got = b.submit(handoff=carried).result(120.0)
        np.testing.assert_array_equal(
            got, _reference(model, params, prompts[0], keys[0], 9),
            err_msg="adopted decode diverged from B=1")
        # The transfer WARMED b: a same-prefix local request hits.
        before = b.stats()["prefix_cache"]["hits"]
        got2 = b.submit(prompts[1], rng=keys[1]).result(120.0)
        np.testing.assert_array_equal(
            got2, _reference(model, params, prompts[1], keys[1], 9))
        assert b.stats()["prefix_cache"]["hits"] == before + 1, \
            "warm transfer did not register the carried prefix"
        # Layout guard: a left-layout blob must not adopt here.
        left = dict(vars(carried))
        left["layout"] = "left"
        left_handoff = type(carried)(**left)
        with pytest.raises(ValueError, match="layout"):
            b.submit(handoff=left_handoff)
        _assert_drained(a)
        _assert_drained(b)
    finally:
        a.stop()
        b.stop()


def test_prefix_metrics_exposed_and_strictly_parseable(model, params):
    """The hit/miss/evict counters and saved-tokens histogram render
    on the shared registry in strict OpenMetrics-compatible form —
    the r13 collector ingests whatever parse_exposition accepts."""
    from kubeflow_tpu.obs import metrics as obs_metrics

    cfg = EngineConfig(max_new_tokens=5, max_prompt_len=MAX_PROMPT,
                       temperature=0.0, num_slots=1, page_size=PAGE,
                       slice_tokens=4, prefix_cache=True)
    engine = DecodeEngine(model, params, cfg, name="px-metrics")
    try:
        prompts = _prefixed_prompts(9, [1, 2], seed=41)
        keys = _keys(2, base=3100)
        for p, k in zip(prompts, keys):
            engine.submit(p, rng=k).result(120.0)
        text = obs_metrics.render()
        parsed = obs_metrics.parse_exposition(text)  # strict: raises
        for family in ("kft_engine_prefix_hits_total",
                       "kft_engine_prefix_misses_total",
                       "kft_engine_prefix_evicted_pages_total",
                       "kft_engine_prefix_saved_tokens",
                       "kft_engine_prefix_cached_pages",
                       "kft_engine_page_occupancy"):
            assert any(family in name for name in parsed), \
                f"{family} missing from /metrics"
        stats = engine.stats()
        assert 0.0 <= stats["page_occupancy"] <= 1.0
        assert stats["prefix_cache"]["hits"] == 1
        _assert_drained(engine)
    finally:
        engine.stop()


# -- host-side machinery (no model, no jax dispatch) -----------------------


def test_allocator_ref_retain_reclaim_cycle():
    class _StubCache:
        def __init__(self):
            self.idle = []

        def holds(self, page):
            return True

        def on_idle(self, page):
            self.idle.append(page)

        def on_pinned(self, page):
            self.idle.remove(page)

        def idle_pages(self):
            return list(self.idle)

        def reclaim(self, n):
            out, self.idle = self.idle[:n], self.idle[n:]
            return out

        def reclaimable(self):
            return len(self.idle)

    alloc = PageAllocator(6)  # null + 5 usable
    cache = _StubCache()
    alloc.set_cache(cache)
    assert alloc.reserve(3)
    pages = alloc.alloc(3)
    assert all(alloc.refcount(p) == 1 for p in pages)
    alloc.ref(pages[0])
    assert alloc.refcount(pages[0]) == 2
    alloc.unref(pages[0])
    alloc.unref(pages[0])  # 0 → retained (cache holds it)
    assert alloc.refcount(pages[0]) == 0
    assert alloc.retained_pages == 1 and cache.idle == [pages[0]]
    assert alloc.available() == 3  # 2 free + 1 retained
    alloc.check_invariants()
    # Re-pin from retained custody.
    assert alloc.ref(pages[0])
    assert alloc.refcount(pages[0]) == 1 and alloc.retained_pages == 0
    alloc.unref(pages[0])
    # Reclaim feeds alloc when the free list runs dry.
    assert alloc.reserve(3)
    got = alloc.alloc(3)  # 2 free + 1 reclaimed
    assert pages[0] in got
    alloc.check_invariants()
    for p in got + pages[1:]:
        alloc.unref(p)
    alloc.check_invariants()


def test_allocator_pin_refuses_to_starve_reservations():
    """The FIFO no-deadlock guard: pinning a retained page must fail
    when outstanding reservations have spoken for every reclaimable
    page — instead of silently invalidating a promised alloc."""
    class _StubCache:
        def __init__(self):
            self.idle = []

        def holds(self, page):
            return True

        def on_idle(self, page):
            self.idle.append(page)

        def on_pinned(self, page):
            self.idle.remove(page)

        def idle_pages(self):
            return list(self.idle)

        def reclaim(self, n):
            out, self.idle = self.idle[:n], self.idle[n:]
            return out

        def reclaimable(self):
            return len(self.idle)

    alloc = PageAllocator(4)  # 3 usable
    alloc.set_cache(_StubCache())
    assert alloc.reserve(1)
    pages = alloc.alloc(1)
    alloc.unref(pages[0])  # retained
    assert alloc.reserve(3)  # 2 free + 1 retained, all promised
    assert alloc.available() == 0
    assert alloc.ref(pages[0]) is False, \
        "pin must fail rather than starve a reservation"
    alloc.check_invariants()
    got = alloc.alloc(3)
    assert set(got) >= {pages[0]}
    for p in got:
        alloc.unref(p)
    alloc.check_invariants()


def test_radix_match_register_partial_and_collision_guard():
    alloc = PageAllocator(12)
    cache = PrefixCache(4, alloc)
    prompt = list(range(1, 12))  # 11 tokens: 2 full blocks + 3 rest
    assert alloc.reserve(3)
    pages = alloc.alloc(3)
    assert cache.register(prompt, pages) == 3
    # Full match walks the chain; cap at len-1 keeps one token to
    # prefill: matching the SAME 11 tokens covers 8 + 2 (not 3).
    m = cache.match(prompt)
    assert [e.page for e in m.entries] == pages[:2]
    assert m.fork is not None and m.fork_len == 2 and m.matched == 10
    # A diverging second block stops the walk at block 1.
    other = prompt[:4] + [99, 98, 97, 96, 95]
    m2 = cache.match(other)
    assert [e.page for e in m2.entries] == pages[:1]
    assert m2.fork is None and m2.matched == 4
    # Longest-partial-wins: a shorter partial does not replace.
    assert alloc.reserve(3)
    pages2 = alloc.alloc(3)
    short = prompt[:10]  # same 2 blocks + 2-token partial
    added = cache.register(short, pages2)
    assert added == 0, "shorter partial must not displace the longer"
    cache.check_invariants()
    alloc.check_invariants()
    # Release: all pages retained (indexed), pool still accounts.
    for p in reversed(pages):
        alloc.unref(p)
    for p in reversed(pages2):
        alloc.unref(p)
    alloc.check_invariants()
    assert alloc.retained_pages == 3  # pages2's 3 went straight free
    assert cache.clear() == 3
    assert alloc.free_pages == 11
    alloc.check_invariants()


def test_eviction_fuzz_no_deadlock_no_leak():
    """Random admit/retire/cancel interleavings × prefix overlap over
    a deliberately tiny pool, allocator + index invariants checked
    after EVERY step. 'Admit' mirrors the engine's sequence (match →
    pin → reserve private remainder → alloc prompt pages → register);
    a blocked admission must always unblock once actives retire (the
    FIFO no-deadlock acceptance), and after quiesce + clear the pool
    drains to zero resident pages."""
    rng = np.random.RandomState(7)
    P = 4
    alloc = PageAllocator(14)  # 13 usable
    cache = PrefixCache(P, alloc)
    # A small universe of prompts with heavy prefix overlap.
    bases = [list(rng.randint(0, 50, (10,))) for _ in range(3)]
    prompts = []
    for b in bases:
        for s in range(4):
            suffix = list(rng.randint(0, 50, (rng.randint(0, 5),)))
            prompts.append(b + suffix)
    live = []  # (pages, budget_pages, shared_count)
    pending = []

    def pages_for(n):
        return -(-n // P)

    def try_admit(prompt):
        budget = pages_for(len(prompt) + 6)
        match = cache.pin(cache.match(prompt))
        if not alloc.reserve(budget - len(match.entries)):
            cache.unpin(match)
            return False
        if match.fork is not None:
            cache.unpin_fork(match)
        n_prompt = pages_for(len(prompt))
        priv = alloc.alloc(n_prompt - len(match.entries))
        rows = match.shared_pages + priv
        cache.register(prompt, rows)
        live.append((rows, budget, len(match.entries)))
        return True

    def retire(i):
        rows, budget, _shared = live.pop(i)
        for p in reversed(rows):
            alloc.unref(p)
        alloc.unreserve(budget - len(rows))

    steps = 0
    for _ in range(600):
        op = rng.rand()
        if op < 0.5 and len(live) < 3:
            prompt = prompts[rng.randint(len(prompts))]
            if not try_admit(prompt):
                pending.append(prompt)
        elif op < 0.8 and live:
            retire(rng.randint(len(live)))
        elif pending:
            # Drain the blocked queue FIFO: head first, stop at the
            # first that still doesn't fit (strict FIFO).
            while pending and try_admit(pending[0]):
                pending.pop(0)
        alloc.check_invariants()
        cache.check_invariants()
        steps += 1
    # No deadlock: retire everything, then every blocked admission
    # must admit (possibly evicting cached pages).
    while live:
        retire(0)
        alloc.check_invariants()
    attempts = 0
    while pending:
        assert try_admit(pending[0]), \
            "FIFO head blocked with an empty engine — deadlock"
        pending.pop(0)
        while live:
            retire(0)
        attempts += 1
        alloc.check_invariants()
        cache.check_invariants()
    # Quiesce: only cached pages remain, and clear() frees them all.
    assert alloc.reserved_pages == 0
    assert alloc.inuse_pages == 0
    cache.clear()
    assert alloc.free_pages == 13, \
        f"pages leaked after drain: free={alloc.free_pages}"
    alloc.check_invariants()


# -- tiered KV memory: host-RAM spill + re-adopt (ISSUE 20) ----------------


def test_tiered_eviction_fuzz_no_leak_across_tiers():
    """The r15 fuzz extended ACROSS TIERS: the same random
    admit/retire interleavings over a tiny pool, with a host tier
    attached — reclaim spills full entries host-ward, matches walk
    into the host tier and re-adopt (host blocks come back as private
    pages and re-register), and a random fleet-'fetch' op imports
    chain blocks as a peer would. Allocator + index invariants AND
    the host pool's byte ledger checked after every step; a tiny host
    budget forces host-side LRU evictions too; at the end BOTH tiers
    drain to zero."""
    from kubeflow_tpu.inference.engine.kv_tier import HostKVTier
    from kubeflow_tpu.inference.engine.prefix_cache import (
        _ROOT,
        _block_key,
    )

    rng = np.random.RandomState(23)
    P = 4
    alloc = PageAllocator(14)  # 13 usable
    cache = PrefixCache(P, alloc)
    # ~32 bytes per fuzz block; a 12-block budget forces host-side
    # evictions under the ~24-block universe below.
    host = HostKVTier(12 * 32)
    cache.set_host_tier(host)

    def fake_layers(block):
        # Model-free stand-in for the KV rows: content keyed by the
        # block tokens so a wrong-block splice would be detectable.
        return [np.full((P, 2), block[0], np.float32)]

    cache.set_spill(
        lambda e: host.put(e.key, e.tokens, fake_layers(e.tokens)))

    bases = [list(rng.randint(0, 50, (10,))) for _ in range(3)]
    prompts = []
    for b in bases:
        for _s in range(4):
            suffix = list(rng.randint(0, 50, (rng.randint(0, 5),)))
            prompts.append(b + suffix)
    live = []
    pending = []

    def pages_for(n):
        return -(-n // P)

    def try_admit(prompt):
        budget = pages_for(len(prompt) + 6)
        match = cache.pin(cache.match(prompt))
        if not alloc.reserve(budget - len(match.entries)):
            cache.unpin(match)
            return False
        if match.fork is not None:
            cache.unpin_fork(match)
        n_prompt = pages_for(len(prompt))
        priv = alloc.alloc(n_prompt - len(match.entries))
        rows = match.shared_pages + priv
        cache.register(prompt, rows)
        # The re-adopt half: host-matched blocks came back as private
        # pages and re-registered HBM-ward (the engine's splice path,
        # minus the model).
        host.note_readopted(len(match.host_entries))
        live.append((rows, budget, len(match.entries)))
        return True

    def retire(i):
        rows, budget, _shared = live.pop(i)
        for p in reversed(rows):
            alloc.unref(p)
        alloc.unreserve(budget - len(rows))

    def fleet_import(prompt):
        # What a peer's export→import lands: the chain keys re-derived
        # from token content, full blocks only.
        parent = _ROOT
        for j in range(len(prompt) // P):
            block = tuple(prompt[j * P:(j + 1) * P])
            key = _block_key(parent, block)
            host.put(key, block, fake_layers(block), imported=True)
            parent = key

    for _ in range(600):
        op = rng.rand()
        if op < 0.45 and len(live) < 3:
            prompt = prompts[rng.randint(len(prompts))]
            if not try_admit(prompt):
                pending.append(prompt)
        elif op < 0.75 and live:
            retire(rng.randint(len(live)))
        elif op < 0.85:
            fleet_import(prompts[rng.randint(len(prompts))])
        elif pending:
            while pending and try_admit(pending[0]):
                pending.pop(0)
        alloc.check_invariants()
        cache.check_invariants()
        host.check_accounting()
    # No deadlock: retire everything, then every blocked admission
    # must admit (evicting across BOTH tiers as needed).
    while live:
        retire(0)
        alloc.check_invariants()
    while pending:
        assert try_admit(pending[0]), \
            "FIFO head blocked with an empty engine — deadlock"
        pending.pop(0)
        while live:
            retire(0)
        alloc.check_invariants()
        cache.check_invariants()
        host.check_accounting()
    assert host.spilled_blocks > 0, "pool was sized to force spills"
    assert host.readopted_blocks > 0, \
        "overlapping prompts must have re-adopted host blocks"
    # Drain to zero: the HBM index clears its pages, the host pool
    # clears its bytes, and both ledgers agree on empty.
    assert alloc.reserved_pages == 0
    assert alloc.inuse_pages == 0
    cache.clear()
    assert alloc.free_pages == 13, \
        f"pages leaked after drain: free={alloc.free_pages}"
    alloc.check_invariants()
    host.check_accounting()
    host.clear()
    assert host.resident_blocks() == 0 and host.resident_bytes() == 0
    host.check_accounting()


def test_host_tier_spill_readopt_bitwise_greedy(model, params):
    """Evict-to-host instead of drop: a pool too small to retain
    every conversation spills full prefix blocks to host RAM; a
    revisit walks the index INTO the host tier, splices the blocks
    back HBM-ward, and still comes out bitwise equal to B=1 —
    including the non-aligned-prefix (CoW fork) shape. The kv_tier
    stats block rides engine.stats() for healthz/dashboard."""
    cfg = EngineConfig(max_new_tokens=7, max_prompt_len=MAX_PROMPT,
                       temperature=0.0, num_slots=1, page_size=PAGE,
                       slice_tokens=3, num_pages=10, prefix_cache=True,
                       host_cache_bytes=64 * 1024 * 1024)
    engine = DecodeEngine(model, params, cfg, name="px-tier-greedy")
    try:
        assert engine.host_tier is not None
        # Three conversations with non-aligned 10-token prefixes
        # (2 full blocks + a 2-token boundary): cycling them through
        # a 9-usable-page pool forces evict-to-host.
        groups = [_prefixed_prompts(10, [2, 1], seed=s)
                  for s in (31, 32, 33)]
        keys = _keys(6, base=3100)
        k = 0
        for group in groups:
            for prompt in group:
                got = engine.submit(prompt, rng=keys[k]).result(180.0)
                np.testing.assert_array_equal(
                    got, _reference(model, params, prompt, keys[k], 7),
                    err_msg=f"request {k} diverged with host tier on")
                engine.kv.allocator.check_invariants()
                engine.prefix.check_invariants()
                engine.host_tier.check_accounting()
                k += 1
        tier = engine.stats()["kv_tier"]
        assert tier["host"]["spilled_blocks"] > 0, tier
        # Revisit the FIRST conversation: its blocks are host-resident
        # now; the revisit must re-adopt (not re-prefill) and stay
        # bitwise.
        readopts_before = tier["host"]["readopted_blocks"]
        hits_before = engine.stats()["prefix_cache"]["hits"]
        revisit = _prefixed_prompts(10, [3], seed=31)[0]
        key = _keys(1, base=3200)[0]
        got = engine.submit(revisit, rng=key).result(180.0)
        np.testing.assert_array_equal(
            got, _reference(model, params, revisit, key, 7),
            err_msg="host re-adopt diverged from B=1")
        tier = engine.stats()["kv_tier"]
        assert tier["host"]["readopted_blocks"] > readopts_before, tier
        assert engine.stats()["prefix_cache"]["hits"] > hits_before
        # The saturation surface carries the whole tier block.
        for key_name in ("budget_bytes", "resident_bytes",
                         "resident_blocks", "spilled_blocks",
                         "evicted_blocks", "readopted_blocks",
                         "imported_blocks"):
            assert key_name in tier["host"], tier
        _assert_drained(engine)
        engine.host_tier.check_accounting()
    finally:
        engine.stop()


def test_host_tier_sampled_mid_decode_join_bitwise(model, params):
    """Sampled decode over re-adopted host blocks, with a LIVE
    mid-decode join: the donor re-adopts a spilled conversation and
    is still decoding when a sharer pins its freshly re-registered
    pages. Both outputs bitwise equal to B=1 — re-adoption must not
    perturb any rng stream."""
    sampling = dict(temperature=0.8, top_k=50, top_p=0.95)
    cfg = EngineConfig(max_new_tokens=7, max_prompt_len=MAX_PROMPT,
                       num_slots=2, page_size=PAGE, slice_tokens=3,
                       num_pages=13, prefix_cache=True,
                       host_cache_bytes=64 * 1024 * 1024, **sampling)
    engine = DecodeEngine(model, params, cfg, name="px-tier-sampled")
    try:
        conv = _prefixed_prompts(12, [2, 3, 2], seed=41)
        fills = [_prefixed_prompts(12, [2], seed=s)[0]
                 for s in (42, 43, 44)]
        keys = _keys(6, base=4100)
        # Warm conversation A, then churn B/C/D through the pool to
        # evict A's prefix host-ward.
        engine.submit(conv[0], rng=keys[0]).result(180.0)
        for i, fill in enumerate(fills):
            engine.submit(fill, rng=keys[1 + i]).result(180.0)
        host = engine.stats()["kv_tier"]["host"]
        assert host["spilled_blocks"] > 0, host
        readopts_before = host["readopted_blocks"]
        # Donor re-adopts; joiner lands while the donor is mid-decode.
        donor = engine.submit(conv[1], rng=keys[4])
        assert donor.next_event(timeout=120.0) is not None
        joiner = engine.submit(conv[2], rng=keys[5])
        results = [donor.result(120.0), joiner.result(120.0)]
        for got, prompt, key in zip(results, conv[1:], keys[4:]):
            np.testing.assert_array_equal(
                got, _reference(model, params, prompt, key, 7,
                                **sampling),
                err_msg="sampled tier re-adopt/join diverged")
        host = engine.stats()["kv_tier"]["host"]
        assert host["readopted_blocks"] > readopts_before, host
        _assert_drained(engine)
        engine.host_tier.check_accounting()
    finally:
        engine.stop()


def test_fleet_export_import_roundtrip_bitwise(model, params):
    """Tier 2's engine half: replica A exports a warmed prompt's full
    blocks (`export_prefix_blocks`), replica B imports them into its
    host tier (`import_prefix_blocks`, chain keys re-derived from
    token content — peer hashes never trusted), and B's first-ever
    request on that conversation HITS and stays bitwise equal to B=1
    cold prefill. Malformed payloads import zero blocks and raise
    nothing."""
    cfg = EngineConfig(max_new_tokens=7, max_prompt_len=MAX_PROMPT,
                       temperature=0.0, num_slots=1, page_size=PAGE,
                       slice_tokens=3, num_pages=10, prefix_cache=True,
                       host_cache_bytes=64 * 1024 * 1024)
    owner = DecodeEngine(model, params, cfg, name="px-kv-owner")
    asker = DecodeEngine(model, params, cfg, name="px-kv-asker")
    try:
        prompts = _prefixed_prompts(12, [2, 3], seed=51)
        keys = _keys(2, base=5100)
        owner.submit(prompts[0], rng=keys[0]).result(180.0)
        blocks = owner.export_prefix_blocks(
            np.asarray(prompts[0], np.int32))
        assert len(blocks) == 3, \
            f"12-token prefix should export 3 full blocks: " \
            f"{len(blocks)}"
        imported = asker.import_prefix_blocks(blocks)
        assert imported == 3
        asker.note_kv_fetch("hit", blocks=imported)
        hits_before = asker.stats()["prefix_cache"]["hits"]
        got = asker.submit(prompts[1], rng=keys[1]).result(180.0)
        np.testing.assert_array_equal(
            got, _reference(model, params, prompts[1], keys[1], 7),
            err_msg="fleet-fetched blocks diverged from cold prefill")
        st = asker.stats()
        assert st["prefix_cache"]["hits"] > hits_before
        assert st["kv_tier"]["fetch_hits"] == 1
        assert st["kv_tier"]["fetched_blocks"] == 3
        assert st["kv_tier"]["host"]["imported_blocks"] == 3
        # Malformed import attempts: wrong block length, wrong layer
        # count — all land zero blocks, raise nothing.
        assert asker.import_prefix_blocks([]) == 0
        bad_len = [(tuple(range(PAGE + 1)), blocks[0][1])]
        assert asker.import_prefix_blocks(bad_len) == 0
        bad_layers = [(blocks[0][0], blocks[0][1][:1])]
        assert asker.import_prefix_blocks(bad_layers) == 0
        _assert_drained(asker)
        _assert_drained(owner)
    finally:
        owner.stop()
        asker.stop()


# -- autoscaler + healthz: page pressure visibility ------------------------


def test_replica_sample_reports_page_pressure_and_hit_rate():
    """The decode-pool scaling path and the fleet dashboard see PAGE
    pressure and the prefix hit rate, not just slot occupancy — and
    malformed values degrade, never raise."""
    from kubeflow_tpu.scaling.autoscaler import (
        Autoscaler,
        AutoscalerConfig,
        AutoscalerLoop,
    )

    class _FakeScaler:
        def get_replicas(self):
            return 1

        def set_replicas(self, n):
            pass

    loop = AutoscalerLoop(
        Autoscaler(AutoscalerConfig(), _FakeScaler()),
        discover=lambda: [])
    row = loop._replica_sample("a:1", {
        "status": "ok", "role": "decode",
        "saturation": {"m": {
            "queue_depth": 0, "est_batch_latency_ms": 5.0,
            "shed": 0, "expired": 0,
            "engine": {"slots": 4, "active_slots": 1,
                       "queue_depth": 0, "est_ttft_ms": 1.0,
                       "page_occupancy": 0.625,
                       "prefix_cache": {"hits": 30, "misses": 10},
                       "kv_tier": {
                           "fetch_hits": 4,
                           "host": {"budget_bytes": 1000,
                                    "resident_bytes": 250}}},
        }}}, now=1.0)
    assert row["page_occupancy"] == 0.625
    assert row["prefix_hit_rate"] == 0.75
    # Host-tier headroom + fleet-fetch activity (ISSUE 20) ride the
    # same scrape for the scaler and the dashboard fleet table.
    assert row["host_kv_occupancy"] == 0.25
    assert row["kv_fetch_hits"] == 4
    # No engine / no prefix cache / no host tier → fields absent,
    # row intact.
    row2 = loop._replica_sample("b:1", {
        "status": "ok", "saturation": {"m": {"queue_depth": 0}}},
        now=2.0)
    assert "page_occupancy" not in row2
    assert "prefix_hit_rate" not in row2
    assert "host_kv_occupancy" not in row2
    assert "kv_fetch_hits" not in row2
    # A tier with budget 0 (off) must not report occupancy.
    row2b = loop._replica_sample("b:2", {
        "status": "ok", "saturation": {"m": {"engine": {
            "kv_tier": {"fetch_hits": 0,
                        "host": {"budget_bytes": 0,
                                 "resident_bytes": 0}}}}}}, now=2.5)
    assert "host_kv_occupancy" not in row2b
    assert "kv_fetch_hits" not in row2b
    # Malformed values degrade, never raise.
    row3 = loop._replica_sample("c:1", {
        "status": "ok",
        "saturation": {"m": {"engine": {
            "page_occupancy": "hot",
            "prefix_cache": {"hits": "many"},
            "kv_tier": {"fetch_hits": "lots",
                        "host": {"budget_bytes": "big"}}}}}},
        now=3.0)
    assert row3["reachable"] and "page_occupancy" not in row3
    assert "host_kv_occupancy" not in row3


# -- balancer: prefix affinity ---------------------------------------------


def test_normalize_prefix_key_stability_and_degrade():
    from kubeflow_tpu.scaling.balancer import normalize_prefix_key

    a = normalize_prefix_key([[1, 2, 3, 4] + [0] * 100])
    b = normalize_prefix_key([[1, 2, 3, 4] + [0] * 100, [9, 9]])
    assert a is not None and a == b  # first row, first 64 tokens
    assert normalize_prefix_key([[1, 2, 3]]) != \
        normalize_prefix_key([[1, 2, 4]])
    assert normalize_prefix_key([]) is None
    assert normalize_prefix_key("garbage") is None
    assert normalize_prefix_key([["x", "y"]]) is None
    assert normalize_prefix_key(None) is None


def test_prefix_affinity_balancer_routes_home_and_falls_back():
    from kubeflow_tpu.scaling.balancer import PrefixAffinityBalancer
    from kubeflow_tpu.scaling.endpoints import Endpoint

    eps = [Endpoint(f"replica-{i}:900{i}", register_metrics=False)
           for i in range(3)]
    bal = PrefixAffinityBalancer(overload_ms=100.0)
    # Same key → same replica, every time.
    picks = {bal.pick(eps, prefix_key="k1").address for _ in range(8)}
    assert len(picks) == 1
    # Distinct keys spread across the pool (rendezvous uniformity —
    # with 40 keys over 3 replicas, all 3 should own some).
    owners = {bal.pick(eps, prefix_key=f"key-{i}").address
              for i in range(40)}
    assert owners == {ep.address for ep in eps}
    # Membership churn moves only the departed replica's keys.
    home = bal.pick(eps, prefix_key="sticky").address
    survivors = [ep for ep in eps if ep.address != home]
    moved = bal.pick(survivors, prefix_key="sticky").address
    assert moved != home
    keep = [k for k in (f"key-{i}" for i in range(40))
            if bal.pick(eps, prefix_key=k).address != home]
    for k in keep:
        assert bal.pick(survivors, prefix_key=k).address == \
            bal.pick(eps, prefix_key=k).address, \
            "HRW moved a key its replica still owns"
    # Overloaded home falls back to least-saturation (never a
    # hotspot), and a keyless pick degrades the same way.
    target = bal.pick(eps, prefix_key="k1")
    target.saturation = {"m": {"queue_depth": 10,
                               "est_batch_latency_ms": 50.0}}
    assert bal.pick(eps, prefix_key="k1").address != target.address
    assert bal.pick(eps, prefix_key=None) is not None


def test_role_balancer_applies_prefix_affinity_inside_the_pool():
    """Role-split decode-hop affinity (ISSUE 11): within the healthy
    phase-matching pool, the SAME prefix key picks the SAME decode
    replica — and never a prefill-role one."""
    from kubeflow_tpu.scaling.balancer import RoleAwareBalancer
    from kubeflow_tpu.scaling.endpoints import Endpoint

    decode = [Endpoint(f"decode-{i}:91{i}", register_metrics=False,
                       role="decode") for i in range(3)]
    prefill = [Endpoint("prefill-0:900", register_metrics=False,
                        role="prefill")]
    bal = RoleAwareBalancer(overload_ms=100.0)
    picks = {bal.pick(decode + prefill, phase="decode",
                      prefix_key="conv-1").address for _ in range(6)}
    assert len(picks) == 1 and picks < {ep.address for ep in decode}
    # Distinct keys spread across the decode pool.
    owners = {bal.pick(decode + prefill, phase="decode",
                       prefix_key=f"c{i}").address for i in range(40)}
    assert owners == {ep.address for ep in decode}
    # Keyless picks still route (least-saturation inside the pool).
    assert bal.pick(decode + prefill, phase="decode") is not None
