# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Execute the checked-in example notebooks (the reference's notebook
walkthrough tier: user_guide.md MNIST-softmax flow, accuracy golden
0.9014 — here rerun hermetically on every CI pass instead of by hand).
"""

import re
from pathlib import Path

import nbformat
import pytest

NOTEBOOKS = sorted(
    (Path(__file__).resolve().parent.parent / "examples" / "notebooks")
    .glob("*.ipynb"))


def test_notebooks_are_present():
    # An empty glob must fail loudly — a silently-skipped tier would
    # let BASELINE.md's "executed in CI" claim rot (e.g. an image
    # that forgets to COPY examples/).
    assert NOTEBOOKS, "examples/notebooks/*.ipynb missing"


@pytest.mark.parametrize("path", NOTEBOOKS, ids=lambda p: p.name)
def test_notebook_executes_and_hits_accuracy(path):
    from nbclient import NotebookClient

    nb = nbformat.read(path, as_version=4)
    client = NotebookClient(nb, timeout=300, kernel_name="python3")
    client.execute()  # raises CellExecutionError on any failing cell

    text = "\n".join(
        out.get("text", "")
        for cell in nb.cells if cell.cell_type == "code"
        for out in cell.get("outputs", []))
    match = re.search(r"test accuracy: ([0-9.]+)", text)
    assert match, f"no accuracy line in outputs of {path.name}:\n{text}"
    # Reference golden: 0.9014 (user_guide.md); hold the same bar.
    assert float(match.group(1)) >= 0.90
