# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""GhostBatchNorm: exact nn.BatchNorm equivalence at stat_rows=0,
correct subset semantics at stat_rows>0, drop-in layout parity."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from flax import linen as nn

from kubeflow_tpu.ops.batch_norm import GhostBatchNorm


def _data(shape=(8, 4, 4, 16), seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randn(*shape), jnp.float32)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_matches_flax_batchnorm_exactly_at_stat_rows_0(dtype):
    """Bitwise parity with nn.BatchNorm in BOTH dtypes — bf16 is the
    production ResNet config, so the swap must be a no-op there."""
    x = _data().astype(dtype)
    ours = GhostBatchNorm(use_running_average=False, dtype=dtype)
    theirs = nn.BatchNorm(use_running_average=False, momentum=0.9,
                          epsilon=1e-5, dtype=dtype)
    v_ours = ours.init(jax.random.PRNGKey(0), x)
    v_theirs = theirs.init(jax.random.PRNGKey(0), x)
    # Identical param/collection layout → interchangeable checkpoints.
    assert jax.tree.structure(v_ours) == jax.tree.structure(v_theirs)

    y_ours, m_ours = ours.apply(v_ours, x, mutable=["batch_stats"])
    y_theirs, m_theirs = theirs.apply(v_theirs, x,
                                      mutable=["batch_stats"])
    assert y_ours.dtype == y_theirs.dtype
    np.testing.assert_array_equal(
        np.asarray(y_ours, np.float32), np.asarray(y_theirs, np.float32))
    for a, b in zip(jax.tree.leaves(m_ours), jax.tree.leaves(m_theirs)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Eval path identical too.
    y_eval_o = GhostBatchNorm(use_running_average=True,
                              dtype=dtype).apply(v_ours, x)
    y_eval_t = nn.BatchNorm(use_running_average=True,
                            dtype=dtype).apply(v_theirs, x)
    np.testing.assert_array_equal(
        np.asarray(y_eval_o, np.float32),
        np.asarray(y_eval_t, np.float32))


def test_stat_rows_uses_leading_subset():
    x = _data((16, 2, 2, 8))
    bn = GhostBatchNorm(use_running_average=False, dtype=jnp.float32,
                        stat_rows=4)
    v = bn.init(jax.random.PRNGKey(0), x)
    y, mutated = bn.apply(v, x, mutable=["batch_stats"])
    # Expected: stats from rows [:4] only, applied to ALL rows.
    xf = np.asarray(x, np.float64)
    mean = xf[:4].mean(axis=(0, 1, 2))
    var = (np.square(xf[:4]).mean(axis=(0, 1, 2)) - np.square(mean))
    want = (xf - mean) / np.sqrt(var + 1e-5)
    np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4,
                               atol=1e-4)
    # Running averages updated from the SUBSET stats.
    got_mean = np.asarray(mutated["batch_stats"]["mean"])
    np.testing.assert_allclose(got_mean, 0.1 * mean, rtol=1e-4,
                               atol=1e-5)


def test_stat_rows_zero_or_oversized_is_full_batch():
    x = _data((4, 2, 2, 8))
    full = GhostBatchNorm(use_running_average=False,
                          dtype=jnp.float32, stat_rows=0)
    over = GhostBatchNorm(use_running_average=False,
                          dtype=jnp.float32, stat_rows=99)
    v = full.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(
        np.asarray(full.apply(v, x, mutable=["batch_stats"])[0]),
        np.asarray(over.apply(v, x, mutable=["batch_stats"])[0]),
        rtol=1e-6)


def test_resnet_bn_stat_rows_trains():
    """The wired-through model trains and its loss decreases with
    subset stats (semantics sanity, not perf)."""
    import optax

    from kubeflow_tpu.models.resnet import resnet18ish
    from kubeflow_tpu.training.train import (
        create_train_state,
        make_train_step,
    )

    model = resnet18ish(num_classes=10, bn_stat_rows=4)
    state = create_train_state(
        model, optax.sgd(0.05, momentum=0.9), jax.random.PRNGKey(0),
        jnp.zeros((1, 32, 32, 3), jnp.bfloat16))
    step = make_train_step(None, donate=False)
    rng = np.random.RandomState(0)
    batch = {"inputs": jnp.asarray(rng.rand(16, 32, 32, 3), jnp.bfloat16),
             "labels": jnp.asarray(rng.randint(0, 10, 16))}
    _, first = step(state, batch)
    for _ in range(8):
        state, metrics = step(state, batch)
    assert float(metrics["loss"]) < float(first["loss"])
    # batch_stats moved off init zeros.
    assert any(np.abs(np.asarray(leaf)).sum() > 0
               for leaf in jax.tree.leaves(state.batch_stats))


def test_ghost_stats_converge_like_exact_stats():
    """The statistics trade must not change training behavior when
    the stat sample count per channel is adequate: ghost at HALF the
    batch tracks exact BN on the same stream; a 4-row subset (only 4
    samples/channel at this net's 1x1 deep stages) measurably does
    NOT — which is the boundary the module docstring warns about.
    (resnet50 at stat_rows=32 has 32x7x7=1568 samples/channel in its
    deepest stage, far inside the safe regime.)"""
    import optax

    from kubeflow_tpu.models.resnet import resnet18ish
    from kubeflow_tpu.training.train import (
        create_train_state,
        make_train_step,
    )

    rng = np.random.RandomState(0)
    batches = [
        {"inputs": jnp.asarray(rng.rand(16, 32, 32, 3), jnp.bfloat16),
         "labels": jnp.asarray(rng.randint(0, 10, 16))}
        for _ in range(6)
    ]

    def train(stat_rows):
        model = resnet18ish(num_classes=10, bn_stat_rows=stat_rows)
        state = create_train_state(
            model, optax.sgd(0.05, momentum=0.9), jax.random.PRNGKey(0),
            jnp.zeros((1, 32, 32, 3), jnp.bfloat16))
        step = make_train_step(None, donate=False)
        first = None
        for _ in range(3):  # 3 epochs over the 6 batches
            for batch in batches:
                state, metrics = step(state, batch)
                if first is None:
                    first = float(metrics["loss"])
        return first, float(metrics["loss"])

    _, exact = train(0)
    first8, ghost8 = train(8)
    assert np.isfinite(ghost8)
    # This toy memorizes random labels, so run-to-run losses are
    # seed-fragile (measured exact 1.4-1.6; ghost-8 1.85-2.56) — the
    # gate is a DIVERGENCE gate, not a tight band: ghost-4's
    # too-few-samples failure mode measured 4.9+, >3x exact, and must
    # stay caught; 2.5x leaves headroom over the measured ghost-8
    # spread without letting the ghost-4 mode through.
    assert ghost8 < 2.5 * exact, (exact, ghost8)
    assert ghost8 < first8 + 0.5  # no blow-up over 18 steps


def test_ghost_bn_grads_flow_through_stat_rows():
    x = _data((8, 2, 2, 4))
    bn = GhostBatchNorm(use_running_average=False, dtype=jnp.float32,
                        stat_rows=2)
    v = bn.init(jax.random.PRNGKey(0), x)

    def loss(xin):
        y, _ = bn.apply(v, xin, mutable=["batch_stats"])
        return jnp.sum(y ** 2)

    g = jax.grad(loss)(x)
    assert np.isfinite(np.asarray(g)).all()
    # Rows outside the stat subset still receive gradients (they are
    # normalized, just don't contribute to the stats).
    assert np.abs(np.asarray(g[4:])).sum() > 0


def test_inception_ghost_bn_layout_and_exactness():
    """Inception's ConvBN carries the same ghost-BN lever as resnet:
    identical param/collection tree to the exact-BN module, and
    stat_rows ≥ batch degenerates to exact BN (train and eval).
    (On the chip the lever measured SLOWER for inception — PERF.md —
    so the default stays exact; this test pins the wiring.)"""
    from kubeflow_tpu.models.inception import inception_v3

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 75, 75, 3))
    m0 = inception_v3(num_classes=10, dtype=jnp.float32)
    m32 = inception_v3(num_classes=10, dtype=jnp.float32,
                       bn_stat_rows=32)
    v0 = m0.init(jax.random.PRNGKey(1), x)
    v32 = m32.init(jax.random.PRNGKey(1), x)
    assert jax.tree.structure(v0) == jax.tree.structure(v32)
    o0, _ = m0.apply(v0, x, train=True, mutable=["batch_stats"])
    o32, _ = m32.apply(v32, x, train=True, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(o0), np.asarray(o32),
                               atol=1e-6)
    e0 = m0.apply(v0, x, train=False)
    e32 = m32.apply(v32, x, train=False)
    np.testing.assert_allclose(np.asarray(e0), np.asarray(e32),
                               atol=1e-6)
