# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Gang scheduling deadlines (spec.schedulingDeadlineSeconds): a gang
that can never place must not hold TPU slices forever — on expiry the
job Fails with a DeadlineExceeded condition + Event and its pods are
torn down. Unit tests against the fake, plus the acceptance e2e:
reconciler → WatchController → HttpApiClient → real socket → facade.
"""

import datetime
import threading
import time

from kubeflow_tpu.manifests.tpujob import (
    KIND,
    crd,
    replica_spec,
    termination_policy,
    tpu_job,
)
from kubeflow_tpu.operator import FakeApiServer, Reconciler
from kubeflow_tpu.operator.controller import WatchController
from kubeflow_tpu.operator.http_client import HttpApiClient
from kubeflow_tpu.operator.reconciler import (
    DEADLINE_CONDITION,
    JOB_LABEL,
)
from kubeflow_tpu.operator.workqueue import ExponentialBackoff

from tests._http_apiserver import HttpFakeApiServer
from tests.test_operator import submit


def make_deadline_job(name="dj", workers=2, deadline=30):
    spec = replica_spec(
        "TPU_WORKER", workers, image="img:1",
        tpu_accelerator="tpu-v5-lite-podslice", tpu_topology="2x4")
    job = tpu_job(name, "default", [spec],
                  termination=termination_policy("TPU_WORKER", 0),
                  scheduling_deadline_seconds=deadline)
    job["metadata"]["uid"] = "uid-dl"
    return job


def _age_pending_condition(api, name, seconds):
    """Kubelet-less time travel: move the Pending condition's
    transition time into the past."""
    past = (datetime.datetime.now(datetime.timezone.utc)
            - datetime.timedelta(seconds=seconds)).isoformat()

    def mutate(obj):
        for cond in obj.get("status", {}).get("conditions", []):
            if cond["type"] == "Pending":
                cond["lastTransitionTime"] = past

    with api.as_kubelet():
        api.patch(KIND, "default", name, mutate)


def test_crd_schema_carries_scheduling_deadline():
    schema = (crd()["spec"]["versions"][0]["schema"]
              ["openAPIV3Schema"]["properties"]["spec"]["properties"])
    assert schema["schedulingDeadlineSeconds"] == {
        "type": "integer", "minimum": 1}
    job = make_deadline_job(deadline=120)
    assert job["spec"]["schedulingDeadlineSeconds"] == 120
    # Jobs without a deadline stay schema-identical to pre-r7 CRs.
    plain = tpu_job("p", "default", [replica_spec(
        "TPU_WORKER", 1, image="i", tpu_accelerator="a",
        tpu_topology="1x1")])
    assert "schedulingDeadlineSeconds" not in plain["spec"]


def test_deadline_expiry_fails_job_and_releases_gang():
    api = FakeApiServer()
    job = submit(api, make_deadline_job(workers=3, deadline=5))
    r = Reconciler(api)
    assert r.reconcile(job) == "Pending"
    assert len(api.list("Pod", "default", {JOB_LABEL: "dj"})) == 3

    # Not yet expired: the reconciler asks for a wake-up at expiry.
    job = api.get(KIND, "default", "dj")
    assert r.reconcile(job) == "Pending"
    assert r.requeue_after is not None
    assert 0 < r.requeue_after <= 5.0

    _age_pending_condition(api, "dj", seconds=6)
    job = api.get(KIND, "default", "dj")
    assert r.reconcile(job) == "Failed"
    # TPU slices released: every gang pod deleted.
    assert api.list("Pod", "default", {JOB_LABEL: "dj"}) == []
    job = api.get(KIND, "default", "dj")
    assert "schedulingDeadlineSeconds" in job["status"]["reason"]
    conds = {c["type"]: c for c in job["status"]["conditions"]}
    assert conds["Failed"]["status"] == "True"
    assert conds[DEADLINE_CONDITION]["status"] == "True"
    assert "deadline" in conds[DEADLINE_CONDITION]["reason"]
    # The Event carries reason DeadlineExceeded (kubectl describe).
    events = [e for e in api.list("Event", "default")
              if e["involvedObject"]["name"] == "dj"]
    assert any(e["reason"] == DEADLINE_CONDITION
               and e["type"] == "Warning" for e in events), events
    # Terminal is absorbing: a later pass changes nothing.
    assert r.reconcile(api.get(KIND, "default", "dj")) == "Failed"


def test_deadline_verdict_uses_live_pods_not_stale_phase():
    """Review finding: a deadline timer firing in the same pass that
    first observes the gang Running (per-key dedup coalesces the pod
    event and the timer) must NOT tear down the healthy gang just
    because status.phase still reads Pending."""
    api = FakeApiServer()
    job = submit(api, make_deadline_job(workers=2, deadline=5))
    r = Reconciler(api)
    r.reconcile(job)  # creates the gang; phase Pending
    # Kubelet starts the pods, but no pass has observed it yet —
    # status.phase is still Pending AND the deadline has expired.
    api.set_all_pod_phases("default", "Running", {JOB_LABEL: "dj"})
    _age_pending_condition(api, "dj", seconds=60)
    job = api.get(KIND, "default", "dj")
    assert job["status"]["phase"] == "Pending"  # stale, by design
    assert r.reconcile(job) == "Running"  # NOT Failed
    assert len(api.list("Pod", "default", {JOB_LABEL: "dj"})) == 2
    conds = {c["type"] for c in api.get(KIND, "default", "dj")
             ["status"]["conditions"]}
    assert DEADLINE_CONDITION not in conds


def test_deadline_counts_from_operator_observation_not_creation():
    """Review finding: a job submitted while the operator was down
    must get its full deadline of scheduling time after the operator
    returns — the anchor is the operator's own Pending write, never
    metadata.creationTimestamp."""
    api = FakeApiServer()
    job = make_deadline_job(workers=1, deadline=5)
    # Submitted an hour ago, operator down the whole time.
    job["metadata"]["creationTimestamp"] = "2020-01-01T00:00:00+00:00"
    job = submit(api, job)
    r = Reconciler(api)
    # First pass after the outage: creates the gang, anchors Pending
    # NOW — must not instantly execute the deadline.
    assert r.reconcile(job) == "Pending"
    assert len(api.list("Pod", "default", {JOB_LABEL: "dj"})) == 1
    job = api.get(KIND, "default", "dj")
    assert r.reconcile(job) == "Pending"  # still within the deadline
    assert len(api.list("Pod", "default", {JOB_LABEL: "dj"})) == 1


def test_stalled_condition_cleared_without_process_memory():
    """Review finding: ReconcileStalled=True written by a previous
    operator incarnation is cleared by any successful pass of a NEW
    process (no in-memory _stalled set) — the clear rides the status
    write itself."""
    from kubeflow_tpu.operator.reconciler import STALLED_CONDITION

    api = FakeApiServer()
    job = submit(api, make_deadline_job(workers=1, deadline=600))
    old = Reconciler(api)
    old.reconcile(job)
    old.mark_stalled("default", "dj", failures=7)
    conds = {c["type"]: c["status"]
             for c in api.get(KIND, "default", "dj")
             ["status"]["conditions"]}
    assert conds[STALLED_CONDITION] == "True"

    fresh = Reconciler(api)  # the restarted operator
    fresh.reconcile(api.get(KIND, "default", "dj"))
    conds = {c["type"]: c["status"]
             for c in api.get(KIND, "default", "dj")
             ["status"]["conditions"]}
    assert conds[STALLED_CONDITION] == "False"


def test_deadline_not_enforced_once_running():
    """The deadline is about SCHEDULING: a gang that started must
    never be deadline-killed, however long it runs."""
    api = FakeApiServer()
    job = submit(api, make_deadline_job(workers=1, deadline=5))
    r = Reconciler(api)
    r.reconcile(job)
    api.set_all_pod_phases("default", "Running", {JOB_LABEL: "dj"})
    r.reconcile(api.get(KIND, "default", "dj"))
    _age_pending_condition(api, "dj", seconds=600)  # stale, now False
    job = api.get(KIND, "default", "dj")
    assert r.reconcile(job) == "Running"
    assert r.requeue_after is None
    assert len(api.list("Pod", "default", {JOB_LABEL: "dj"})) == 1


def test_no_deadline_means_wait_forever():
    api = FakeApiServer()
    job = submit(api, tpu_job("nd", "default", [replica_spec(
        "TPU_WORKER", 1, image="i", tpu_accelerator="a",
        tpu_topology="1x1")],
        termination=termination_policy("TPU_WORKER", 0)))
    r = Reconciler(api)
    r.reconcile(job)
    _age_pending_condition(api, "nd", seconds=10_000)
    job = api.get(KIND, "default", "nd")
    assert r.reconcile(job) == "Pending"
    assert r.requeue_after is None
    assert len(api.list("Pod", "default", {JOB_LABEL: "nd"})) == 1


def test_deadline_e2e_over_http_apiserver():
    """Acceptance: an unsatisfiable gang (pods never scheduled — no
    kubelet ever writes a status) fails within
    schedulingDeadlineSeconds ± one resync, its pods are deleted, and
    the job carries the DeadlineExceeded condition + Event — all
    through the production HTTP client over a real socket."""
    with HttpFakeApiServer(token="dl") as srv:
        client = HttpApiClient(srv.url, token="dl")
        ctl = WatchController(
            client, relist_seconds=0.5,
            backoff=ExponentialBackoff(base=0.02, cap=0.5))
        t = threading.Thread(target=ctl.run, daemon=True)
        t.start()
        try:
            deadline_s = 1
            t0 = time.monotonic()
            client.create(make_deadline_job(workers=2,
                                            deadline=deadline_s))
            failed_at = None
            while time.monotonic() - t0 < 10.0:
                job = srv.fake.get(KIND, "default", "dj")
                if job.get("status", {}).get("phase") == "Failed":
                    failed_at = time.monotonic() - t0
                    break
                time.sleep(0.02)
            assert failed_at is not None, "deadline never fired"
            # Within the deadline ± one resync period (+ scheduling
            # slack): the reconciler's requeue_after timer fires at
            # expiry, the relist is only the safety net.
            assert failed_at >= deadline_s * 0.5
            assert failed_at <= deadline_s + 0.5 + 1.0, failed_at
            assert srv.fake.list("Pod", "default",
                                 {JOB_LABEL: "dj"}) == []
            job = srv.fake.get(KIND, "default", "dj")
            conds = {c["type"]: c["status"]
                     for c in job["status"]["conditions"]}
            assert conds[DEADLINE_CONDITION] == "True"
            assert conds["Failed"] == "True"
            # The Event write follows the status write by one HTTP
            # round trip — poll briefly instead of racing it.
            def deadline_event_recorded():
                return any(
                    e["reason"] == DEADLINE_CONDITION
                    for e in srv.fake.list("Event", "default")
                    if e["involvedObject"]["name"] == "dj")

            t1 = time.monotonic()
            while (not deadline_event_recorded()
                   and time.monotonic() - t1 < 3.0):
                time.sleep(0.02)
            assert deadline_event_recorded()
        finally:
            ctl.stop.set()
            t.join(timeout=10)
