# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI, so all sharding tests
run on XLA's host-platform device-count idiom (the hermetic layer the
reference never had — its distributed tests needed a live GKE cluster,
``testing/workflows/components/workflows.libsonnet:51-54``).

Must run before jax initializes a backend, hence env mutation at import.
"""

import os

# Force CPU: the session presets JAX_PLATFORMS=axon (the real TPU
# tunnel), which tests must never grab.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
# Tornado AsyncHTTPTestCase default is 5 s — observed flaking when the
# suite shares the box with a chip benchmark; the tests assert
# behavior, not latency.
os.environ.setdefault("ASYNC_TEST_TIMEOUT", "30")

import jax  # noqa: E402

# The session's sitecustomize imports jax config with JAX_PLATFORMS=axon
# before conftest runs, freezing the env default — override explicitly.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    devices = jax.devices()
    assert len(devices) == 8, f"expected 8 virtual devices, got {len(devices)}"
    return devices
