# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Fleet-wide trace assembly + latency attribution (ISSUE 15).

Units: trace-context parent links and leg tags, the SpanStore's caps
under fuzz (drop-counting, never unbounded), tree assembly and
attribution over synthetic spans, the export queue + SpanShipper push
path, and the collector exposition trace endpoints.

E2E: a REAL proxy + two REAL role-split servers + a span-scraping
collector — unary, SSE, and hedged requests must each assemble into
ONE trace fleet-wide whose queue/prefill/decode/relay/gap buckets
cover >=95% of the client-measured wall; kill+resume (fault-injected,
slow tier) keeps one trace id across the resume leg."""

import json
import random
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubeflow_tpu.obs import tracing
from kubeflow_tpu.obs import trace as obs_trace
from kubeflow_tpu.obs.collector import (
    Collector,
    SpanShipper,
    SpanStore,
    TimeSeriesStore,
)
from kubeflow_tpu.models.llama import llama_test
from kubeflow_tpu.scaling.endpoints import EndpointPool
from kubeflow_tpu.serving import wire

PROMPT_LEN = 8
NEW_TOKENS = 6
CACHE = 32


# --- trace context: parent links + leg tags --------------------------------

def test_child_context_parents_and_legs():
    ctx = tracing.new_context()
    child = ctx.child("hedge")
    assert child.trace_id == ctx.trace_id
    assert child.parent_span_id == ctx.span_id
    assert child.span_id != ctx.span_id
    assert child.leg == "hedge"
    # leg=None inherits; a fresh tag overrides.
    assert child.child().leg == "hedge"
    assert child.child("resume-1").leg == "resume-1"


def test_from_headers_mints_hop_span_with_parent():
    ctx = tracing.new_context()
    hop = tracing.from_headers(ctx.child("decode").headers())
    assert hop.trace_id == ctx.trace_id
    # The inbound span id is the CALLER's: this hop's parent, never
    # its own id (one tree node per hop).
    assert hop.parent_span_id is not None
    assert hop.span_id != hop.parent_span_id
    assert hop.leg == "decode"


def test_grpc_metadata_round_trips_leg():
    ctx = tracing.new_context().child("primary")
    back = tracing.from_grpc_metadata(ctx.grpc_metadata())
    assert back.trace_id == ctx.trace_id
    assert back.parent_span_id == ctx.span_id
    assert back.leg == "primary"


def test_span_args_linkage():
    ctx = tracing.new_context().child("prefill")
    args = tracing.span_args(ctx, model="m", outcome="ok")
    assert args["trace_id"] == ctx.trace_id
    assert args["parent_id"] == ctx.span_id
    assert args["leg"] == "prefill"
    assert args["model"] == "m"
    # No context → just the extras (a documented root's shape).
    assert tracing.span_args(None, model="m") == {"model": "m"}


# --- SpanStore: bounded, dedup, drop-counted -------------------------------

def _span(trace_id, name="s", ts=None, dur=1000.0, pid=1, tid=1,
          **args):
    return {"name": name, "cat": "t", "ph": "X",
            "ts": ts if ts is not None else random.random() * 1e9,
            "dur": dur, "pid": pid, "tid": tid,
            "args": {"trace_id": trace_id, **args}}


def test_span_store_caps_fuzz():
    rng = random.Random(7)
    store = SpanStore(max_traces=8, max_spans_per_trace=16)
    for _ in range(3000):
        trace_id = f"t{rng.randrange(40):02d}"
        store.ingest([_span(trace_id, ts=rng.random() * 1e9,
                            tid=rng.randrange(4))])
        state = store.state()
        assert state["traces"] <= 8
        assert state["spans"] <= 8 * 16
    state = store.state()
    assert state["evicted_traces"] > 0
    assert state["ingested"] > 0
    # Per-trace overflow is COUNTED, never stored — and counted ONCE:
    # a rescrape of the same overlapping ring must not re-inflate the
    # drop counter (the cap-discipline signal would become noise).
    store2 = SpanStore(max_traces=2, max_spans_per_trace=4)
    batch = [_span("hot", ts=float(i)) for i in range(10)]
    ingested, dropped = store2.ingest(batch)
    assert (ingested, dropped) == (4, 6)
    assert store2.dropped_spans == 6
    assert store2.ingest(batch) == (0, 0)
    assert store2.dropped_spans == 6


def test_span_store_dedups_rescrape_and_matches_request_id():
    store = SpanStore()
    span = _span("abc123", name="http_request", ts=42.0,
                 request_id="req-9")
    assert store.ingest([span], instance="a:1") == (1, 0)
    # The same ring scraped twice (or once via scrape + once via
    # push) must not double the trace.
    assert store.ingest([span], instance="b:2") == (0, 0)
    assert len(store.trace("abc123")) == 1
    # request-id lookup (the access-log join key a human holds).
    assert store.trace("req-9")[0]["args"]["instance"] == "a:1"
    assert store.trace_ids()[0]["trace_id"] == "abc123"


# --- assembly + attribution over synthetic spans ---------------------------

def _synthetic_trace():
    """A role-split request's shape: proxy root, one proxy-side
    upstream window per hop, server legs under the windows, engine
    spans under the server legs."""
    t = "f" * 32
    spans = [
        _span(t, name="proxy_request", ts=0.0, dur=100_000.0,
              span_id="p" * 16, model="m"),
        _span(t, name="proxy_upstream", ts=1.0, dur=32_000.0,
              span_id="u" * 16, parent_id="p" * 16, leg="prefill"),
        _span(t, name="proxy_upstream", ts=2.0, dur=52_000.0,
              span_id="v" * 16, parent_id="p" * 16, leg="decode"),
        _span(t, name="http_request", ts=0.0, dur=30_000.0, pid=2,
              span_id="a" * 16, parent_id="u" * 16, leg="prefill"),
        _span(t, name="engine_prefill", ts=1.0, dur=25_000.0, pid=2,
              parent_id="a" * 16, leg="prefill", handoff=True),
        _span(t, name="http_request", ts=0.0, dur=50_000.0, pid=3,
              span_id="b" * 16, parent_id="v" * 16, leg="decode"),
        _span(t, name="engine_request", ts=2.0, dur=45_000.0, pid=3,
              parent_id="b" * 16, leg="decode", queue_ms=5.0,
              prefill_ms=1.0, decode_ms=40.0),
    ]
    return t, spans


def test_assemble_tree_shape():
    _, spans = _synthetic_trace()
    assembled = obs_trace.assemble(spans)
    assert len(assembled["roots"]) == 1
    root = assembled["roots"][0]
    assert root["span"]["name"] == "proxy_request"
    hops = {c["span"]["args"]["leg"]: c for c in root["children"]}
    assert set(hops) == {"prefill", "decode"}
    for leg, hop in hops.items():
        assert hop["span"]["name"] == "proxy_upstream"
        (server,) = hop["children"]
        assert server["span"]["name"] == "http_request"
        assert server["span"]["args"]["leg"] == leg
    assert hops["prefill"]["children"][0]["children"][0]["span"][
        "name"] == "engine_prefill"
    assert hops["decode"]["children"][0]["children"][0]["span"][
        "name"] == "engine_request"


def test_attribution_buckets_cover_wall():
    _, spans = _synthetic_trace()
    report = obs_trace.attribution(spans)
    b = report["buckets"]
    assert report["total_ms"] == 100.0
    assert b["queue_ms"] == 5.0
    # hop1's slot-less prefill (handoff=True) + hop2's adopt.
    assert b["prefill_ms"] == 26.0
    assert b["decode_ms"] == 40.0
    # relay is MEASURED: proxy wall minus its upstream windows.
    assert b["relay_ms"] == 16.0
    # gap = per-leg network gaps (2 + 2) + server residual (80 - 71).
    assert b["gap_ms"] == 13.0
    assert report["coverage"] == 1.0
    assert report["legs"] == {"decode": 50.0, "prefill": 30.0}
    assert report["upstream_legs"] == {"decode": 52.0,
                                       "prefill": 32.0}
    assert report["missing"] == []
    # An upstream window whose server was never scraped is NOT
    # covered: coverage drops and the leg lands in missing — the
    # signal the assembly layer owes.
    partial = [s for s in spans
               if not (s["name"] in ("http_request", "engine_request")
                       and s["args"].get("leg") == "decode")]
    partial_report = obs_trace.attribution(partial)
    assert partial_report["coverage"] < 0.95
    assert "server_leg:decode" in partial_report["missing"]


def test_attribution_kv_fetch_gets_its_own_bucket():
    """The fleet KV pull-through's spend (ISSUE 20) rides the
    engine's exact per-request figure into its OWN bucket — never
    folded into prefill or decode, so a slow owner shows up as
    kv_fetch time in the waterfall, not as a phantom decode
    regression."""
    t = "d" * 32
    spans = [
        _span(t, name="http_request", ts=0.0, dur=60_000.0, pid=3,
              span_id="b" * 16, leg="decode"),
        _span(t, name="engine_request", ts=2.0, dur=55_000.0, pid=3,
              parent_id="b" * 16, leg="decode", queue_ms=5.0,
              kv_fetch_ms=6.0, prefill_ms=4.0, decode_ms=40.0),
    ]
    report = obs_trace.attribution(spans)
    b = report["buckets"]
    assert b["kv_fetch_ms"] == 6.0
    assert b["queue_ms"] == 5.0
    assert b["prefill_ms"] == 4.0
    assert b["decode_ms"] == 40.0
    # A trace with no fetch reports the bucket as plain zero (the
    # column is always present for dashboards to sum).
    no_fetch = obs_trace.attribution(_synthetic_trace()[1])
    assert no_fetch["buckets"]["kv_fetch_ms"] == 0.0


def test_attribution_direct_to_server():
    t = "e" * 32
    spans = [
        _span(t, name="http_request", ts=0.0, dur=40_000.0,
              span_id="a" * 16),
        _span(t, name="queue_wait", ts=0.0, dur=8_000.0,
              parent_id="a" * 16),
        _span(t, name="execute", ts=1.0, dur=30_000.0,
              parent_id="a" * 16),
    ]
    report = obs_trace.attribution(spans)
    assert report["total_ms"] == 40.0
    assert report["buckets"]["queue_ms"] == 8.0
    assert report["buckets"]["decode_ms"] == 30.0
    assert report["buckets"]["relay_ms"] == 0.0
    assert report["buckets"]["gap_ms"] == 2.0
    assert report["coverage"] == 1.0
    assert "proxy_request" in report["missing"]


# --- export queue + shipper (push path) ------------------------------------

def test_tracer_export_queue_bounded_with_pressure_hook():
    tr = tracing.Tracer(capacity=64)
    tr.enable_export(8)
    fired = []
    tr.on_export_pressure = lambda: fired.append(True)
    for i in range(20):
        tr.record("x", "c", float(i), 0.1, {"trace_id": "t" * 32})
    stats = tr.export_stats()
    assert stats["queued"] == 8  # bounded
    assert stats["dropped"] == 12  # counted, never unbounded
    assert fired  # pressure hook woke the shipper
    assert len(tr.drain_export()) == 8
    assert tr.export_stats() == {"queued": 0, "dropped": 12}
    tr.disable_export()
    tr.record("x", "c", 0.0, 0.1, {"trace_id": "t" * 32})
    assert tr.drain_export() == []


def test_span_shipper_posts_batches():
    tr = tracing.Tracer(capacity=64)
    posts = []
    shipper = SpanShipper(tr, "127.0.0.1:9", component="unit",
                          post=lambda url, body: posts.append(
                              (url, json.loads(body))))
    tr.enable_export(32)
    for i in range(5):
        tr.record("y", "c", float(i), 0.1, {"trace_id": "a" * 32})
    assert shipper.ship_once() == 5
    (url, doc), = posts
    assert url.endswith("/spans")
    assert doc["component"] == "unit"
    assert len(doc["spans"]) == 5
    # A dead collector drops the batch and counts the failure.
    def boom(url, body):
        raise OSError("refused")
    shipper._post = boom
    tr.record("y", "c", 9.0, 0.1, {"trace_id": "a" * 32})
    assert shipper.ship_once() == 0
    assert shipper.failed_posts == 1


def test_exposition_trace_endpoints_and_push():
    import urllib.request

    from kubeflow_tpu.obs.exposition import start_exposition_server

    store = SpanStore()
    server = start_exposition_server(0, span_store=store,
                                     host="127.0.0.1")
    port = server.server_address[1]
    try:
        _, spans = _synthetic_trace()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/spans",
            data=json.dumps({"component": "unit",
                             "spans": spans}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert json.loads(resp.read())["ingested"] == len(spans)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/traces", timeout=5) as resp:
            doc = json.loads(resp.read())
        assert doc["traces"][0]["spans"] == len(spans)
        trace_id = doc["traces"][0]["trace_id"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/trace?trace_id={trace_id}",
                timeout=5) as resp:
            doc = json.loads(resp.read())
        assert doc["attribution"]["coverage"] == 1.0
        # The kft-trace CLI speaks exactly this surface.
        rc = obs_trace.main([trace_id,
                             "--collector", f"127.0.0.1:{port}"])
        assert rc == 0
    finally:
        server.shutdown()


# --- engine cold-start profile: compile events + slice records -------------

def test_engine_cold_start_emits_compile_and_slice_spans():
    from kubeflow_tpu.inference.engine import DecodeEngine, EngineConfig

    model = llama_test(dtype=jnp.float32, cache_size=CACHE)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, PROMPT_LEN), jnp.int32))
    engine = DecodeEngine(model, variables["params"], EngineConfig(
        max_new_tokens=NEW_TOKENS, max_prompt_len=PROMPT_LEN,
        temperature=0.8, num_slots=2, page_size=4, slice_tokens=2,
        seed=0), name="trace-asm-cold")
    ctx = tracing.new_context()
    try:
        engine.submit(np.asarray([3, 4, 5], np.int32),
                      obs_ctx=ctx).result(timeout=120)
    finally:
        engine.stop()
    spans = [s for s in tracing.TRACER.snapshot()
             if (s.get("args") or {}).get("model") == "trace-asm-cold"]
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    # Cold start: the prefill and first decode slice are jit traces.
    compiles = {s["args"]["program"]: s["args"]
                for s in by_name.get("engine_compile", ())}
    assert "prefill" in compiles
    assert "decode_slice" in compiles
    # A request-triggered compile joins THAT request's trace — the
    # cold-start waterfall contains its compile events.
    assert compiles["prefill"]["trace_id"] == ctx.trace_id
    # Per-slice structured profile records.
    slice_span = by_name["engine_slice"][0]
    assert slice_span["args"]["slots"] >= 1
    assert slice_span["args"]["steps"] >= 1
    assert "free_pages" in slice_span["args"]
    # Per-request attribution triple, linked to the request's trace.
    req_span = by_name["engine_request"][0]
    assert req_span["args"]["trace_id"] == ctx.trace_id
    assert req_span["args"]["parent_id"] == ctx.span_id
    for key in ("queue_ms", "prefill_ms", "decode_ms"):
        assert req_span["args"][key] >= 0.0
    assert req_span["args"]["decode_ms"] > 0.0
    stats = engine.stats()
    assert stats["slices"] >= 1
    assert stats["compiled_programs"] >= 2


# --- multi-process-shaped e2e: proxy + 2 role servers + collector ----------

@pytest.fixture(scope="module")
def trace_stack(tmp_path_factory):
    """The role_stack harness (test_role_routing) + a hedging proxy
    and the span-scraping collector targets."""
    import asyncio

    from kubeflow_tpu.serving.export import export_model
    from kubeflow_tpu.serving.manager import ModelManager
    from kubeflow_tpu.serving.signature import (
        ModelMetadata,
        Signature,
        TensorSpec,
    )

    base = tmp_path_factory.mktemp("trace") / "m"
    model = llama_test(dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, PROMPT_LEN), jnp.int32))
    meta = ModelMetadata(
        model_name="m", registry_name="llama-test",
        model_kwargs={"dtype": "float32", "cache_size": CACHE},
        signatures={"serving_default": Signature(
            "generate",
            {"input_ids": TensorSpec("int32", (-1, PROMPT_LEN))},
            {"tokens": TensorSpec("int32", (-1, NEW_TOKENS))})},
        generate_config={"max_new_tokens": NEW_TOKENS,
                         "temperature": 0.8, "seed": 11,
                         "deterministic": True,
                         "engine_slots": 2, "engine_page_size": 8,
                         "engine_slice_tokens": 2})
    export_model(str(base), 1, meta, {"params": variables["params"]})

    from kubeflow_tpu.serving.http_proxy import make_app as proxy_app
    from kubeflow_tpu.serving.server import make_app as rest_app

    managers, holders = [], []

    def serve(factory, holder, started):
        import tornado.ioloop

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        server = factory().listen(0)
        holder["port"] = next(iter(
            server._sockets.values())).getsockname()[1]
        holder["loop"] = tornado.ioloop.IOLoop.current()
        started.set()
        holder["loop"].start()

    for role in ("prefill", "decode"):
        mgr = ModelManager(poll_interval_s=3600)
        mgr.add_model("m", str(base), max_batch=4,
                      continuous_batching=True)
        managers.append(mgr)
        holder, started = {"role": role}, threading.Event()
        threading.Thread(
            target=serve,
            args=(lambda m=mgr, r=role: rest_app(m, role=r), holder,
                  started),
            daemon=True).start()
        assert started.wait(60)
        holders.append(holder)

    pool = EndpointPool()
    for holder in holders:
        pool.add(f"127.0.0.1:{holder['port']}", None, holder["role"])
    proxy, started = {}, threading.Event()
    threading.Thread(
        target=serve,
        args=(lambda: proxy_app(pool=pool, balancer="role",
                                probe_interval_s=3600.0), proxy,
              started),
        daemon=True).start()
    assert started.wait(60)

    # A second, hedging proxy over the SAME two servers (round-robin
    # ignores roles; both replicas serve full generates).
    hedge_pool = EndpointPool()
    for holder in holders:
        hedge_pool.add(f"127.0.0.1:{holder['port']}", None, "any")
    hedge_holder, started = {}, threading.Event()
    hedge_app_box = {}

    def hedge_factory():
        app = proxy_app(pool=hedge_pool, balancer="round_robin",
                        probe_interval_s=3600.0, hedge_rate=1.0)
        hedge_app_box["app"] = app
        return app

    threading.Thread(target=serve,
                     args=(hedge_factory, hedge_holder, started),
                     daemon=True).start()
    assert started.wait(60)

    targets = [(f"127.0.0.1:{h['port']}", "serving")
               for h in holders]
    targets.append((f"127.0.0.1:{proxy['port']}", "router"))
    targets.append((f"127.0.0.1:{hedge_holder['port']}", "router"))
    yield {"base": base, "proxy": proxy, "holders": holders,
           "managers": managers, "pool": pool, "targets": targets,
           "hedge": hedge_holder, "hedge_app": hedge_app_box}
    for holder in holders + [proxy, hedge_holder]:
        holder["loop"].add_callback(holder["loop"].stop)
    for mgr in managers:
        mgr.stop()


def _collect_trace(stack, trace_id, want_names, timeout=15):
    """Scrape the fleet until the trace holds ``want_names``."""
    collector = Collector(TimeSeriesStore(),
                          static_targets=stack["targets"],
                          span_store=SpanStore(max_traces=64))
    try:
        deadline = time.monotonic() + timeout
        spans = []
        while time.monotonic() < deadline:
            collector.scrape_once()
            spans = collector.span_store.trace(trace_id)
            if want_names <= {s["name"] for s in spans}:
                return spans
            time.sleep(0.2)
        names = {s["name"] for s in spans}
        raise AssertionError(
            f"trace {trace_id} never assembled {want_names - names}; "
            f"got {sorted(names)}")
    finally:
        collector.stop()


def _one_trace_fleetwide(request_id, trace_id):
    """The continuity regression: every span this request produced —
    whatever leg it rode — carries ONE trace id."""
    seen = {(s.get("args") or {}).get("trace_id")
            for s in tracing.TRACER.snapshot()
            if (s.get("args") or {}).get("request_id") == request_id}
    seen.discard(None)
    assert seen == {trace_id}, f"fleet-wide trace ids: {seen}"


def _post_generate(port, body, headers=None, timeout=120):
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/model/m:generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json",
                 **(headers or {})})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def test_unary_split_assembles_one_trace_with_attribution(trace_stack):
    ctx = tracing.new_context(request_id="trace-asm-unary")
    out = _post_generate(trace_stack["proxy"]["port"],
                         {"instances": [[7] * PROMPT_LEN]},
                         headers=ctx.headers())
    assert out["predictions"][0]["tokens"]
    spans = _collect_trace(
        trace_stack, ctx.trace_id,
        {"proxy_request", "proxy_upstream", "http_request",
         "engine_request", "engine_prefill"})
    _one_trace_fleetwide("trace-asm-unary", ctx.trace_id)
    # Tree shape: one proxy root; both split hops hang under it as
    # leg-tagged upstream windows, each carrying its server span.
    assembled = obs_trace.assemble(spans)
    roots = [r for r in assembled["roots"]
             if r["span"]["name"] == "proxy_request"]
    assert len(roots) == 1
    hops = {c["span"]["args"].get("leg"): c
            for c in roots[0]["children"]
            if c["span"]["name"] == "proxy_upstream"}
    assert {"prefill", "decode"} <= set(hops)
    for leg in ("prefill", "decode"):
        server_children = [n for n in hops[leg]["children"]
                           if n["span"]["name"] == "http_request"]
        assert server_children, f"{leg} hop has no server span"
    # Attribution: buckets cover >=95% of the client-measured wall
    # (the acceptance bar), with real prefill and decode time.
    report = obs_trace.attribution(spans)
    assert report["coverage"] >= 0.95
    assert report["buckets"]["prefill_ms"] > 0.0
    assert report["buckets"]["decode_ms"] > 0.0
    assert report["missing"] == []


def test_sse_split_stream_assembles_one_trace(trace_stack):
    import http.client

    ctx = tracing.new_context(request_id="trace-asm-sse")
    conn = http.client.HTTPConnection(
        "127.0.0.1", trace_stack["proxy"]["port"], timeout=120)
    conn.request(
        "POST", "/model/m:generate",
        body=json.dumps({"instances": [[2, 3, 4, 5]],
                         "stream": True}),
        headers={"Content-Type": "application/json",
                 **ctx.headers()})
    resp = conn.getresponse()
    assert resp.status == 200
    done = None
    for event, data in wire.iter_sse_events(resp):
        if event == "done":
            done = data
    conn.close()
    assert done is not None
    spans = _collect_trace(
        trace_stack, ctx.trace_id,
        {"proxy_request", "http_request", "engine_request"})
    _one_trace_fleetwide("trace-asm-sse", ctx.trace_id)
    legs = {(s.get("args") or {}).get("leg") for s in spans}
    assert {"prefill", "decode"} <= legs
    report = obs_trace.attribution(spans)
    assert report["coverage"] >= 0.95
    assert report["buckets"]["decode_ms"] > 0.0


def test_hedged_twins_share_one_trace_with_distinct_legs(trace_stack):
    # Prime the hedge window so the delay is ~instant and the twin
    # always fires (rate cap 1.0; generous budget).
    app = trace_stack["hedge_app"]["app"]
    for _ in range(8):
        app.settings["hedge_latency"].observe(0.0005)
    ctx = tracing.new_context(request_id="trace-asm-hedge")
    out = _post_generate(
        trace_stack["hedge"]["port"],
        {"instances": [[9] * PROMPT_LEN]},
        headers={**ctx.headers(), "X-Deadline-Ms": "60000"})
    assert out["predictions"][0]["tokens"]
    spans = _collect_trace(trace_stack, ctx.trace_id,
                           {"proxy_request", "engine_request"})
    _one_trace_fleetwide("trace-asm-hedge", ctx.trace_id)
    legs = {(s.get("args") or {}).get("leg") for s in spans}
    assert "primary" in legs
    assert "hedge" in legs, f"hedge leg missing; legs={legs}"
    # Distinct leg-tagged span ids: the twins are separate tree
    # nodes, one waterfall.
    parent_ids = {(s.get("args") or {}).get("parent_id")
                  for s in spans
                  if s["name"] == "engine_request"}
    assert len(parent_ids) >= 2


# --- kill + resume keeps one trace id (fault-injected, slow tier) ----------

@pytest.mark.slow
def test_kill_resume_stream_keeps_one_trace_id(trace_stack,
                                               monkeypatch, tmp_path):
    """ISSUE 15 satellite regression: one client request through
    kill+resume produces exactly ONE trace_id fleet-wide, with the
    resume replay leg-tagged."""
    import asyncio
    import http.client

    monkeypatch.setenv("KFT_ENABLE_FAULTS", "1")
    from kubeflow_tpu.serving.manager import ModelManager
    from kubeflow_tpu.serving.http_proxy import make_app as proxy_app
    from kubeflow_tpu.serving.server import make_app as rest_app

    plan = tmp_path / "plan.json"
    plan.write_text(json.dumps({"rules": [{
        "match": {"route": "generate", "phase": "stream"},
        "action": {"kill_after_events": 2},
    }]}))

    managers, holders = [], []
    proxy = {}

    def serve(factory, holder, started):
        import tornado.ioloop

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        server = factory().listen(0)
        holder["port"] = next(iter(
            server._sockets.values())).getsockname()[1]
        holder["loop"] = tornado.ioloop.IOLoop.current()
        started.set()
        holder["loop"].start()

    try:
        for i in range(2):
            mgr = ModelManager(poll_interval_s=3600)
            mgr.add_model("m", str(trace_stack["base"]), max_batch=4,
                          continuous_batching=True)
            managers.append(mgr)
            holder, started = {}, threading.Event()
            threading.Thread(
                target=serve,
                args=(lambda m=mgr: rest_app(m, fault_plan=str(plan)),
                      holder, started),
                daemon=True).start()
            assert started.wait(60)
            holders.append(holder)
        pool = EndpointPool()
        for holder in holders:
            pool.add(f"127.0.0.1:{holder['port']}", None, "any")
        proxy, started = {}, threading.Event()
        threading.Thread(
            target=serve,
            args=(lambda: proxy_app(pool=pool, balancer="round_robin",
                                    probe_interval_s=3600.0), proxy,
                  started),
            daemon=True).start()
        assert started.wait(60)

        ctx = tracing.new_context(request_id="trace-asm-resume")
        conn = http.client.HTTPConnection("127.0.0.1", proxy["port"],
                                          timeout=180)
        conn.request(
            "POST", "/model/m:generate",
            body=json.dumps({"instances": [[4] * PROMPT_LEN],
                             "stream": True}),
            headers={"Content-Type": "application/json",
                     **ctx.headers()})
        resp = conn.getresponse()
        assert resp.status == 200
        events = list(wire.iter_sse_events(resp))
        conn.close()
        assert any(e == "done" for e, _ in events), events
        assert not any(e == "error" for e, _ in events), events
        # Exactly one trace id fleet-wide, resume leg tagged.
        _one_trace_fleetwide("trace-asm-resume", ctx.trace_id)
        legs = {(s.get("args") or {}).get("leg")
                for s in tracing.TRACER.snapshot()
                if (s.get("args") or {}).get("trace_id")
                == ctx.trace_id}
        assert any(str(leg).startswith("resume-") for leg in legs), \
            f"no resume leg recorded; legs={legs}"
    finally:
        for holder in holders + [proxy]:
            if "loop" in holder:
                holder["loop"].add_callback(holder["loop"].stop)
        for mgr in managers:
            mgr.stop()
