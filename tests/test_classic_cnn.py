# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Classic CNN zoo (vgg16/alexnet): shapes, training, mesh step —
the remaining values of the reference's tf-cnn ``--model`` flag."""

import numpy as np

import jax
import jax.numpy as jnp
import optax

from kubeflow_tpu.models.registry import get_model
from kubeflow_tpu.models.classic_cnn import alexnet, vgg_test
from kubeflow_tpu.training.train import (
    create_train_state,
    make_train_step,
    place_batch,
    place_state,
)


def test_registry_and_forward_shapes():
    model = get_model("vgg-test").make()
    x = jnp.zeros((2, 32, 32, 3), jnp.bfloat16)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32
    # Full-size entries resolve and declare the canonical input.
    assert get_model("vgg16").input_spec == ((224, 224, 3), "bfloat16")
    assert get_model("alexnet").input_spec == ((224, 224, 3), "bfloat16")


def test_alexnet_forward_small_input():
    # 64² exercises all three pools (the canonical 224² is too heavy
    # for CI; stride arithmetic is input-size-independent with SAME).
    model = alexnet(num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((2, 64, 64, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)


def test_vgg_trains_single_device():
    model = vgg_test(dtype=jnp.float32)
    state = create_train_state(
        model, optax.adamw(1e-3), jax.random.PRNGKey(0),
        jnp.zeros((1, 32, 32, 3), jnp.float32))
    assert state.batch_stats is None  # no BN in classic VGG
    step = make_train_step(None, donate=False)
    rng = np.random.RandomState(0)
    batch = {"inputs": jnp.asarray(rng.rand(8, 32, 32, 3), jnp.float32),
             "labels": jnp.asarray(rng.randint(0, 10, 8))}
    _, first = step(state, batch)
    for _ in range(10):
        state, metrics = step(state, batch)
    assert float(metrics["loss"]) < float(first["loss"])


def test_vgg_dp_fsdp_mesh_step():
    from kubeflow_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(data=2, fsdp=2), jax.devices("cpu")[:4])
    model = vgg_test()
    state = create_train_state(
        model, optax.sgd(0.1), jax.random.PRNGKey(0),
        jnp.zeros((1, 32, 32, 3), jnp.bfloat16))
    state = place_state(mesh, state)
    rng = jax.random.PRNGKey(1)
    batch = place_batch(mesh, {
        "inputs": jax.random.normal(rng, (8, 32, 32, 3), jnp.bfloat16),
        "labels": jax.random.randint(rng, (8,), 0, 10)})
    step = make_train_step(mesh, donate=False)
    state, metrics = step(state, batch)
    jax.block_until_ready(metrics["loss"])
    assert int(state.step) == 1
    assert np.isfinite(float(metrics["loss"]))
