# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Fleet pull-through KV store (ISSUE 20, tier 2) — the serving-layer
half, model-free: owner addressing, fetch gating, and above all the
failure semantics. A fleet fetch is an optimisation, never
load-bearing: every failure mode here must degrade to "pay local
prefill" with zero raises out of :func:`prefetch_into`."""

import base64
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from kubeflow_tpu.serving import kv_store, wire


# -- addressing ------------------------------------------------------------


def test_kv_fetch_path_pins_version():
    assert kv_store.kv_fetch_path("m") == "/v1/models/m:kv/fetch"
    assert kv_store.kv_fetch_path("m", 3) == \
        "/v1/models/m/versions/3:kv/fetch"


def test_prompt_of_first_row_or_none():
    assert kv_store.prompt_of([[1, 2, 3], [9]]) == [1, 2, 3]
    assert kv_store.prompt_of(np.asarray([[4, 5]])) == [4, 5]
    assert kv_store.prompt_of([[]]) is None
    assert kv_store.prompt_of([]) is None
    assert kv_store.prompt_of("garbage") is None
    assert kv_store.prompt_of([["x", "y"]]) is None
    assert kv_store.prompt_of(None) is None


def test_rendezvous_owner_is_stable_and_matches_affinity():
    """The owner the proxy names in X-KFT-KV-Owner must be the SAME
    replica the prefix-affinity balancer steers traffic to — that
    coupling is what makes the owner's caches worth asking. It must
    also hold over the full routable pool, not drift with exclusions,
    and survive membership churn for keys whose owner stayed."""
    from kubeflow_tpu.scaling.balancer import (
        PrefixAffinityBalancer,
        rendezvous_owner,
    )
    from kubeflow_tpu.scaling.endpoints import Endpoint

    eps = [Endpoint(f"replica-{i}:900{i}", register_metrics=False)
           for i in range(3)]
    bal = PrefixAffinityBalancer(overload_ms=100.0)
    for i in range(20):
        key = f"conv-{i}"
        owner = rendezvous_owner(eps, key)
        assert owner is not None
        # Stable across calls...
        assert rendezvous_owner(eps, key).address == owner.address
        # ...and identical to where the balancer routes the key.
        assert bal.pick(eps, prefix_key=key).address == owner.address
    # Churn: keys not owned by the departed replica keep their owner.
    gone = rendezvous_owner(eps, "conv-0").address
    survivors = [ep for ep in eps if ep.address != gone]
    for i in range(20):
        key = f"conv-{i}"
        if rendezvous_owner(eps, key).address != gone:
            assert rendezvous_owner(survivors, key).address == \
                rendezvous_owner(eps, key).address
    assert rendezvous_owner(eps, None) is None
    assert rendezvous_owner([], "k") is None


# -- prefetch_into: gating + failure semantics -----------------------------


class _StubEngine:
    """Just the surface prefetch_into touches, with call recording."""

    class _Cfg:
        page_size = 4

    def __init__(self, *, host_tier=object(), probe=0, imports=None):
        self.host_tier = host_tier
        self.config = self._Cfg()
        self._probe = probe
        self._imports = imports
        self.fetch_notes = []
        self.imported_payloads = []

    def probe_prefix(self, prompt):
        return self._probe

    def import_prefix_blocks(self, blocks):
        self.imported_payloads.append(blocks)
        if isinstance(self._imports, Exception):
            raise self._imports
        return len(blocks) if self._imports is None else self._imports

    def note_kv_fetch(self, outcome, *, blocks=0):
        self.fetch_notes.append((outcome, blocks))


def test_prefetch_skips_when_it_cannot_pay_off():
    """Every skip gate returns 0.0 WITHOUT touching the network (the
    owner_url below would raise instantly if dialled) and without
    noting a fetch — skips are not misses."""
    url = "http://owner.invalid:1"
    tokens = list(range(12))
    # No engine / no host tier.
    assert kv_store.prefetch_into(None, "m", 1, url, tokens) == 0.0
    e = _StubEngine(host_tier=None)
    assert kv_store.prefetch_into(e, "m", 1, url, tokens) == 0.0
    # Un-int-able prompt.
    e = _StubEngine()
    assert kv_store.prefetch_into(e, "m", 1, url, ["x"]) == 0.0
    # Too short to span one full block (page_size=4: 4 tokens = the
    # final token excluded → 0 consumable blocks).
    assert kv_store.prefetch_into(e, "m", 1, url, [1, 2, 3, 4]) == 0.0
    # Local match already covers every consumable block.
    e = _StubEngine(probe=8)
    assert kv_store.prefetch_into(e, "m", 1, url,
                                  list(range(9))) == 0.0
    # Deadline already spent / fetching disabled.
    e = _StubEngine()
    assert kv_store.prefetch_into(e, "m", 1, url, tokens,
                                  deadline_ms=0) == 0.0
    assert kv_store.prefetch_into(
        e, "m", 1, url, tokens,
        deadline=time.monotonic() - 1.0) == 0.0
    assert e.fetch_notes == [] and e.imported_payloads == []


def test_prefetch_dead_owner_is_an_error_note_never_a_raise():
    """THE chaos acceptance for this tier: the owner is unreachable
    and the asker's request proceeds to local prefill — prefetch_into
    returns elapsed seconds, notes one 'error', and raises nothing."""
    e = _StubEngine()
    spent = kv_store.prefetch_into(
        e, "m", 1, "http://127.0.0.1:1", list(range(12)),
        deadline_ms=200)
    assert spent >= 0.0
    assert e.fetch_notes == [("error", 0)]
    assert e.imported_payloads == []


def test_prefetch_import_failure_is_an_error_note_never_a_raise(
        monkeypatch):
    e = _StubEngine(imports=RuntimeError("pool shape moved"))
    monkeypatch.setattr(
        kv_store, "fetch_blocks",
        lambda *a, **k: [((1, 2, 3, 4),
                          [np.zeros((4, 2, 2), np.float32)])])
    spent = kv_store.prefetch_into(e, "m", 1, "http://x", range(12))
    assert spent >= 0.0
    assert e.fetch_notes == [("error", 0)]


def test_prefetch_outcomes_hit_and_miss(monkeypatch):
    blocks = [((1, 2, 3, 4), [np.zeros((4, 2, 2), np.float32)])] * 2
    # Owner answered with adoptable blocks → hit with the count.
    e = _StubEngine()
    monkeypatch.setattr(kv_store, "fetch_blocks",
                        lambda *a, **k: list(blocks))
    assert kv_store.prefetch_into(e, "m", 1, "http://x",
                                  range(12)) >= 0.0
    assert e.fetch_notes == [("hit", 2)]
    # Owner answered cleanly but held nothing → miss.
    e = _StubEngine()
    monkeypatch.setattr(kv_store, "fetch_blocks", lambda *a, **k: [])
    kv_store.prefetch_into(e, "m", 1, "http://x", range(12))
    assert e.fetch_notes == [("miss", 0)]
    # Blocks arrived but none survived the import shape gate → miss.
    e = _StubEngine(imports=0)
    monkeypatch.setattr(kv_store, "fetch_blocks",
                        lambda *a, **k: list(blocks))
    kv_store.prefetch_into(e, "m", 1, "http://x", range(12))
    assert e.fetch_notes == [("miss", 0)]


def test_fetch_blocks_round_trip_against_live_owner():
    """fetch_blocks speaks real HTTP to a real (stub) owner: the
    request body carries the token ids, the response's b64 msgpack
    decodes byte-exact, and an empty answer is a clean []."""
    payload = wire.encode_kv_blocks(
        "m", 2, 4,
        [((5, 6, 7, 8), [np.arange(16, dtype=np.float32
                                   ).reshape(4, 2, 2)])])
    seen = {}

    class _Owner(BaseHTTPRequestHandler):
        def do_POST(self):
            body = json.loads(self.rfile.read(
                int(self.headers["Content-Length"])))
            seen["path"] = self.path
            seen["tokens"] = body["tokens"]
            blob = (base64.b64encode(payload).decode()
                    if body["tokens"] else None)
            out = json.dumps({"blocks": blob}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(out)))
            self.end_headers()
            self.wfile.write(out)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), _Owner)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        url = f"http://127.0.0.1:{srv.server_port}"
        got = kv_store.fetch_blocks(url, "m", 2, 4, [5, 6, 7, 8, 9],
                                    timeout_s=5.0)
        assert seen["path"] == "/v1/models/m/versions/2:kv/fetch"
        assert seen["tokens"] == [5, 6, 7, 8, 9]
        assert len(got) == 1 and got[0][0] == (5, 6, 7, 8)
        np.testing.assert_array_equal(
            got[0][1][0],
            np.arange(16, dtype=np.float32).reshape(4, 2, 2))
        assert kv_store.fetch_blocks(url, "m", 2, 4, [],
                                     timeout_s=5.0) == []
        # Version skew: the asker pins ITS version; a payload built
        # for another one must raise (prefetch_into maps it to a
        # fall-back, tested above).
        with pytest.raises(ValueError):
            kv_store.fetch_blocks(url, "m", 3, 4, [5, 6, 7, 8, 9],
                                  timeout_s=5.0)
    finally:
        srv.shutdown()
        srv.server_close()
