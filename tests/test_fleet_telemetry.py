# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Fleet telemetry pipeline end to end (ISSUE 9 acceptance): a
3-replica fake fleet with an injected deadline-exceeded burst — the
collector aggregates cross-replica rates, the fast-burn SLO alert
walks pending→firing (Event + kft-alerts ConfigMap + kft_alert_state
gauge) and resolves after the burst; a deadline-bucket exemplar
resolves to a tail-sampling-retained trace through /tracez?trace_id=;
the series-cardinality cap holds under a label-churn fuzz riding the
scrape path; and the /metrics OpenMetrics negotiation + /tracez
filters work over real HTTP."""

import json
import random

import tornado.testing
import tornado.web

from kubeflow_tpu.obs import metrics as obs_metrics
from kubeflow_tpu.obs import tracing as obs_tracing
from kubeflow_tpu.obs.collector import (
    Collector,
    ScrapeTarget,
    TimeSeriesStore,
)
from kubeflow_tpu.obs.exposition import ChromeTraceHandler, MetricsHandler
from kubeflow_tpu.obs.slo import (
    ALERTS_CONFIGMAP,
    ALERTS_KEY,
    AlertManager,
    BurnWindow,
    default_slos,
)
from kubeflow_tpu.operator.fake import FakeApiServer


class _FakeReplica:
    """One serving replica's scrape surface: its own registry with the
    real serving metric families, driven by hand."""

    def __init__(self, address: str):
        self.address = address
        self.registry = obs_metrics.Registry()
        reg = self.registry
        self.rows = obs_metrics.Counter(
            "kft_serving_batch_rows_total", "rows", ("model",),
            registry=reg).labels("m")
        self.shed = obs_metrics.Counter(
            "kft_serving_shed_total", "shed", ("model",),
            registry=reg).labels("m")
        self.expired = obs_metrics.Counter(
            "kft_serving_expired_total", "expired", ("model",),
            registry=reg).labels("m")
        self.queue_wait = obs_metrics.Histogram(
            "kft_serving_queue_wait_seconds", "wait", ("model",),
            buckets=(0.05, 0.25, 1.0), registry=reg, exemplars=True)

    def serve(self, n: int) -> None:
        self.rows.inc(n)

    def burst(self, n: int) -> None:
        self.expired.inc(n)


def _fleet(n=3):
    return {f"r{i}:8500": _FakeReplica(f"r{i}:8500") for i in range(n)}


def _pipeline(replicas, *, max_series=4096, for_s=2.0, resolve_s=5.0):
    store = TimeSeriesStore(max_series=max_series)
    collector = Collector(
        store,
        static_targets=[ScrapeTarget(a) for a in replicas],
        interval_s=1.0,
        fetch=lambda t: replicas[t.address].registry.render(
            openmetrics=True))
    fake = FakeApiServer()
    window = BurnWindow("fast", long_s=60.0, short_s=10.0,
                        factor=14.4, severity="page")
    alerts = AlertManager(store, default_slos(windows=(window,)),
                          api=fake, for_s=for_s, resolve_s=resolve_s)
    collector.on_cycle.append(alerts.evaluate)
    return store, collector, alerts, fake


def test_deadline_burst_alert_lifecycle_across_three_replicas():
    replicas = _fleet(3)
    store, collector, alerts, fake = _pipeline(replicas)

    def tick(t, serve=50, burst=0):
        for replica in replicas.values():
            replica.serve(serve)
            if burst:
                replica.burst(burst)
        collector.scrape_once(now=float(t))

    # Healthy half-minute.
    for t in range(30):
        tick(t)
    assert [h["to"] for h in alerts.history] == []
    # Cross-replica aggregation: fleet rows/s is the 3-replica SUM.
    fleet_rate = store.sum_rate("kft_serving_batch_rows_total",
                                window_s=20, now=29)
    per_replica = store.rate("kft_serving_batch_rows_total",
                             window_s=20, now=29)
    assert len(per_replica) == 3
    assert fleet_rate == sum(per_replica.values())
    assert fleet_rate == 150.0  # 3 × 50/s

    # Deadline-exceeded burst on every replica: ~50% violations vs a
    # 1% budget → burn ≫ 14.4 on both windows.
    for t in range(30, 40):
        tick(t, burst=60)
    transitions = [h["to"] for h in alerts.history]
    assert transitions[:2] == ["pending", "firing"]
    assert any(e["reason"] == "AlertFiring"
               for e in fake.list("Event", "default"))
    cm = fake.get("ConfigMap", "default", ALERTS_CONFIGMAP)
    doc = json.loads(cm["data"][ALERTS_KEY])
    assert doc["slos"][0]["slo"] == "serving-deadline"
    fams = obs_metrics.parse_exposition(obs_metrics.render())
    states = {labels["slo"]: v for _, labels, v
              in fams["kft_alert_state"]["samples"]}
    assert states["serving-deadline"] == 2.0  # firing

    # Burst ends; the windows drain, the resolve hold passes.
    for t in range(40, 120):
        tick(t)
    assert [h["to"] for h in alerts.history] \
        == ["pending", "firing", "resolved"]
    assert any(e["reason"] == "AlertResolved"
               for e in fake.list("Event", "default"))
    fams = obs_metrics.parse_exposition(obs_metrics.render())
    states = {labels["slo"]: v for _, labels, v
              in fams["kft_alert_state"]["samples"]}
    assert states["serving-deadline"] == 0.0


def test_cardinality_cap_enforced_over_scrape_path():
    """Label-churn fuzz THROUGH the scrape pipeline: a replica whose
    exposition churns a label value per scrape saturates the store at
    the cap instead of growing without bound."""
    replicas = _fleet(1)
    store, collector, alerts, _ = _pipeline(replicas, max_series=40)
    rng = random.Random(7)
    churny = obs_metrics.Counter(
        "kft_churny_total", "churn", ("victim",),
        registry=next(iter(replicas.values())).registry)
    for t in range(60):
        for _ in range(5):
            churny.labels(f"v{rng.randrange(100_000)}").inc()
        for replica in replicas.values():
            replica.serve(10)
        collector.scrape_once(now=float(t))
        assert store.series_count() <= 40
    assert store.series_count() == 40
    assert store.dropped_series() > 0
    status = collector.target_status(now=60.0)
    assert all(st["ok"] for st in status.values())
    # The capped store still answers fleet queries from the series
    # it admitted first.
    assert store.sum_rate("kft_serving_batch_rows_total",
                          window_s=30, now=59) is not None


class ExemplarToTracezFlow(tornado.testing.AsyncHTTPTestCase):
    """The exemplar workflow over real HTTP: a deadline-bucket
    exemplar scraped from /metrics (OpenMetrics negotiation) resolves
    to a tail-sampling-retained span at /tracez?trace_id=."""

    def get_app(self):
        self.registry = obs_metrics.Registry()
        self.tracer = obs_tracing.Tracer(capacity=64)
        self.tracer.set_tail_sampling(0.0, retained_capacity=64)
        self.hist = obs_metrics.Histogram(
            "kft_serving_queue_wait_seconds", "wait", ("model",),
            buckets=(0.05, 0.25, 1.0), registry=self.registry,
            exemplars=True)
        return tornado.web.Application(
            [(r"/metrics", MetricsHandler),
             (r"/tracez", ChromeTraceHandler)],
            metrics_registry=self.registry, tracer=self.tracer)

    def _drive(self):
        # Happy-path noise: sampled away entirely (keep_prob 0).
        for i in range(50):
            ctx = obs_tracing.new_context()
            self.hist.labels("m").observe(0.01, trace_id=ctx.trace_id)
            self.tracer.record("queue_wait", "serving", float(i),
                               0.01, {"trace_id": ctx.trace_id,
                                      "outcome": "ok"})
        # THE slow request: deadline-exceeded, lands in the top
        # bucket, span retained by outcome.
        slow = obs_tracing.new_context()
        self.hist.labels("m").observe(2.0, trace_id=slow.trace_id)
        self.tracer.record("queue_wait", "serving", 99.0, 2.0,
                           {"trace_id": slow.trace_id,
                            "request_id": slow.request_id,
                            "outcome": "expired"})
        return slow

    def test_exemplar_resolves_to_retained_trace(self):
        slow = self._drive()
        # Scrape over HTTP with the OpenMetrics Accept — the
        # collector's wire format. (fetch body via self.fetch: the
        # in-process HTTP round trip.)
        resp = self.fetch("/metrics", headers={
            "Accept": "application/openmetrics-text; version=1.0.0"})
        assert resp.code == 200
        assert resp.headers["Content-Type"].startswith(
            "application/openmetrics-text")
        text = resp.body.decode()
        assert text.rstrip().endswith("# EOF")
        store = TimeSeriesStore()
        store.ingest_exposition(obs_metrics.parse_exposition(text),
                                1.0, {"instance": "local"})
        exemplars = store.exemplars("kft_serving_queue_wait_seconds")
        by_le = {e["labels"]["le"]: e for e in exemplars}
        # The deadline bucket (+Inf here: 2.0s > top finite bound)
        # carries the slow request's trace id.
        assert by_le["+Inf"]["trace_id"] == slow.trace_id
        # ... which resolves to the RETAINED span via the /tracez
        # filter, even though 50 happy-path spans were dropped.
        resp = self.fetch(f"/tracez?trace_id={slow.trace_id}")
        assert resp.code == 200
        events = [e for e in json.loads(resp.body)["traceEvents"]
                  if e.get("ph") == "X"]
        assert len(events) == 1
        assert events[0]["args"]["outcome"] == "expired"
        assert events[0]["args"]["retain"] == "error"

    def test_plain_scrape_carries_no_exemplars(self):
        self._drive()
        resp = self.fetch("/metrics")
        assert resp.headers["Content-Type"].startswith("text/plain")
        body = resp.body.decode()
        assert " # {" not in body and "# EOF" not in body
        obs_metrics.parse_exposition(body)

    def test_tracez_filters(self):
        self._drive()
        # Error-status filter finds exactly the expired span.
        doc = json.loads(self.fetch("/tracez?status=error").body)
        spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert len(spans) == 1
        # min_duration filter: only the 2 s span is ≥ 1000 ms.
        doc = json.loads(
            self.fetch("/tracez?min_duration_ms=1000").body)
        assert len([e for e in doc["traceEvents"]
                    if e.get("ph") == "X"]) == 1
        # limit bounds the dump.
        self.tracer.set_tail_sampling(None)
        for i in range(20):
            self.tracer.record("s", "app", float(i), 0.001)
        doc = json.loads(self.fetch("/tracez?limit=5").body)
        assert len([e for e in doc["traceEvents"]
                    if e.get("ph") == "X"]) == 5
        # Malformed number → 400, never a 500.
        assert self.fetch("/tracez?limit=banana").code == 400


class DashboardFleetHealth(tornado.testing.AsyncHTTPTestCase):
    """The dashboard's /tpujobs/api/slo + Fleet health page over the
    in-process pipeline."""

    def get_app(self):
        import tempfile

        from kubeflow_tpu.dashboard.server import make_app

        self.replicas = _fleet(2)
        store, self.collector, self.alerts, _ = _pipeline(
            self.replicas, for_s=0.0)
        self.api = FakeApiServer()
        for t in range(15):
            for replica in self.replicas.values():
                replica.serve(50)
                replica.burst(60)  # permanently burning: firing
            self.collector.scrape_once(now=float(t))
        return make_app(self.api, trace_root=tempfile.mkdtemp(),
                        collector=self.collector, alerts=self.alerts)

    def test_slo_api_payload(self):
        resp = self.fetch("/tpujobs/api/slo")
        assert resp.code == 200
        doc = json.loads(resp.body)
        assert doc["available"] and doc["source"] == "in-process"
        assert doc["slos"][0]["slo"] == "serving-deadline"
        assert doc["slos"][0]["state"] == "firing"
        assert doc["collector"]["store"]["series"] > 0
        assert set(doc["collector"]["targets"]) == set(self.replicas)
        assert [h["to"] for h in doc["history"]] \
            == ["pending", "firing"]

    def test_fleet_health_page_renders(self):
        resp = self.fetch("/tpujobs/ui/health")
        assert resp.code == 200
        page = resp.body.decode()
        assert "FIRING" in page
        assert "serving-deadline" in page
        for address in self.replicas:
            assert address in page

    def test_main_page_links_fleet_health(self):
        resp = self.fetch("/tpujobs/ui/")
        assert resp.code == 200
        assert "/tpujobs/ui/health" in resp.body.decode()


class DashboardTelemetryFallback(tornado.testing.AsyncHTTPTestCase):
    """Without an in-process collector the handlers fall back to the
    kft-alerts ConfigMap a sidecar collector publishes — and degrade
    to 404 with the wiring hint when that's absent too."""

    def get_app(self):
        import tempfile

        from kubeflow_tpu.dashboard.server import make_app

        self.api = FakeApiServer()
        return make_app(self.api, trace_root=tempfile.mkdtemp())

    def test_404_with_hint_when_nothing_publishes(self):
        resp = self.fetch("/tpujobs/api/slo")
        assert resp.code == 404
        assert "collector" in json.loads(resp.body)["error"]

    def test_reads_sidecar_configmap(self):
        payload = {"slos": [{"slo": "serving-deadline",
                             "state": "firing",
                             "objective": 0.99,
                             "windows": [{"window": "fast",
                                          "severity": "page",
                                          "state": "firing",
                                          "long_burn": 50.0,
                                          "short_burn": 60.0,
                                          "factor": 14.4,
                                          "fire_count": 1}]}],
                   "history": []}
        self.api.create({
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": ALERTS_CONFIGMAP,
                         "namespace": "default"},
            "data": {ALERTS_KEY: json.dumps(payload)}})
        doc = json.loads(self.fetch("/tpujobs/api/slo").body)
        assert doc["available"] and doc["source"] == "configmap"
        assert doc["slos"][0]["state"] == "firing"
        page = self.fetch("/tpujobs/ui/health").body.decode()
        assert "serving-deadline" in page


def test_artifacts_collect_obs_snapshots_collector(tmp_path,
                                                   monkeypatch):
    """collect-obs drops the collector state + alert history next to
    the junit XML (satellite: the CI observability trail grows the
    telemetry pipeline's state)."""
    from kubeflow_tpu.citests import artifacts

    monkeypatch.setenv("KFT_ARTIFACTS_DIR", str(tmp_path / "art"))
    monkeypatch.setenv("KFT_OBS_DIR", str(tmp_path / "obs"))
    replicas = _fleet(1)
    store, collector, alerts, _ = _pipeline(replicas, for_s=0.0)
    for t in range(12):
        for replica in replicas.values():
            replica.serve(10)
            replica.burst(20)
        collector.scrape_once(now=float(t))
    copied = artifacts.collect_obs()
    snaps = [p for p in copied if p.name.startswith("collector_state")]
    assert snaps, copied
    # Other tests' collectors may still be alive in the weak registry;
    # find OURS by its cycle count.
    docs = [json.loads(p.read_text()) for p in snaps]
    (doc,) = [d for d in docs if d["cycles"] == 12]
    assert doc["store"]["series"] > 0
    (evaluator,) = doc["alerts"]
    assert [h["to"] for h in evaluator["history"]] \
        == ["pending", "firing"]
