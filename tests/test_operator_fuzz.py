# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Reconciler property fuzz: random kubelet/chaos event sequences,
plus the r12 preemption fuzz — random priorities under chip scarcity
with the preemption safety invariants asserted every step (never
evict equal-or-higher priority, at most one victim per decision,
preempted jobs eventually reschedule or fail by deadline).

The C++ gang kernel is fuzzed under tsan/asan (native/stress_test.cc);
this is the same discipline one level up — the full reconcile loop
(service/pod creation, completion-skew grace, restart budget, status
conditions) against the fake apiserver under seeded random sequences
of pod phase flips, evictions, and resyncs. Each pass asserts the
operator's safety invariants; each episode ends with a liveness
wind-down proving the job still reaches a terminal phase from
whatever state the chaos left it in. The reference had nothing like
this — its operator was an external Go image tested only on a live
cluster (SURVEY §4).
"""

import datetime
import random

from kubeflow_tpu.operator import FakeApiServer, Reconciler
from kubeflow_tpu.operator.reconciler import (
    JOB_LABEL,
    PREEMPTED_CONDITION,
    SHRUNK_CONDITION,
    PreemptionPolicy,
    elastic_current_replicas,
    job_elastic_bounds,
    job_priority,
)

from tests.test_operator import make_job, submit

POD_PHASES = ("Pending", "Running", "Succeeded", "Failed")
TERMINAL = ("Succeeded", "Failed")


def _invariants(api, name, max_restarts, grace_passes, prev_status):
    job = api.get("TPUJob", "default", name)
    status = job.get("status", {})
    phase = status.get("phase", "Pending")
    restarts = int(status.get("restartCount", 0))

    # Restart budget is a hard ceiling and the counter is monotone.
    assert restarts <= max_restarts, (restarts, max_restarts)
    assert restarts >= int(prev_status.get("restartCount", 0))
    # The skew counter never exceeds its grace budget (at the budget
    # decide() rules a real slice fault instead of holding again).
    assert int(status.get("completionSkewPasses", 0)) <= grace_passes
    # Terminal phases are absorbing.
    prev_phase = prev_status.get("phase")
    if prev_phase in TERMINAL:
        assert phase == prev_phase, (prev_phase, phase)
    # Conditions stay k8s-conventional: exactly the current phase's
    # condition is True, every other materialized one is False.
    conds = {c["type"]: c["status"] for c in status.get("conditions", [])}
    if conds:
        assert conds.get(phase) == "True", (phase, conds)
        assert all(v == "False" for t, v in conds.items() if t != phase)
    return status


def _episode(seed: int) -> str:
    rng = random.Random(seed)
    workers = rng.randint(1, 4)
    coordinator = rng.random() < 0.3
    recovery = "restart-slice" if rng.random() < 0.8 else "none"
    max_restarts = rng.randint(0, 3)
    name = "fuzz"

    api = FakeApiServer()
    job = submit(api, make_job(name=name, workers=workers,
                               recovery=recovery, coordinator=coordinator))
    r = Reconciler(api, max_restarts=max_restarts)
    grace = r.completion_grace_passes
    status = {}

    for _ in range(rng.randint(20, 50)):
        roll = rng.random()
        pods = api.list("Pod", "default", {JOB_LABEL: name})
        if roll < 0.45 or not pods:
            r.reconcile(api.get("TPUJob", "default", name))
            status = _invariants(api, name, max_restarts, grace, status)
        elif roll < 0.85:
            victim = rng.choice(pods)["metadata"]["name"]
            api.set_pod_phase("default", victim,
                              rng.choice(POD_PHASES))
        else:
            victim = rng.choice(pods)["metadata"]["name"]
            api.delete("Pod", "default", victim)  # eviction/preemption

    # Liveness wind-down: chaos stops, every pod that exists finishes
    # cleanly — from ANY reachable state the job must go terminal in
    # a bounded number of resyncs (Restarting holds one pass per
    # deleted gang, skew holds up to `grace` passes, budget bounds
    # the restart loops).
    bound = 4 * (max_restarts + 1) + grace + 4
    for _ in range(bound):
        api.set_all_pod_phases("default", "Succeeded", {JOB_LABEL: name})
        phase = r.reconcile(api.get("TPUJob", "default", name))
        status = _invariants(api, name, max_restarts, grace, status)
        if phase in TERMINAL:
            break
    assert phase in TERMINAL, (seed, phase)

    # Terminal is quiescent: further resyncs change nothing.
    snapshot = (phase,
                sorted(p["metadata"]["name"] for p in
                       api.list("Pod", "default", {JOB_LABEL: name})))
    for _ in range(2):
        assert r.reconcile(api.get("TPUJob", "default", name)) == phase
    after = (phase,
             sorted(p["metadata"]["name"] for p in
                    api.list("Pod", "default", {JOB_LABEL: name})))
    assert after == snapshot
    return phase


def test_reconciler_fuzz_invariants_and_liveness():
    outcomes = {p: 0 for p in TERMINAL}
    for seed in range(60):
        outcomes[_episode(seed)] += 1
    # The chaos mix must actually reach both terminal phases across
    # seeds — otherwise the fuzz is exercising one corridor only.
    assert outcomes["Succeeded"] > 0, outcomes
    assert outcomes["Failed"] > 0, outcomes


# -- preemption fuzz (r12) ------------------------------------------------


def _preemption_job(name, priority, deadline, *, workers=1,
                    min_replicas=None):
    from kubeflow_tpu.manifests.tpujob import (
        replica_spec,
        termination_policy,
        tpu_job,
    )

    spec = replica_spec(
        "TPU_WORKER", workers, image="img:1",
        tpu_accelerator="tpu-v5-lite-podslice", tpu_topology="1x1",
        chips_per_worker=1)
    job = tpu_job(name, "default", [spec],
                  termination=termination_policy("TPU_WORKER", 0),
                  scheduling_deadline_seconds=deadline,
                  priority=priority,
                  min_replicas=min_replicas)
    job["metadata"]["uid"] = f"uid-{name}"
    return job


def _backdate_pending(api, name, seconds):
    past = (datetime.datetime.now(datetime.timezone.utc)
            - datetime.timedelta(seconds=seconds)).isoformat()

    def mutate(obj):
        for cond in obj.get("status", {}).get("conditions", []):
            if cond["type"] == "Pending":
                cond["lastTransitionTime"] = past

    with api.as_kubelet():
        api.patch("TPUJob", "default", name, mutate)


def _preempted_set(api, names):
    out = set()
    for name in names:
        with api.as_kubelet():
            job = api.get("TPUJob", "default", name)
        for cond in job.get("status", {}).get("conditions", []):
            if (cond.get("type") == PREEMPTED_CONDITION
                    and cond.get("status") == "True"):
                out.add(name)
    return out


def _scarce_kubelet(api, capacity):
    """Mark Pending pods Running only while ≤ ``capacity`` chips are
    in use — the chip-scarcity model (1 chip per fuzz gang)."""
    with api.as_kubelet():
        pods = api._list("Pod", "default", {JOB_LABEL: None})
        used = sum(1 for p in pods
                   if p.get("status", {}).get("phase") == "Running")
        for pod in pods:
            if used >= capacity:
                break
            if pod.get("status", {}).get("phase") in (None, "Pending"):
                api.set_pod_phase("default", pod["metadata"]["name"],
                                  "Running")
                used += 1


def _preemption_episode(seed: int) -> bool:
    """Returns whether any preemption happened this episode."""
    rng = random.Random(seed)
    api = FakeApiServer()
    capacity = rng.randint(1, 2)
    deadline = 50
    names = [f"pz{i}" for i in range(rng.randint(3, 5))]
    priorities = {n: rng.randint(0, 3) for n in names}
    r = Reconciler(api, preemption=PreemptionPolicy(
        min_interval_seconds=0.0,
        deadline_fraction=0.5))

    for name in names:
        with api.as_kubelet():
            api.create(_preemption_job(name, priorities[name],
                                       deadline))

    preempted_ever = set()
    for _ in range(rng.randint(25, 45)):
        roll = rng.random()
        target = rng.choice(names)
        if roll < 0.55:
            with api.as_kubelet():
                job = api.get("TPUJob", "default", target)
            if job.get("status", {}).get("phase") in TERMINAL:
                continue
            before = _preempted_set(api, names)
            r.reconcile(job)
            after = _preempted_set(api, names)
            fresh = after - before
            # Invariant: at most ONE victim per decision.
            assert len(fresh) <= 1, (seed, fresh)
            for victim in fresh:
                # Invariant: never evict equal-or-higher priority.
                assert priorities[victim] < priorities[target], (
                    seed, victim, priorities[victim], target,
                    priorities[target])
                preempted_ever.add(victim)
        elif roll < 0.8:
            # Time passes for a Pending job (may cross the
            # preemption-eligibility fraction or the deadline).
            _backdate_pending(api, target,
                              rng.choice((10, 30, 60)))
        else:
            _scarce_kubelet(api, capacity)

    # Wind-down: scarcity ends. Every preempted job must either
    # reschedule onto real chips or fail by its own deadline.
    for _ in range(30):
        _scarce_kubelet(api, capacity=10_000)
        for name in names:
            with api.as_kubelet():
                job = api.get("TPUJob", "default", name)
            if job.get("status", {}).get("phase") not in TERMINAL:
                r.reconcile(job)
    for name in sorted(preempted_ever):
        with api.as_kubelet():
            job = api.get("TPUJob", "default", name)
        phase = job.get("status", {}).get("phase")
        if phase == "Failed":
            # Fail-by-deadline is a legitimate end for a preempted
            # job on a still-contended pool — but only by DEADLINE.
            conds = {c["type"]: c["status"]
                     for c in job["status"].get("conditions", [])}
            assert conds.get("DeadlineExceeded") == "True", (
                seed, name, job["status"])
        else:
            # Otherwise it rescheduled: its gang is back and running.
            pods = api._list("Pod", "default", {JOB_LABEL: name})
            assert pods, (seed, name, phase)
            assert all(p.get("status", {}).get("phase") == "Running"
                       for p in pods), (seed, name, phase)
    # Sanity on the ledger: nothing was evicted by a priority-0 job
    # (only priority > 0 jobs may preempt at all).
    assert job_priority({"spec": {}}) == 0
    return bool(preempted_ever)


def test_preemption_fuzz_invariants():
    saw_preemption = 0
    for seed in range(14):
        saw_preemption += bool(_preemption_episode(seed))
    # The mix must actually exercise preemption across seeds,
    # otherwise the invariants above were vacuous.
    assert saw_preemption >= 3, saw_preemption


# -- elastic shrink-first fuzz (r16) ---------------------------------------


def _shrunk_set(api, names):
    out = set()
    for name in names:
        with api.as_kubelet():
            job = api.get("TPUJob", "default", name)
        for cond in job.get("status", {}).get("conditions", []):
            if (cond.get("type") == SHRUNK_CONDITION
                    and cond.get("status") == "True"):
                out.add(name)
    return out


def _elastic_episode(seed: int):
    """Random priorities × chip scarcity with ELASTIC victims in the
    mix. Invariants per decision: at most ONE action (shrink OR kill)
    fleet-wide; shrinks never touch equal-or-higher priority; a raw
    status.currentReplicas below minReplicas is never written; an
    elastic victim still above min is shrunk, never killed."""
    rng = random.Random(seed)
    api = FakeApiServer()
    names, priorities, elastic_bounds = [], {}, {}
    for i in range(rng.randint(3, 5)):
        name = f"ez{i}"
        names.append(name)
        priorities[name] = rng.randint(0, 3)
        workers = rng.randint(1, 3)
        if workers > 1 and rng.random() < 0.6:
            elastic_bounds[name] = (rng.randint(1, workers - 1),
                                    workers)
        with api.as_kubelet():
            api.create(_preemption_job(
                name, priorities[name], 50, workers=workers,
                min_replicas=elastic_bounds.get(name,
                                                (None,))[0]))
    # At least one rigid high-priority aggressor: an elastic Pending
    # aggressor SHRINKS ITSELF at the eligibility fraction before it
    # ever preempts anyone (admission shrink runs first), so an
    # all-elastic mix would exercise mostly self-shrinks.
    names.append("ez-hi")
    priorities["ez-hi"] = 4
    with api.as_kubelet():
        api.create(_preemption_job("ez-hi", 4, 50, workers=1))
    r = Reconciler(api, preemption=PreemptionPolicy(
        min_interval_seconds=0.0, deadline_fraction=0.5))
    capacity = rng.randint(2, 4)
    # Warm-up: give the pre-existing fleet a chance to actually hold
    # chips (victims must be Running to be candidates).
    for _ in range(3):
        for name in names:
            with api.as_kubelet():
                job = api.get("TPUJob", "default", name)
            r.reconcile(job)
        _scarce_kubelet(api, capacity)

    def check_bounds():
        for name, (lo, _) in elastic_bounds.items():
            with api.as_kubelet():
                job = api.get("TPUJob", "default", name)
            raw = job.get("status", {}).get("currentReplicas")
            if raw is not None:
                assert int(raw) >= lo, (seed, name, raw, lo)
            assert job_elastic_bounds(job) == elastic_bounds[name]

    acted = 0
    for _ in range(rng.randint(25, 45)):
        roll = rng.random()
        target = rng.choice(names)
        if roll < 0.6:
            with api.as_kubelet():
                job = api.get("TPUJob", "default", target)
            if job.get("status", {}).get("phase") in TERMINAL:
                continue
            pre_kill = _preempted_set(api, names)
            pre_shrunk = _shrunk_set(api, names)
            pre_sizes = {
                n: elastic_current_replicas(
                    api.get("TPUJob", "default", n))
                for n in elastic_bounds}
            r.reconcile(job)
            fresh_kill = _preempted_set(api, names) - pre_kill
            fresh_shrunk = _shrunk_set(api, names) - pre_shrunk
            # ≤ 1 action per decision, kill OR shrink.
            assert len(fresh_kill) + len(fresh_shrunk) <= 1, (
                seed, fresh_kill, fresh_shrunk)
            for victim in fresh_kill | fresh_shrunk:
                assert priorities[victim] < priorities[target], (
                    seed, victim, target)
                acted += 1
            for victim in fresh_kill:
                # Shrink-first: a killable elastic victim must have
                # been AT min already when the decision fired.
                if victim in elastic_bounds:
                    assert (pre_sizes[victim]
                            == elastic_bounds[victim][0]), (
                        seed, victim, pre_sizes[victim])
            check_bounds()
        elif roll < 0.8:
            # Time passes for EVERY Pending job (crossing the
            # shrink/preemption eligibility fraction or the
            # deadline) — per-target aging starves the aggressor.
            age = rng.choice((10, 30, 60))
            for name in names:
                _backdate_pending(api, name, age)
        else:
            _scarce_kubelet(api, capacity)

    # Wind-down: scarcity ends; every non-terminal job must settle
    # (resize rolls complete, gangs run) with bounds still honored.
    for _ in range(40):
        _scarce_kubelet(api, capacity=10_000)
        for name in names:
            with api.as_kubelet():
                job = api.get("TPUJob", "default", name)
            if job.get("status", {}).get("phase") not in TERMINAL:
                r.reconcile(job)
        check_bounds()
    for name in names:
        with api.as_kubelet():
            job = api.get("TPUJob", "default", name)
        phase = job.get("status", {}).get("phase")
        if phase == "Failed":
            conds = {c["type"]: c["status"]
                     for c in job["status"].get("conditions", [])}
            assert conds.get("DeadlineExceeded") == "True", (
                seed, name, job["status"])
        elif phase != "Succeeded":
            pods = api._list("Pod", "default", {JOB_LABEL: name})
            assert pods, (seed, name, phase)
            assert all(p.get("status", {}).get("phase") == "Running"
                       for p in pods), (seed, name, phase)
            bounds = elastic_bounds.get(name)
            if bounds is not None:
                assert bounds[0] <= len(pods) <= bounds[1], (
                    seed, name, len(pods), bounds)
    return acted


def test_elastic_preemption_fuzz_invariants():
    acted = 0
    for seed in range(12):
        acted += _elastic_episode(seed)
    # The mix must actually exercise shrink/kill decisions.
    assert acted >= 3, acted
