# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Reconciler property fuzz: random kubelet/chaos event sequences.

The C++ gang kernel is fuzzed under tsan/asan (native/stress_test.cc);
this is the same discipline one level up — the full reconcile loop
(service/pod creation, completion-skew grace, restart budget, status
conditions) against the fake apiserver under seeded random sequences
of pod phase flips, evictions, and resyncs. Each pass asserts the
operator's safety invariants; each episode ends with a liveness
wind-down proving the job still reaches a terminal phase from
whatever state the chaos left it in. The reference had nothing like
this — its operator was an external Go image tested only on a live
cluster (SURVEY §4).
"""

import random

from kubeflow_tpu.operator import FakeApiServer, Reconciler
from kubeflow_tpu.operator.reconciler import JOB_LABEL

from tests.test_operator import make_job, submit

POD_PHASES = ("Pending", "Running", "Succeeded", "Failed")
TERMINAL = ("Succeeded", "Failed")


def _invariants(api, name, max_restarts, grace_passes, prev_status):
    job = api.get("TPUJob", "default", name)
    status = job.get("status", {})
    phase = status.get("phase", "Pending")
    restarts = int(status.get("restartCount", 0))

    # Restart budget is a hard ceiling and the counter is monotone.
    assert restarts <= max_restarts, (restarts, max_restarts)
    assert restarts >= int(prev_status.get("restartCount", 0))
    # The skew counter never exceeds its grace budget (at the budget
    # decide() rules a real slice fault instead of holding again).
    assert int(status.get("completionSkewPasses", 0)) <= grace_passes
    # Terminal phases are absorbing.
    prev_phase = prev_status.get("phase")
    if prev_phase in TERMINAL:
        assert phase == prev_phase, (prev_phase, phase)
    # Conditions stay k8s-conventional: exactly the current phase's
    # condition is True, every other materialized one is False.
    conds = {c["type"]: c["status"] for c in status.get("conditions", [])}
    if conds:
        assert conds.get(phase) == "True", (phase, conds)
        assert all(v == "False" for t, v in conds.items() if t != phase)
    return status


def _episode(seed: int) -> str:
    rng = random.Random(seed)
    workers = rng.randint(1, 4)
    coordinator = rng.random() < 0.3
    recovery = "restart-slice" if rng.random() < 0.8 else "none"
    max_restarts = rng.randint(0, 3)
    name = "fuzz"

    api = FakeApiServer()
    job = submit(api, make_job(name=name, workers=workers,
                               recovery=recovery, coordinator=coordinator))
    r = Reconciler(api, max_restarts=max_restarts)
    grace = r.completion_grace_passes
    status = {}

    for _ in range(rng.randint(20, 50)):
        roll = rng.random()
        pods = api.list("Pod", "default", {JOB_LABEL: name})
        if roll < 0.45 or not pods:
            r.reconcile(api.get("TPUJob", "default", name))
            status = _invariants(api, name, max_restarts, grace, status)
        elif roll < 0.85:
            victim = rng.choice(pods)["metadata"]["name"]
            api.set_pod_phase("default", victim,
                              rng.choice(POD_PHASES))
        else:
            victim = rng.choice(pods)["metadata"]["name"]
            api.delete("Pod", "default", victim)  # eviction/preemption

    # Liveness wind-down: chaos stops, every pod that exists finishes
    # cleanly — from ANY reachable state the job must go terminal in
    # a bounded number of resyncs (Restarting holds one pass per
    # deleted gang, skew holds up to `grace` passes, budget bounds
    # the restart loops).
    bound = 4 * (max_restarts + 1) + grace + 4
    for _ in range(bound):
        api.set_all_pod_phases("default", "Succeeded", {JOB_LABEL: name})
        phase = r.reconcile(api.get("TPUJob", "default", name))
        status = _invariants(api, name, max_restarts, grace, status)
        if phase in TERMINAL:
            break
    assert phase in TERMINAL, (seed, phase)

    # Terminal is quiescent: further resyncs change nothing.
    snapshot = (phase,
                sorted(p["metadata"]["name"] for p in
                       api.list("Pod", "default", {JOB_LABEL: name})))
    for _ in range(2):
        assert r.reconcile(api.get("TPUJob", "default", name)) == phase
    after = (phase,
             sorted(p["metadata"]["name"] for p in
                    api.list("Pod", "default", {JOB_LABEL: name})))
    assert after == snapshot
    return phase


def test_reconciler_fuzz_invariants_and_liveness():
    outcomes = {p: 0 for p in TERMINAL}
    for seed in range(60):
        outcomes[_episode(seed)] += 1
    # The chaos mix must actually reach both terminal phases across
    # seeds — otherwise the fuzz is exercising one corridor only.
    assert outcomes["Succeeded"] > 0, outcomes
    assert outcomes["Failed"] > 0, outcomes
