# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""K8s builder tests: shape, pruning, list wrapping."""

from kubeflow_tpu.manifests import k8s


def test_prune_drops_none_only():
    # Empty containers are legitimate K8s values (emptyDir: {}, data: {})
    # and must survive; only None means "absent".
    assert k8s._prune({"a": None, "b": {}, "c": [], "d": 0, "e": False}) == {
        "b": {},
        "c": [],
        "d": 0,
        "e": False,
    }


def test_empty_dir_volume_survives_prune():
    spec = k8s.pod_spec([k8s.container("c", "i")],
                        volumes=[k8s.volume("scratch", empty_dir=True)])
    assert spec["volumes"][0] == {"name": "scratch", "emptyDir": {}}


def test_env_var_requires_value():
    import pytest

    with pytest.raises(ValueError, match="FOO"):
        k8s.env_var("FOO")
    assert k8s.env_var("FOO", "") == {"name": "FOO", "value": ""}


def test_deployment_shape():
    c = k8s.container("web", "img:1", ports=[k8s.port(80)])
    d = k8s.deployment("web", "ns", k8s.pod_spec([c]), replicas=3)
    assert d["kind"] == "Deployment"
    assert d["apiVersion"] == "apps/v1"
    assert d["spec"]["replicas"] == 3
    assert d["spec"]["selector"]["matchLabels"] == {"app": "web"}
    assert d["spec"]["template"]["metadata"]["labels"] == {"app": "web"}
    tpl = d["spec"]["template"]["spec"]
    assert tpl["containers"][0]["image"] == "img:1"
    assert "volumes" not in tpl


def test_service_with_annotations():
    s = k8s.service(
        "svc", "ns", {"app": "svc"},
        [k8s.service_port(9000, name="grpc"), k8s.service_port(8000, name="http")],
        annotations={"getambassador.io/config": "x"},
    )
    assert s["metadata"]["annotations"]["getambassador.io/config"] == "x"
    assert len(s["spec"]["ports"]) == 2
    assert "type" not in s["spec"]


def test_crd_v1_shape():
    c = k8s.crd("tpujobs.kubeflow.org", "kubeflow.org", "v1alpha1", "TPUJob",
                "tpujobs", short_names=["tpj"])
    assert c["apiVersion"] == "apiextensions.k8s.io/v1"
    v = c["spec"]["versions"][0]
    assert v["served"] and v["storage"]
    assert v["schema"]["openAPIV3Schema"]["type"] == "object"
    assert c["spec"]["names"]["shortNames"] == ["tpj"]


def test_ambassador_mapping_render():
    m = k8s.ambassador_mapping(
        "m-http", "/models/m/", "m.ns:8000", method="POST",
        rewrite="/model/m:predict",
    )
    assert "kind: Mapping" in m
    assert "prefix: /models/m/" in m
    assert "rewrite: /model/m:predict" in m
    assert m.rstrip().endswith("service: m.ns:8000")


def test_rbac_builders():
    cr = k8s.cluster_role("r", [k8s.policy_rule([""], ["pods"], ["get", "list"])])
    crb = k8s.cluster_role_binding("rb", "r", [k8s.subject("ServiceAccount", "sa", "ns")])
    assert cr["rules"][0]["resources"] == ["pods"]
    assert crb["roleRef"]["name"] == "r"
    assert crb["subjects"][0]["namespace"] == "ns"


def test_k8s_list():
    lst = k8s.k8s_list([k8s.namespace_obj("a"), None])
    assert lst["kind"] == "List"
    assert len(lst["items"]) == 1


def test_env_var_forms():
    assert k8s.env_var("A", 1) == {"name": "A", "value": "1"}
    assert k8s.env_var("B", field_path="metadata.name")["valueFrom"]["fieldRef"] == {
        "fieldPath": "metadata.name"
    }
    assert k8s.env_var("C", secret="s", secret_key="k")["valueFrom"]["secretKeyRef"] == {
        "name": "s", "key": "k"
    }
