# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""LLM generation serving: export → load → :generate, REST e2e.

Beyond-parity surface (the reference serves classify-style models
only): a generate-method signature bakes decode config at export
time, the server routes ``:generate``, and responses carry tokens.
"""

import json

import numpy as np
import pytest
import tornado.testing

import jax
import jax.numpy as jnp

from kubeflow_tpu.inference import generate as direct_generate
from kubeflow_tpu.models.llama import llama_test
from kubeflow_tpu.serving.export import export_model
from kubeflow_tpu.serving.manager import ModelManager
from kubeflow_tpu.serving.model import load_version
from kubeflow_tpu.serving.signature import (
    ModelMetadata,
    Signature,
    TensorSpec,
)

PROMPT_LEN = 8
NEW_TOKENS = 6
CACHE = 32


@pytest.fixture(scope="module")
def lm_dir(tmp_path_factory):
    base = tmp_path_factory.mktemp("models") / "tinyllama"
    model = llama_test(dtype=jnp.float32)
    ids = jnp.zeros((1, PROMPT_LEN), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids)
    metadata = ModelMetadata(
        model_name="tinyllama",
        registry_name="llama-test",
        model_kwargs={"dtype": "float32", "cache_size": CACHE},
        signatures={"serving_default": Signature(
            method="generate",
            inputs={"input_ids": TensorSpec("int32", (-1, PROMPT_LEN))},
            outputs={"tokens": TensorSpec("int32", (-1, NEW_TOKENS))},
        )},
        generate_config={"max_new_tokens": NEW_TOKENS,
                         "temperature": 0.0},
    )
    export_model(str(base), 1, metadata, {"params": variables["params"]})
    return base


def test_generate_load_and_run(lm_dir):
    loaded = load_version(str(lm_dir / "1"))
    prompt = np.asarray(
        jax.random.randint(jax.random.PRNGKey(3), (2, PROMPT_LEN), 0, 512))
    out = loaded.run({"input_ids": prompt})
    assert out["tokens"].shape == (2, NEW_TOKENS)
    assert out["tokens"].dtype == np.int32

    # Greedy serving output == direct library generation.
    model = llama_test(dtype=jnp.float32, cache_size=CACHE)
    tokens, _ = direct_generate(
        model, loaded.variables["params"], jnp.asarray(prompt),
        max_new_tokens=NEW_TOKENS, temperature=0.0)
    np.testing.assert_array_equal(out["tokens"], np.asarray(tokens))


def test_generate_rejects_predict_verb(lm_dir):
    loaded = load_version(str(lm_dir / "1"))
    prompt = np.zeros((1, PROMPT_LEN), np.int32)
    with pytest.raises(ValueError, match="incompatible"):
        loaded.run({"input_ids": prompt}, method="predict")


def test_generate_bucket_padding(lm_dir):
    # 3 rows → bucket 4; padded rows must not leak into outputs.
    loaded = load_version(str(lm_dir / "1"))
    prompt = np.asarray(
        jax.random.randint(jax.random.PRNGKey(5), (3, PROMPT_LEN), 0, 512))
    out3 = loaded.run({"input_ids": prompt})
    out1 = loaded.run({"input_ids": prompt[:1]})
    assert out3["tokens"].shape == (3, NEW_TOKENS)
    np.testing.assert_array_equal(out3["tokens"][0], out1["tokens"][0])


class GenerateEndToEnd(tornado.testing.AsyncHTTPTestCase):
    """:generate over a real socket through the model server."""

    @pytest.fixture(autouse=True)
    def _dir(self, lm_dir):
        type(self).base_path = lm_dir

    def get_app(self):
        from kubeflow_tpu.serving.server import make_app

        manager = ModelManager()
        self.manager = manager
        manager.add_model("tinyllama", str(type(self).base_path),
                          max_batch=8)
        return make_app(manager)

    def test_generate_roundtrip(self):
        prompt = [[7] * PROMPT_LEN, [11] * PROMPT_LEN]
        resp = self.fetch(
            "/v1/models/tinyllama:generate", method="POST",
            body=json.dumps({"instances": prompt}))
        assert resp.code == 200, resp.body
        payload = json.loads(resp.body)
        preds = payload["predictions"]
        assert len(preds) == 2
        assert len(preds[0]["tokens"]) == NEW_TOKENS
        # Identical prompts in one batch would collide; distinct rows
        # must produce per-row continuations deterministically.
        resp2 = self.fetch(
            "/v1/models/tinyllama:generate", method="POST",
            body=json.dumps({"instances": prompt}))
        assert json.loads(resp2.body)["predictions"] == preds

    def test_wrong_verb_is_400(self):
        resp = self.fetch(
            "/v1/models/tinyllama:predict", method="POST",
            body=json.dumps({"instances": [[1] * PROMPT_LEN]}))
        assert resp.code == 400

    def tearDown(self):
        self.manager.stop()
        super().tearDown()


def test_short_prompts_ride_length_buckets(lm_dir):
    """Generate signatures treat the exported prompt length as a MAX:
    shorter prompts left-pad to a power-of-two length bucket and
    return exactly the unpadded B=1 result (greedy export)."""
    loaded = load_version(str(lm_dir / "1"))
    model = llama_test(dtype=jnp.float32, cache_size=CACHE)
    for length in (3, 5, PROMPT_LEN):
        prompt = np.asarray(jax.random.randint(
            jax.random.PRNGKey(length), (1, length), 0, 512))
        out = loaded.run({"input_ids": prompt})
        want, _ = direct_generate(
            model, loaded.variables["params"], jnp.asarray(prompt),
            max_new_tokens=NEW_TOKENS, temperature=0.0)
        np.testing.assert_array_equal(out["tokens"], np.asarray(want),
                                      f"length {length}")
    # Longer than the signature max stays a hard error.
    with pytest.raises(ValueError, match="signature"):
        loaded.run({"input_ids": np.zeros((1, PROMPT_LEN + 1),
                                          np.int32)})


def test_explicit_prompt_buckets_respected(lm_dir):
    """generate_config.prompt_buckets overrides the power-of-two
    lengths; outputs stay identical to the unpadded run."""
    import dataclasses

    loaded = load_version(str(lm_dir / "1"))
    md = dataclasses.replace(
        loaded.metadata,
        generate_config={"max_new_tokens": NEW_TOKENS,
                         "temperature": 0.0,
                         "prompt_buckets": [6, PROMPT_LEN]})
    bucketed = dataclasses.replace(loaded, metadata=md)
    assert bucketed._length_bucket(3, PROMPT_LEN) == 6
    assert bucketed._length_bucket(7, PROMPT_LEN) == PROMPT_LEN
    prompt = np.asarray(jax.random.randint(
        jax.random.PRNGKey(44), (2, 5), 0, 512))
    out = bucketed.run({"input_ids": prompt})
    model = llama_test(dtype=jnp.float32, cache_size=CACHE)
    want, _ = direct_generate(
        model, loaded.variables["params"], jnp.asarray(prompt),
        max_new_tokens=NEW_TOKENS, temperature=0.0)
    np.testing.assert_array_equal(out["tokens"], np.asarray(want))


def test_sampling_fresh_per_request_unless_pinned(lm_dir, tmp_path):
    """Default sampling varies across requests (rng folds a request
    counter); `deterministic: true` pins it for golden replay."""
    import dataclasses

    loaded = load_version(str(lm_dir / "1"))
    md = loaded.metadata
    prompt = np.asarray(
        jax.random.randint(jax.random.PRNGKey(9), (1, PROMPT_LEN), 0, 512))

    sampled_md = dataclasses.replace(
        md, generate_config={"max_new_tokens": NEW_TOKENS,
                             "temperature": 1.2})
    sampled = dataclasses.replace(loaded, metadata=sampled_md)
    a = sampled.run({"input_ids": prompt})["tokens"]
    b = sampled.run({"input_ids": prompt})["tokens"]
    assert not np.array_equal(a, b), "sampling must vary per request"

    pinned_md = dataclasses.replace(
        md, generate_config={"max_new_tokens": NEW_TOKENS,
                             "temperature": 1.2, "deterministic": True})
    pinned = dataclasses.replace(loaded, metadata=pinned_md)
    c = pinned.run({"input_ids": prompt})["tokens"]
    d = pinned.run({"input_ids": prompt})["tokens"]
    np.testing.assert_array_equal(c, d)


class GenerateProxyEndToEnd(tornado.testing.AsyncHTTPTestCase):
    """:generate through the REST proxy in front of the server."""

    @pytest.fixture(autouse=True)
    def _dir(self, lm_dir):
        type(self).base_path = lm_dir

    def get_app(self):
        import tornado.httpserver

        from kubeflow_tpu.serving.http_proxy import make_app as proxy_app
        from kubeflow_tpu.serving.server import make_app as server_app

        self.manager = ModelManager()
        self.manager.add_model("tinyllama", str(type(self).base_path),
                               max_batch=8)
        backend = server_app(self.manager)
        sock, port = tornado.testing.bind_unused_port()
        self.backend_server = tornado.httpserver.HTTPServer(backend)
        self.backend_server.add_sockets([sock])
        return proxy_app(f"http://127.0.0.1:{port}")

    def test_proxy_generate(self):
        resp = self.fetch(
            "/model/tinyllama:generate", method="POST",
            body=json.dumps({"instances": [[3] * PROMPT_LEN]}))
        assert resp.code == 200, resp.body
        preds = json.loads(resp.body)["predictions"]
        assert len(preds) == 1 and len(preds[0]["tokens"]) == NEW_TOKENS

    def tearDown(self):
        self.manager.stop()
        super().tearDown()


def test_native_grpc_predict_runs_generate_signature(lm_dir):
    """TF-Serving semantics: gRPC Predict executes the named
    signature whatever its method — a generate-method export serves
    tokens over the native gRPC surface."""
    grpc = pytest.importorskip("grpc")
    from kubeflow_tpu.serving import wire
    from kubeflow_tpu.serving.grpc_server import make_server

    manager = ModelManager()
    manager.add_model("tinyllama", str(lm_dir), max_batch=4)
    server, port = make_server(manager, 0)
    server.start()
    try:
        prompt = np.asarray(jax.random.randint(
            jax.random.PRNGKey(3), (1, PROMPT_LEN), 0, 512), np.int32)
        request = wire.encode_predict_request(
            "tinyllama", {"input_ids": prompt})
        with grpc.insecure_channel(f"127.0.0.1:{port}") as channel:
            reply = channel.unary_unary(
                "/tensorflow.serving.PredictionService/Predict"
            )(request, timeout=60.0)
        _, outputs = wire.decode_predict_response(reply)
        assert outputs["tokens"].shape == (1, NEW_TOKENS)
        # Same tokens as a direct model run (greedy export).
        direct = manager.get_model("tinyllama").get().run(
            {"input_ids": prompt})
        np.testing.assert_array_equal(outputs["tokens"],
                                      direct["tokens"])
    finally:
        server.stop(grace=None)
        manager.stop()
