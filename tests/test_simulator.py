# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""kubeflow_tpu/scaling/simulator.py: the deterministic fleet sim.

Hermetic and instant: every test here is pure event-time — no
sockets, no sleeps, no wall clock (scripts/lint.py check_sim_purity
enforces the same statically). The determinism test IS the contract:
two same-seed runs must produce byte-identical event logs, or sim
results stop being reproducible evidence.

The autoscaler-in-the-loop tests drive the PRODUCTION
:class:`~kubeflow_tpu.scaling.autoscaler.Autoscaler` (injected clock,
SimScaler actuation) — the sim validates deployed policy code, not a
reimplementation. The sim-vs-MEASURED validation (p99 within 10% of
three recorded workloads) is the fleet-sim CI gate:
``bench.py --sim`` (manifests/ci.py, PERF.md).
"""

import json
import random

import pytest

from kubeflow_tpu.obs import trace as obs_trace
from kubeflow_tpu.scaling.autoscaler import Autoscaler, AutoscalerConfig
from kubeflow_tpu.scaling.simulator import (
    FleetSimulator,
    ServiceModel,
    SimRequest,
    SimScaler,
    Workload,
    percentile,
)


# -- determinism (the contract) ---------------------------------------

def _bursty_sim(seed):
    rng = random.Random(99)  # workload fixed; only the SIM seed varies
    workload = Workload.bursty(5.0, 40.0, 20.0, 40.0, 60.0, rng,
                               ramp_s=10.0)
    service = ServiceModel([0.03, 0.05, 0.08, 0.12])
    return FleetSimulator(workload, service, replicas=2, seed=seed)


def test_same_seed_runs_produce_identical_event_logs():
    a, b = _bursty_sim(7).run(), _bursty_sim(7).run()
    assert a.event_log == b.event_log
    assert a.latencies_s == b.latencies_s
    assert a.completed == b.completed > 0


def test_different_seed_changes_service_draws_only():
    a, b = _bursty_sim(7).run(), _bursty_sim(8).run()
    assert a.completed == b.completed  # same arrivals either way
    assert a.event_log != b.event_log  # different service draws


def test_rerunning_the_same_instance_is_deterministic():
    sim = _bursty_sim(7)
    assert sim.run().event_log == sim.run().event_log


# -- closed loop: exact queueing math ---------------------------------

def test_closed_loop_constant_service_is_exact():
    # 6 clients over 2 single-slot replicas at a constant 40ms: each
    # replica carries 3 clients, steady-state sojourn = 3 x 40ms.
    sim = FleetSimulator(Workload.closed(6, 2.0),
                         ServiceModel.constant(0.04), replicas=2)
    res = sim.run()
    assert res.p50_ms == pytest.approx(120.0)
    assert res.p99_ms == pytest.approx(120.0)
    # Both replicas saturated for the whole window: throughput =
    # 2 replicas / 40ms = 50 rps over 2s.
    assert res.completed == pytest.approx(100, abs=4)


def test_doubling_replicas_halves_closed_loop_latency():
    def p50(n):
        return FleetSimulator(Workload.closed(8, 2.0),
                              ServiceModel.constant(0.05),
                              replicas=n).run().p50_ms
    assert p50(2) == pytest.approx(2 * p50(4))


# -- service model calibration ----------------------------------------

def test_scaled_to_mean_preserves_shape():
    base = ServiceModel([0.1, 0.2, 0.3])
    scaled = base.scaled_to_mean(0.4)
    assert scaled.mean == pytest.approx(0.4)
    rng = random.Random(0)
    draws = sorted({scaled.sample(rng) for _ in range(64)})
    assert draws == pytest.approx([0.2, 0.4, 0.6])


def test_from_attribution_sums_prefill_and_decode():
    model = ServiceModel.from_attribution(
        [(5.0, 30.0, 50.0), (2.0, 10.0, 20.0)])  # queue excluded
    assert model.mean == pytest.approx((0.08 + 0.03) / 2)


def test_from_histogram_midpoints():
    model = ServiceModel.from_histogram(
        {0.1: 4.0, 0.2: 8.0, float("inf"): 8.0})
    assert 0.05 <= model.mean <= 0.2
    with pytest.raises(ValueError):
        ServiceModel.from_histogram({float("inf"): 3.0})


def test_service_model_rejects_empty():
    with pytest.raises(ValueError):
        ServiceModel([0.0, -1.0])


# -- prefix-hit service class (ROADMAP #7a / ISSUE 20) -----------------

def test_prefix_hit_model_blends_mean_and_splits_draws():
    """Per-request Bernoulli(hit_rate) branch selection: the blended
    ``mean`` is what the saturation math reads, but the DRAWS stay
    bimodal — every sample comes from exactly one branch, never an
    average of the two."""
    from kubeflow_tpu.scaling.simulator import PrefixHitServiceModel

    hit = ServiceModel([0.01, 0.02])
    miss = ServiceModel([0.10, 0.20])
    m = PrefixHitServiceModel(hit, miss, 0.75)
    assert m.mean == pytest.approx(0.75 * 0.015 + 0.25 * 0.15)
    rng = random.Random(3)
    draws = [m.sample(rng) for _ in range(400)]
    assert set(draws) <= {0.01, 0.02, 0.10, 0.20}
    hit_frac = sum(1 for d in draws if d < 0.05) / len(draws)
    assert 0.65 <= hit_frac <= 0.85
    with pytest.raises(ValueError):
        PrefixHitServiceModel(hit, miss, 1.5)
    # Degenerate rates collapse to a single branch.
    always_miss = PrefixHitServiceModel(hit, miss, 0.0)
    assert {always_miss.sample(rng) for _ in range(32)} <= {0.10, 0.20}


def test_prefix_hit_model_from_tier_stats():
    """Calibration straight off the tier-stats dump the kv-tier bench
    writes (collect-obs ships it as kv_tier_stats.json): hit_rate
    from the prefix counters, hit-path mean = miss mean with the
    prefill share removed plus the fleet-fetch penalty weighted by
    remote share."""
    from kubeflow_tpu.scaling.simulator import PrefixHitServiceModel

    miss = ServiceModel([0.08, 0.10, 0.12])
    stats = {"prefix_cache": {"hits": 60, "misses": 40},
             "kv_tier": {"fetch_hits": 30}}
    m = PrefixHitServiceModel.from_tier_stats(
        miss, stats, prefill_share=0.5, fetch_penalty_s=0.01)
    assert m.hit_rate == pytest.approx(0.6)
    # remote_share = 30/60: half the hits paid the fetch penalty.
    assert m.hit.mean == pytest.approx(0.1 * 0.5 + 0.5 * 0.01)
    assert m.hit.mean < m.miss.mean
    # No lookups at all → a cold fleet: everything is a miss.
    cold = PrefixHitServiceModel.from_tier_stats(miss, {})
    assert cold.hit_rate == 0.0
    with pytest.raises(ValueError):
        PrefixHitServiceModel.from_tier_stats(miss, stats,
                                              prefill_share=1.0)


def test_prefix_hit_model_rescale_preserves_bimodality():
    """scaled_to_mean moves BOTH branches by one factor: the blend
    lands on the target while hit/miss separation (what the queueing
    percentiles are sensitive to) and the hit rate survive."""
    from kubeflow_tpu.scaling.simulator import PrefixHitServiceModel

    m = PrefixHitServiceModel(ServiceModel([0.02]),
                              ServiceModel([0.10]), 0.5)
    scaled = m.scaled_to_mean(0.12)
    assert scaled.mean == pytest.approx(0.12)
    assert scaled.hit_rate == 0.5
    assert scaled.hit.mean / scaled.miss.mean == \
        pytest.approx(m.hit.mean / m.miss.mean)
    assert scaled.miss.mean > scaled.hit.mean


def test_prefix_hit_model_drives_fleet_sim_deterministically():
    """The conditioned class plugs into FleetSimulator through the
    ordinary ServiceModel seam; same seed → byte-identical event
    logs, and the conditioned tail beats a flat model with the SAME
    mean (the bimodality is load-bearing, not cosmetic)."""
    from kubeflow_tpu.scaling.simulator import PrefixHitServiceModel

    def build(service):
        rng = random.Random(11)
        return FleetSimulator(Workload.open_loop(18.0, 30.0, rng),
                              service, replicas=2, seed=5)

    def conditioned():
        return PrefixHitServiceModel(
            ServiceModel([0.02, 0.03]),
            ServiceModel([0.14, 0.18, 0.22]), 0.7)

    a = build(conditioned()).run()
    b = build(conditioned()).run()
    assert a.event_log == b.event_log
    flat = build(ServiceModel([conditioned().mean])).run()
    assert a.completed > 0 and flat.completed > 0
    assert a.p99_ms > flat.p99_ms


def test_percentile_matches_bench_convention():
    xs = list(range(1, 101))
    # benchmark._pct: index int(q*n) clamped — p50 of 1..100 is 51.
    assert percentile(xs, 50) == 51
    assert percentile(xs, 99) == 100
    assert percentile([], 99) == 0.0


# -- workload shapes ---------------------------------------------------

def test_open_loop_poisson_rate():
    rng = random.Random(3)
    w = Workload.open_loop(50.0, 20.0, rng)
    assert len(w.requests) == pytest.approx(1000, rel=0.15)
    assert all(0 < r.arrival_s < 20.0 for r in w.requests)


def test_bursty_ramp_raises_rate_between_base_and_spike():
    rng = random.Random(3)
    w = Workload.bursty(5.0, 50.0, 30.0, 50.0, 60.0, rng, ramp_s=10.0)

    def count(lo, hi):
        return sum(lo <= r.arrival_s < hi for r in w.requests)

    base, ramp, spike = count(0, 20), count(20, 30), count(50, 60)
    assert base / 20.0 < ramp / 10.0 < count(30, 50) / 20.0
    assert spike / 10.0 < count(30, 50) / 20.0  # spike window ended


# -- trace replay: export_workload round trip -------------------------

def _request_spans(trace_id, ts_us, queue_us, exec_us, model):
    """One direct-to-server traced request: http_request root with a
    queue_wait + execute child — the assembled-trace shape
    kft-trace --export-workload consumes."""
    root_id = f"{trace_id[:15]}a"
    common = {"cat": "t", "ph": "X", "pid": 1, "tid": 1}
    return [
        dict(common, name="http_request", ts=ts_us,
             dur=queue_us + exec_us,
             args={"trace_id": trace_id, "span_id": root_id,
                   "model": model}),
        dict(common, name="queue_wait", ts=ts_us, dur=queue_us,
             args={"trace_id": trace_id, "parent_id": root_id}),
        dict(common, name="execute", ts=ts_us + queue_us, dur=exec_us,
             args={"trace_id": trace_id, "parent_id": root_id}),
    ]


def test_export_workload_rows_and_sim_replay():
    spans = (
        _request_spans("a" * 32, 1_000_000.0, 5_000.0, 30_000.0, "m1")
        + _request_spans("b" * 32, 3_000_000.0, 0.0, 50_000.0, "m2"))
    doc = obs_trace.export_workload(spans)
    assert doc["version"] == 1
    rows = doc["requests"]
    assert [r["trace_id"] for r in rows] == ["a" * 32, "b" * 32]
    # t=0 is the first arrival; the second request landed 2s later.
    assert rows[0]["arrival_s"] == 0.0
    assert rows[1]["arrival_s"] == pytest.approx(2.0)
    assert rows[0]["model"] == "m1"
    assert rows[0]["queue_ms"] == pytest.approx(5.0)
    assert rows[0]["decode_ms"] == pytest.approx(30.0)

    # Replay: service times are the EXACT recorded attribution (queue
    # time is the sim's to produce), so an uncontended replay returns
    # each request's service component as its latency.
    workload = Workload.from_export(doc)
    assert [r.service_s for r in workload.requests] == \
        pytest.approx([0.030, 0.050])
    res = FleetSimulator(workload, ServiceModel.constant(1.0),
                         replicas=1).run()
    assert res.completed == 2
    assert sorted(res.latencies_s) == pytest.approx([0.030, 0.050])


def test_export_workload_skips_rootless_traces():
    orphan = {"name": "queue_wait", "cat": "t", "ph": "X", "ts": 0.0,
              "dur": 100.0, "args": {"trace_id": "c" * 32}}
    doc = obs_trace.export_workload([orphan])
    assert doc["requests"] == []


def test_spans_from_file_accepts_all_three_dump_forms(tmp_path):
    # A JSONL dump's first line starts with "{" just like a /tracez
    # document — the loader must fall through to line-by-line instead
    # of dying on "Extra data".
    spans = _request_spans("a" * 32, 1_000_000.0, 5_000.0, 30_000.0,
                           "m1")
    jsonl = tmp_path / "spans.jsonl"
    jsonl.write_text("\n".join(json.dumps(s) for s in spans))
    doc = tmp_path / "tracez.json"
    doc.write_text(json.dumps({"spans": spans}))
    arr = tmp_path / "spans_array.json"
    arr.write_text(json.dumps(spans))
    for path in (jsonl, doc, arr):
        loaded = obs_trace._spans_from_file(str(path))
        assert len(loaded) == len(spans), path


# -- autoscaler in the loop -------------------------------------------

def _predictive_cfg(**overrides):
    defaults = dict(min_replicas=1, max_replicas=6,
                    target_queue_wait_ms=300.0, hysteresis=0.2,
                    scale_up_cooldown_s=10.0,
                    scale_down_cooldown_s=40.0, predictive=True,
                    forecast_horizon_s=40.0, forecast_window_s=20.0,
                    replica_capacity_rps=20.0)
    defaults.update(overrides)
    return AutoscalerConfig(**defaults)


def test_sim_requires_sim_scaler():
    class NotASimScaler:
        def get_replicas(self):
            return 1

        def set_replicas(self, n):
            pass

    asc = Autoscaler(_predictive_cfg(), NotASimScaler(),
                     clock=lambda: 0.0)
    sim = FleetSimulator(Workload.closed(2, 1.0),
                         ServiceModel.constant(0.01), autoscaler=asc)
    with pytest.raises(TypeError):
        sim.run()


def test_autoscaler_in_loop_scales_up_on_a_spike():
    rng = random.Random(11)
    workload = Workload.bursty(4.0, 60.0, 60.0, 100.0, 130.0, rng,
                               ramp_s=40.0)
    asc = Autoscaler(_predictive_cfg(), SimScaler(1),
                     clock=lambda: 0.0)
    sim = FleetSimulator(workload, ServiceModel.constant(0.05),
                         replicas=1, seed=11, slo_s=0.5,
                         autoscaler=asc, provision_delay_s=10.0)
    res = sim.run()
    assert res.max_replicas > 1
    assert res.max_replicas <= 6  # the budget clamp held in-loop
    ups = [d for d in res.decisions if d["action"] == "scale_up"]
    assert ups, res.decisions
    # Every decision record carries its inputs, forecast included.
    assert all("forecast" in d["inputs"] for d in res.decisions)
    assert any(d["reason"] == "forecast" for d in ups)


def test_predictive_beats_reactive_on_the_ramped_spike():
    # The acceptance scenario (bench.py --sim phase 2), small: the
    # forecast extrapolates the ramp and pre-scales a provision-delay
    # ahead; the reactive law waits for queues it can already see.
    def run(predictive):
        rng = random.Random(11)
        workload = Workload.bursty(4.0, 60.0, 60.0, 100.0, 130.0, rng,
                                   ramp_s=40.0)
        cfg = (_predictive_cfg() if predictive else
               _predictive_cfg(predictive=False, scale_to_zero=False))
        asc = Autoscaler(cfg, SimScaler(1), clock=lambda: 0.0)
        return FleetSimulator(workload, ServiceModel.constant(0.05),
                              replicas=1, seed=11, slo_s=0.5,
                              autoscaler=asc,
                              provision_delay_s=10.0).run()

    reactive, predictive = run(False), run(True)
    assert predictive.time_over_slo_s < reactive.time_over_slo_s
    assert predictive.max_replicas <= 6


def test_wake_from_zero_serves_the_lobby():
    # A scaled-to-zero fleet: arrivals wait at the door, the forecast
    # wakes capacity, the lobby drains after the provision delay.
    requests = [SimRequest(arrival_s=t) for t in (1.0, 1.5, 2.0)]
    workload = Workload(requests=requests, duration_s=30.0)
    cfg = _predictive_cfg(min_replicas=0, scale_to_zero=True,
                          idle_quiet_s=300.0)
    asc = Autoscaler(cfg, SimScaler(0), clock=lambda: 0.0)
    sim = FleetSimulator(workload, ServiceModel.constant(0.02),
                         replicas=0, seed=1, autoscaler=asc,
                         autoscaler_interval_s=2.0,
                         provision_delay_s=5.0)
    res = sim.run()
    assert res.completed == 3
    kinds = [kind for _, kind, _ in res.event_log]
    assert "lobby" in kinds and "unlobby" in kinds
    assert any(d["reason"] == "wake_from_zero" for d in res.decisions)
    # Lobby wait = wake tick + provision delay, so latencies include
    # the cold start the autoscaler's lead time has to beat.
    assert min(res.latencies_s) > 5.0


def test_scale_to_zero_collapses_an_idle_fleet():
    workload = Workload(requests=[SimRequest(arrival_s=0.5)],
                        duration_s=120.0)
    cfg = _predictive_cfg(min_replicas=0, scale_to_zero=True,
                          idle_quiet_s=20.0, scale_down_cooldown_s=10.0)
    asc = Autoscaler(cfg, SimScaler(1), clock=lambda: 0.0)
    sim = FleetSimulator(workload, ServiceModel.constant(0.02),
                         replicas=1, seed=1, autoscaler=asc)
    res = sim.run()
    assert res.completed == 1
    assert any(d["reason"] == "scale_to_zero" for d in res.decisions)
    assert not sim._live()  # the fleet really collapsed
