# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Fused BN-forward pallas kernel: correctness vs the XLA schedule
(the PERF.md experiment's test tier; runs in interpret mode on CPU)."""

import numpy as np
import pytest

import jax.numpy as jnp

from kubeflow_tpu.ops.bn_pallas import (
    fused_bn_train_forward,
    reference_bn_train_forward,
)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fused_bn_matches_reference(dtype):
    x = jnp.asarray(
        np.random.RandomState(0).randn(1024, 128) * 2 + 0.5, dtype)
    scale = jnp.asarray(np.random.RandomState(1).rand(128), jnp.float32)
    bias = jnp.asarray(np.random.RandomState(2).randn(128), jnp.float32)
    y_p, mean_p, var_p = fused_bn_train_forward(x, scale, bias,
                                                block_m=256,
                                                interpret=True)
    y_r, mean_r, var_r = reference_bn_train_forward(x, scale, bias)
    tol = 1e-4 if dtype == jnp.float32 else 5e-2
    np.testing.assert_allclose(np.asarray(mean_p), np.asarray(mean_r),
                               atol=tol)
    np.testing.assert_allclose(np.asarray(var_p), np.asarray(var_r),
                               atol=tol)
    np.testing.assert_allclose(np.asarray(y_p, np.float32),
                               np.asarray(y_r, np.float32),
                               atol=10 * tol)


def test_fused_bn_validates_shapes():
    x = jnp.zeros((100, 128), jnp.float32)
    s = jnp.ones((128,), jnp.float32)
    with pytest.raises(ValueError, match="block_m"):
        fused_bn_train_forward(x, s, s, block_m=512, interpret=True)
    with pytest.raises(ValueError, match="multiple of 128"):
        fused_bn_train_forward(jnp.zeros((512, 100), jnp.float32),
                               jnp.ones((100,), jnp.float32),
                               jnp.ones((100,), jnp.float32),
                               block_m=256, interpret=True)
