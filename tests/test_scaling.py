# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""kubeflow_tpu/scaling/: registry, balancer policies, autoscaler.

Everything here is hermetic and clock-injected: the prober tests use
an injected fetch (no sockets), the autoscaler hysteresis/cooldown
tests run a scripted metrics trace against a simulated clock (no
sleeping), and actuation goes through FakeApiServer's scale
subresource (plus the HTTP facade once, to cover the wire shape).
The live-socket fleet e2e lives in tests/test_serving_stress.py.
"""

import json
import threading

import pytest

from kubeflow_tpu.operator.fake import FakeApiServer
from kubeflow_tpu.scaling.autoscaler import (
    FLEET_CONFIGMAP,
    FLEET_KEY,
    Autoscaler,
    AutoscalerConfig,
    AutoscalerLoop,
    DeploymentScaler,
    Scaler,
    discover_pod_endpoints,
)
from kubeflow_tpu.scaling.balancer import (
    LeastSaturationBalancer,
    ResidentAffinityBalancer,
    RoundRobinBalancer,
    eligible_endpoints,
    make_balancer,
)
from kubeflow_tpu.scaling.endpoints import (
    DRAINING,
    HEALTHY,
    UNHEALTHY,
    UNKNOWN,
    Endpoint,
    EndpointPool,
    FileEndpointSource,
    HealthProber,
    StaticEndpointSource,
    write_endpoints_file,
)


def _healthz(saturation=None, status="ok"):
    return {"status": status, "breakers": {},
            "saturation": saturation or {}}


def _stats(queue_depth=0.0, latency_ms=10.0, shed=0, expired=0):
    return {"queue_depth": queue_depth,
            "est_batch_latency_ms": latency_ms,
            "shed": shed, "expired": expired}


# ---------------------------------------------------------------------------
# Endpoint / EndpointPool


def test_endpoint_starts_unknown_and_routable():
    ep = Endpoint("a:1")
    assert ep.health == UNKNOWN
    # A fresh member takes traffic before its first probe lands.
    assert ep.routable()


def test_saturation_score_prices_queue_and_inflight():
    ep = Endpoint("a:1")
    ep.saturation = {"m1": _stats(queue_depth=3, latency_ms=20.0),
                     "m2": _stats(queue_depth=1, latency_ms=40.0)}
    # 3*20 + 1*40 = 100 queue wait; inflight priced at the max batch
    # latency (one accelerator serializes all models).
    assert ep.saturation_score() == pytest.approx(100.0)
    ep.inflight = 2
    assert ep.saturation_score() == pytest.approx(100.0 + 2 * 40.0)


def test_probe_success_readmits_and_closes_rest_breaker():
    ep = Endpoint("a:1", breaker_failures=1, breaker_reset_s=60.0)
    for _ in range(3):
        ep.mark_probe_failure(eject_after=3)
    assert ep.health == UNHEALTHY and not ep.routable()
    ep.rest_breaker.record_failure()
    assert ep.rest_breaker.state == "open"
    readmitted = ep.mark_probe_success(
        _healthz({"m": _stats(queue_depth=2)}))
    assert readmitted and ep.health == HEALTHY
    assert ep.resident_models() == ["m"]
    # The probe IS a successful REST round trip: a revived replica
    # must not wait out a stale open circuit to rejoin rotation.
    assert ep.rest_breaker.state == "closed"


def test_probe_success_leaves_closed_breaker_evidence_alone():
    """A replica whose /healthz answers while its INFER path hangs
    must still trip its breaker: probes heal open circuits but never
    reset a closed breaker's consecutive-failure count."""
    ep = Endpoint("a:1", breaker_failures=2, breaker_reset_s=60.0)
    ep.rest_breaker.record_failure()  # one infer transport failure
    ep.mark_probe_success(_healthz())  # healthz still 200
    ep.rest_breaker.record_failure()  # second consecutive failure
    assert ep.rest_breaker.state == "open"  # probe didn't erase #1


def test_dropped_endpoint_unregisters_metric_children():
    from kubeflow_tpu.scaling.endpoints import _G_ENDPOINT_HEALTH

    pool = EndpointPool.from_addresses(["leak-test:1"])
    assert ("leak-test:1",) in _G_ENDPOINT_HEALTH._children
    pool.remove("leak-test:1")
    # Pod-IP churn must not pin dead Endpoints (the gauge callback
    # closes over the object) nor grow /metrics forever.
    assert ("leak-test:1",) not in _G_ENDPOINT_HEALTH._children


def test_probe_failure_ejects_only_after_threshold():
    ep = Endpoint("a:1")
    assert not ep.mark_probe_failure(eject_after=3)
    assert not ep.mark_probe_failure(eject_after=3)
    assert ep.routable()  # two strikes: still in rotation
    assert ep.mark_probe_failure(eject_after=3)  # the ejecting one
    assert ep.health == UNHEALTHY
    # Further failures don't re-report the transition.
    assert not ep.mark_probe_failure(eject_after=3)


def test_pool_remove_is_drain_aware():
    pool = EndpointPool.from_addresses(["a:1", "b:1"])
    busy = pool.get("a:1")
    busy.inflight = 1
    pool.remove("a:1")
    assert busy.health == DRAINING and not busy.routable()
    assert pool.get("a:1") is not None  # kept until drained
    pool.remove("b:1")  # idle: drops immediately
    assert pool.get("b:1") is None
    # Drain finishes → the next sync drops the member.
    busy.inflight = 0
    pool.sync([])
    assert pool.get("a:1") is None


def test_pool_sync_readds_draining_member_with_state_intact():
    pool = EndpointPool.from_addresses(["a:1"])
    ep = pool.get("a:1")
    ep.metadata_cache["m"] = {"version": "7", "payload": {}}
    ep.inflight = 1
    pool.remove("a:1")
    assert ep.health == DRAINING
    # Scale-down reverted before the drain finished: same object
    # rejoins (breakers and caches intact), no new Endpoint.
    pool.sync([("a:1", None)])
    assert pool.get("a:1") is ep
    assert ep.health == UNKNOWN and ep.routable()
    assert ep.metadata_cache["m"]["version"] == "7"


def test_pool_sync_retargets_grpc_on_retained_member():
    # Membership updates may change a RETAINED replica's binary
    # address (gRPC enabled later, port moved, disabled): the pool
    # must swap it — and zero the binary breaker, whose evidence
    # concerns the old wire — instead of silently keeping the stale
    # address/channel forever. REST-side state survives untouched.
    pool = EndpointPool.from_addresses(["a:1"], [None])
    ep = pool.get("a:1")
    ep.metadata_cache["m"] = {"version": "1", "payload": {}}
    for _ in range(ep.grpc_breaker.failure_threshold):
        ep.grpc_breaker.record_failure()
    assert ep.grpc_breaker.state == "open"
    sentinel = object()
    ep.grpc_channel = sentinel  # stale dialed channel must be dropped
    pool.sync([("a:1", "a:9000")])
    assert ep is pool.get("a:1")  # retained, not recreated
    assert ep.grpc_address == "a:9000"
    assert ep.grpc_channel is None
    assert ep.grpc_breaker.state == "closed"
    assert ep.metadata_cache["m"]["version"] == "1"
    pool.sync([("a:1", None)])  # ...and disabling works too
    assert ep.grpc_address is None


def test_pool_sync_adds_and_removes():
    pool = EndpointPool.from_addresses(["a:1", "b:1"])
    added, removed = pool.sync([("b:1", None), ("c:1", "c:9")])
    assert added == ["c:1"] and removed == ["a:1"]
    assert [ep.address for ep in pool.endpoints()] == ["b:1", "c:1"]
    assert pool.get("c:1").grpc_address == "c:9"


# ---------------------------------------------------------------------------
# Discovery sources


def test_file_source_hot_reloads_on_content_change(tmp_path):
    path = tmp_path / "endpoints.json"
    write_endpoints_file(str(path), [("a:1", "a:9"), ("b:1", None)])
    source = FileEndpointSource(str(path))
    assert source.specs() == [("a:1", "a:9"), ("b:1", None)]
    write_endpoints_file(str(path), [("b:1", None), ("c:1", None)])
    assert source.specs() == [("b:1", None), ("c:1", None)]
    # The writer's temp file never survives (atomic rename).
    assert [p.name for p in tmp_path.iterdir()] == ["endpoints.json"]


def test_file_source_keeps_last_good_on_damage(tmp_path):
    path = tmp_path / "endpoints.json"
    path.write_text(json.dumps(["a:1"]))
    source = FileEndpointSource(str(path))
    assert source.specs() == [("a:1", None)]
    path.write_text("{not json")  # half-written human edit
    assert source.specs() == [("a:1", None)]
    path.unlink()  # missing file: same story
    assert source.specs() == [("a:1", None)]
    path.write_text(json.dumps(["b:1"]))  # recovers on good content
    assert source.specs() == [("b:1", None)]


def test_file_source_accepts_bare_list_and_dict_shapes(tmp_path):
    path = tmp_path / "e.json"
    path.write_text(json.dumps(
        {"endpoints": [{"address": "a:1", "grpc_address": "a:9"},
                       {"address": "b:1"}]}))
    assert FileEndpointSource(str(path)).specs() == [
        ("a:1", "a:9"), ("b:1", None)]


# ---------------------------------------------------------------------------
# HealthProber


def _prober(pool, responses, **kwargs):
    """Prober whose fetch is a dict: address → payload | Exception."""

    def fetch(ep):
        value = responses[ep.address]
        if isinstance(value, Exception):
            raise value
        return value

    return HealthProber(pool, fetch=fetch, **kwargs)


def test_prober_ejects_and_readmits():
    pool = EndpointPool.from_addresses(["a:1", "b:1"])
    responses = {"a:1": _healthz(), "b:1": ConnectionError("down")}
    prober = _prober(pool, responses, eject_after=3)
    for _ in range(2):
        prober.probe_all_sync()
    assert pool.get("b:1").routable()  # not yet: 2 of 3 strikes
    prober.probe_all_sync()
    assert not pool.get("b:1").routable()
    assert pool.get("a:1").health == HEALTHY
    # One good probe readmits.
    responses["b:1"] = _healthz({"m": _stats()})
    prober.probe_all_sync()
    assert pool.get("b:1").health == HEALTHY
    assert pool.get("b:1").resident_models() == ["m"]


def test_prober_nonready_status_counts_as_failure():
    pool = EndpointPool.from_addresses(["a:1"])
    prober = _prober(pool, {"a:1": {"status": "loading"}},
                     eject_after=1)
    prober.probe_all_sync()
    assert pool.get("a:1").health == UNHEALTHY
    # "degraded" (some breakers open, still serving) stays routable.
    prober2 = _prober(pool, {"a:1": _healthz(status="degraded")},
                      eject_after=1)
    prober2.probe_all_sync()
    assert pool.get("a:1").health == HEALTHY


def test_prober_syncs_membership_from_source_each_cycle(tmp_path):
    path = tmp_path / "endpoints.json"
    write_endpoints_file(str(path), [("a:1", None)])
    pool = EndpointPool()
    responses = {"a:1": _healthz(), "b:1": _healthz()}
    prober = _prober(pool, responses,
                     source=FileEndpointSource(str(path)))
    prober.probe_all_sync()
    assert [ep.address for ep in pool.endpoints()] == ["a:1"]
    # The autoscaler scales up: rewrite the file, next cycle follows.
    write_endpoints_file(str(path), [("a:1", None), ("b:1", None)])
    prober.probe_all_sync()
    assert [ep.address for ep in pool.endpoints()] == ["a:1", "b:1"]
    assert pool.get("b:1").health == HEALTHY


# ---------------------------------------------------------------------------
# eligible_endpoints + balancer policies


def test_eligible_prefers_closed_breakers_but_degrades():
    pool = EndpointPool.from_addresses(["a:1", "b:1"],
                                       breaker_reset_s=60.0)
    a, b = pool.endpoints()
    for _ in range(5):
        a.rest_breaker.record_failure()
    assert a.rest_breaker.state == "open"
    assert eligible_endpoints(pool) == [b]
    # Both open → the tier collapses rather than refusing to route.
    for _ in range(5):
        b.rest_breaker.record_failure()
    assert eligible_endpoints(pool) == [a, b]
    # Excluded (already tried this request) never come back.
    assert eligible_endpoints(pool, exclude=[a]) == [b]
    assert eligible_endpoints(pool, exclude=[a, b]) == []


def test_eligible_skips_ejected_until_nothing_else():
    pool = EndpointPool.from_addresses(["a:1", "b:1"])
    a, b = pool.endpoints()
    for _ in range(3):
        a.mark_probe_failure(eject_after=3)
    assert eligible_endpoints(pool) == [b]
    for _ in range(3):
        b.mark_probe_failure(eject_after=3)
    # All ejected: still route (probe traffic is how a prober-less
    # pool ever recovers).
    assert eligible_endpoints(pool) == [a, b]


def test_round_robin_rotates_evenly():
    pool = EndpointPool.from_addresses(["a:1", "b:1", "c:1"])
    rr = RoundRobinBalancer()
    picks = [rr.pick(pool.endpoints()).address for _ in range(9)]
    assert picks == ["a:1", "b:1", "c:1"] * 3
    assert rr.pick([]) is None


def test_least_saturation_picks_emptiest():
    pool = EndpointPool.from_addresses(["a:1", "b:1", "c:1"])
    a, b, c = pool.endpoints()
    a.saturation = {"m": _stats(queue_depth=5, latency_ms=10)}
    b.saturation = {"m": _stats(queue_depth=1, latency_ms=10)}
    c.saturation = {"m": _stats(queue_depth=3, latency_ms=10)}
    ls = LeastSaturationBalancer()
    assert ls.pick(pool.endpoints()) is b
    # The proxy's own in-flight count corrects between probes.
    b.inflight = 10
    assert ls.pick(pool.endpoints()) is c


def test_least_saturation_breaks_ties_by_rotation():
    pool = EndpointPool.from_addresses(["a:1", "b:1", "c:1"])
    ls = LeastSaturationBalancer()
    picks = {ls.pick(pool.endpoints()).address for _ in range(6)}
    # All scores equal (0): a pure min() would pin one replica.
    assert picks == {"a:1", "b:1", "c:1"}


def test_affinity_prefers_resident_replica():
    pool = EndpointPool.from_addresses(["a:1", "b:1", "c:1"])
    a, b, c = pool.endpoints()
    b.saturation = {"llama": _stats(queue_depth=1, latency_ms=10)}
    c.saturation = {"llama": _stats(queue_depth=4, latency_ms=10)}
    af = ResidentAffinityBalancer(overload_ms=500.0)
    # Resident on b and c; b is emptier. a (cold) never picked.
    for _ in range(4):
        assert af.pick(pool.endpoints(), model="llama") in (b, c)
    assert af.pick(pool.endpoints(), model="llama") is b


def test_affinity_falls_back_on_overload_and_nonresidence():
    pool = EndpointPool.from_addresses(["a:1", "b:1"])
    a, b = pool.endpoints()
    b.saturation = {"llama": _stats(queue_depth=100, latency_ms=10)}
    af = ResidentAffinityBalancer(overload_ms=500.0)
    # The only resident replica is past the overload bar (1000 ms):
    # overflow to pool-wide least-saturation (a, empty) rather than
    # hotspotting b — affinity is not an availability constraint.
    assert af.pick(pool.endpoints(), model="llama") is a
    # Model resident nowhere → plain least-saturation.
    assert af.pick(pool.endpoints(), model="gemma") is a
    # No model hint (metadata GETs) → plain least-saturation.
    assert af.pick(pool.endpoints()) is a


def test_make_balancer():
    assert make_balancer("round_robin").name == "round_robin"
    assert make_balancer("least_saturation").name == "least_saturation"
    assert make_balancer("affinity").name == "affinity"
    with pytest.raises(ValueError, match="unknown balancer"):
        make_balancer("random")


# ---------------------------------------------------------------------------
# Autoscaler decision core


class FakeScaler(Scaler):
    def __init__(self, replicas=1):
        self.replicas = replicas
        self.writes = []

    def get_replicas(self):
        return self.replicas

    def set_replicas(self, replicas):
        self.replicas = replicas
        self.writes.append(replicas)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _autoscaler(scaler, clock, **overrides):
    defaults = dict(min_replicas=1, max_replicas=8,
                    target_queue_wait_ms=100.0, hysteresis=0.2,
                    scale_up_cooldown_s=10.0,
                    scale_down_cooldown_s=30.0)
    defaults.update(overrides)
    return Autoscaler(AutoscalerConfig(**defaults), scaler,
                      clock=clock)


def test_config_validation():
    with pytest.raises(ValueError):
        AutoscalerConfig(min_replicas=3, max_replicas=2).validate()
    with pytest.raises(ValueError):
        AutoscalerConfig(target_queue_wait_ms=0).validate()
    with pytest.raises(ValueError):
        AutoscalerConfig(hysteresis=1.5).validate()


def test_holds_inside_hysteresis_band():
    scaler, clock = FakeScaler(2), FakeClock()
    asc = _autoscaler(scaler, clock)
    for wait in (81.0, 100.0, 119.0):  # within ±20% of 100
        d = asc.evaluate([{"queue_wait_ms": wait}])
        assert d["action"] == "hold"
        assert d["reason"] == "within_hysteresis_band"
    assert scaler.writes == []


def test_scales_up_proportionally_with_double_cap():
    scaler, clock = FakeScaler(2), FakeClock()
    asc = _autoscaler(scaler, clock)
    # ratio 6 wants 12; one decision may at most double the fleet.
    d = asc.evaluate([{"queue_wait_ms": 600.0}])
    assert d["action"] == "scale_up" and d["desired"] == 4
    assert scaler.replicas == 4


def test_scale_up_cooldown_blocks_consecutive_ups():
    scaler, clock = FakeScaler(1), FakeClock()
    asc = _autoscaler(scaler, clock)
    assert asc.evaluate([{"queue_wait_ms": 300.0}])["action"] == "scale_up"
    clock.t = 5.0  # inside the 10 s up-cooldown
    d = asc.evaluate([{"queue_wait_ms": 300.0}])
    assert d["action"] == "hold" and d["reason"] == "scale_up_cooldown"
    clock.t = 11.0
    assert asc.evaluate([{"queue_wait_ms": 300.0}])["action"] == "scale_up"


def test_scale_down_requires_quiet_since_any_action():
    scaler, clock = FakeScaler(1), FakeClock()
    asc = _autoscaler(scaler, clock)
    asc.evaluate([{"queue_wait_ms": 400.0}])
    assert scaler.replicas == 2
    # Load vanishes right after the up: the down must wait out the
    # down-cooldown from the UP (up-then-down is oscillation).
    clock.t = 15.0
    d = asc.evaluate([{"queue_wait_ms": 10.0}])
    assert d["action"] == "hold" and d["reason"] == "scale_down_cooldown"
    clock.t = 31.0
    d = asc.evaluate([{"queue_wait_ms": 10.0}])
    assert d["action"] == "scale_down" and scaler.replicas == 1


def test_shedding_forces_scale_up_despite_short_queue():
    scaler, clock = FakeScaler(2), FakeClock()
    asc = _autoscaler(scaler, clock)
    # Admission control keeps the queue short exactly when overloaded:
    # wait says "healthy", shed rate says undersized.
    d = asc.evaluate([{"queue_wait_ms": 20.0, "shed_rate": 3.0},
                      {"queue_wait_ms": 30.0, "expired_rate": 0.5}])
    assert d["action"] == "scale_up" and d["reason"] == "shedding"
    assert scaler.replicas == 3


def test_scale_down_halves_at_most_per_decision():
    scaler, clock = FakeScaler(8), FakeClock()
    asc = _autoscaler(scaler, clock)
    # One transiently-empty sample (scrape between dispatches) wants
    # ratio≈0 → min; the symmetric step clamp allows at most a halve.
    d = asc.evaluate([{"queue_wait_ms": 0.5}])
    assert d["action"] == "scale_down" and d["desired"] == 4
    assert scaler.replicas == 4


def test_clamps_at_min_and_max():
    scaler, clock = FakeScaler(8), FakeClock()
    asc = _autoscaler(scaler, clock)
    d = asc.evaluate([{"queue_wait_ms": 900.0}])
    assert d["action"] == "hold" and d["reason"] == "at_max_replicas"
    scaler.replicas = 1
    d = asc.evaluate([{"queue_wait_ms": 1.0}])
    assert d["action"] == "hold" and d["reason"] == "at_min_replicas"
    assert scaler.writes == []


def test_holds_on_blindness():
    scaler, clock = FakeScaler(3), FakeClock()
    asc = _autoscaler(scaler, clock)
    d = asc.evaluate([])
    assert d["action"] == "hold" and d["reason"] == "no_replica_metrics"


def test_bounds_enforced_as_fleet_invariants():
    # With `router true` the manifest omits spec.replicas, so a new
    # Deployment starts at the apiserver default of 1; min_replicas
    # must be a FLOOR the controller climbs to — immediately, even
    # before the first scrape lands (blind), not a mere decision
    # clamp the hold branches never reach.
    scaler, clock = FakeScaler(1), FakeClock()
    asc = _autoscaler(scaler, clock, min_replicas=3)
    d = asc.evaluate([])  # bootstrap: nothing scraped yet
    assert d["action"] == "scale_up"
    assert d["reason"] == "below_min_replicas"
    assert scaler.replicas == 3
    # Idle at the floor: at_min_replicas hold, no further writes.
    clock.t = 100.0
    d = asc.evaluate([{"queue_wait_ms": 1.0}])
    assert d["action"] == "hold" and scaler.replicas == 3
    # Symmetric ceiling: an operator lowering max_replicas below the
    # current fleet must see the fleet follow.
    scaler2, clock2 = FakeScaler(8), FakeClock()
    asc2 = _autoscaler(scaler2, clock2, max_replicas=4)
    d = asc2.evaluate([{"queue_wait_ms": 100.0}])
    assert d["action"] == "scale_down"
    assert d["reason"] == "above_max_replicas"
    assert scaler2.replicas == 4


def test_scale_down_holds_while_any_replica_unreachable():
    # 3 of 6 replicas wedge: the survivors look idle BECAUSE the
    # fleet already lost half its capacity. Shrinking on that signal
    # would delete live pods mid-outage (HPA: missing metrics read as
    # 100% utilization for shrink decisions).
    scaler, clock = FakeScaler(6), FakeClock()
    asc = _autoscaler(scaler, clock)
    idle = [{"queue_wait_ms": 1.0}] * 3
    d = asc.evaluate(idle, unreachable=3)
    assert d["action"] == "hold"
    assert d["reason"] == "unreachable_replicas"
    assert d["replicas_unreachable"] == 3
    assert scaler.writes == []
    # Scale-UP still acts on the survivors' signal: blind spots never
    # suppress adding capacity.
    d = asc.evaluate([{"queue_wait_ms": 500.0}] * 3, unreachable=3)
    assert d["action"] == "scale_up"
    # Fully observable again (and past the down-cooldown): the same
    # idle fleet may now shrink.
    clock.t = 100.0
    d = asc.evaluate(idle, unreachable=0)
    assert d["action"] == "scale_down"


def test_scripted_load_step_converges_without_oscillation():
    """ISSUE 5 acceptance: a load step up then down converges to the
    target band with no hunting. The plant: per-replica queue wait =
    offered_load / n (linear law — more replicas, shorter queues)."""
    scaler, clock = FakeScaler(1), FakeClock()
    asc = _autoscaler(scaler, clock)
    actions = []
    #       (seconds, offered load in queue-wait-at-1-replica ms)
    trace = [(t, 100.0) for t in range(0, 60, 5)]       # idle @ target
    trace += [(t, 600.0) for t in range(60, 240, 5)]    # step UP 6x
    trace += [(t, 100.0) for t in range(240, 480, 5)]   # step DOWN
    for t, load in trace:
        clock.t = float(t)
        d = asc.evaluate([{"queue_wait_ms": load / scaler.replicas}])
        actions.append((t, d["action"], scaler.replicas))
    # Phase 1 (load 100, 1 replica): wait == target, all holds.
    assert all(a == "hold" for t, a, n in actions if t < 60)
    # Phase 2: converges upward to 600/n within [80,120] → n in
    # {5,6,7}; plateau is flat (no further actions once in band).
    up_plateau = [n for t, a, n in actions if 180 <= t < 240]
    assert len(set(up_plateau)) == 1 and up_plateau[0] in (5, 6, 7)
    assert all(a == "hold" for t, a, n in actions if 180 <= t < 240)
    # Phase 3: converges back down (100/n in band → n == 1).
    down_plateau = [n for t, a, n in actions if t >= 420]
    assert set(down_plateau) == {1}
    assert all(a == "hold" for t, a, n in actions if t >= 420)
    # No oscillation anywhere: the replica trajectory is unimodal
    # (never rises again after its first decrease).
    series = [n for _, _, n in actions]
    peak = series.index(max(series))
    assert series[:peak + 1] == sorted(series[:peak + 1])
    assert series[peak:] == sorted(series[peak:], reverse=True)
    # And the control effort is small: a handful of writes, not one
    # per tick.
    assert len(scaler.writes) <= 8


# ---------------------------------------------------------------------------
# Actuation: scale subresource (FakeApiServer + HTTP facade)


def _serving_deployment(fake, name="kft-serving", replicas=2):
    fake.create({
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"replicas": replicas,
                 "template": {"spec": {"containers": []}}},
    })


def test_deployment_scaler_against_fake():
    fake = FakeApiServer()
    _serving_deployment(fake, replicas=2)
    scaler = DeploymentScaler(fake, "default", "kft-serving")
    assert scaler.get_replicas() == 2
    scaler.set_replicas(5)
    assert scaler.get_replicas() == 5
    # The narrow write: spec.replicas moved, the template did not.
    obj = fake.get("Deployment", "default", "kft-serving")
    assert obj["spec"]["replicas"] == 5
    assert obj["spec"]["template"] == {"spec": {"containers": []}}


def test_update_scale_noop_does_not_bump_resource_version():
    fake = FakeApiServer()
    _serving_deployment(fake, replicas=3)
    rv = fake.get("Deployment", "default",
                  "kft-serving")["metadata"]["resourceVersion"]
    fake.update_scale("Deployment", "default", "kft-serving", 3)
    assert fake.get("Deployment", "default",
                    "kft-serving")["metadata"]["resourceVersion"] == rv


def test_update_scale_stale_resource_version_conflicts():
    """The scale PUT carries optimistic concurrency: a writer racing
    another autoscaler (or `kubectl scale`) loses loudly with a 409,
    never last-write-wins."""
    from kubeflow_tpu.operator.fake import Conflict

    fake = FakeApiServer()
    _serving_deployment(fake, replicas=1)
    scale = fake.get_scale("Deployment", "default", "kft-serving")
    rv = scale["metadata"]["resourceVersion"]
    # A concurrent writer lands first (bumps resourceVersion)...
    fake.update_scale("Deployment", "default", "kft-serving", 3)
    # ...so our read-modify-PUT with the stale version must 409.
    with pytest.raises(Conflict):
        fake.update_scale("Deployment", "default", "kft-serving", 2,
                          resource_version=rv)
    assert fake.get_scale("Deployment", "default",
                          "kft-serving")["spec"]["replicas"] == 3


def test_grpc_addresses_refuse_ambiguous_same_host_fleet():
    """One --grpc_port cannot address two replicas on one host: the
    derived binary upstream is disabled for them (REST-only) instead
    of silently collapsing both onto a single gRPC channel."""
    from kubeflow_tpu.serving.http_proxy import _grpc_addresses

    assert _grpc_addresses(["h1:8500", "h2:8500"], 9000) == \
        ["h1:9000", "h2:9000"]
    assert _grpc_addresses(["h1:8501", "h1:8502", "h2:8500"],
                           9000) == [None, None, "h2:9000"]
    assert _grpc_addresses(["h1:8501", "h1:8502"], 0) == [None, None]


def test_make_app_refuses_single_grpc_string_for_fleet():
    """make_app's string back-compat form must not silently bind the
    binary wire to only the FIRST of N replicas — the list form
    raises on a length mismatch, so the string form raises too."""
    from kubeflow_tpu.serving.http_proxy import make_app

    with pytest.raises(ValueError, match="ambiguous"):
        make_app("h1:8500,h2:8500", grpc_address="h1:9000")
    # Single upstream keeps the classic form...
    app = make_app("h1:8500", grpc_address="h1:9000")
    assert app.settings["pool"].get("h1:8500").grpc_address == "h1:9000"
    # ...and a matching list still works for fleets.
    app = make_app(["h1:8500", "h2:8500"],
                   grpc_address=["h1:9000", None])
    assert app.settings["pool"].get("h2:8500").grpc_address is None


def test_scale_subresource_over_http_facade():
    from kubeflow_tpu.operator.http_client import HttpApiClient
    from tests._http_apiserver import HttpFakeApiServer

    fake = FakeApiServer()
    _serving_deployment(fake, replicas=1)
    with HttpFakeApiServer(fake=fake) as srv:
        api = HttpApiClient(srv.url)
        scaler = DeploymentScaler(api, "default", "kft-serving")
        assert scaler.get_replicas() == 1
        scaler.set_replicas(4)
        assert scaler.get_replicas() == 4
    assert fake.get("Deployment", "default",
                    "kft-serving")["spec"]["replicas"] == 4


def test_discover_pod_endpoints_filters_unready_pods():
    fake = FakeApiServer()
    for name, ip, phase in (("p0", "10.0.0.1", "Running"),
                            ("p1", None, "Running"),       # no IP yet
                            ("p2", "10.0.0.3", "Pending"),  # scheduling
                            ("p3", "10.0.0.4", "Running")):
        fake.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default",
                         "labels": {"app": "kft-serving"}},
            "status": {"phase": phase,
                       **({"podIP": ip} if ip else {})},
        })
    fake.create({  # different app: never a fleet member
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "other", "namespace": "default",
                     "labels": {"app": "other"}},
        "status": {"phase": "Running", "podIP": "10.0.0.9"},
    })
    specs = discover_pod_endpoints(fake, "default",
                                   {"app": "kft-serving"},
                                   rest_port=8500, grpc_port=9000)
    assert sorted(specs) == [("10.0.0.1:8500", "10.0.0.1:9000"),
                             ("10.0.0.4:8500", "10.0.0.4:9000")]
    specs = discover_pod_endpoints(fake, "default",
                                   {"app": "kft-serving"},
                                   rest_port=8500, grpc_port=None)
    assert sorted(specs) == [("10.0.0.1:8500", None),
                             ("10.0.0.4:8500", None)]


# ---------------------------------------------------------------------------
# Predictive mode (ISSUE 19): forecast merge, wake/collapse, inputs


def _predictive(scaler, clock, **overrides):
    defaults = dict(predictive=True, forecast_horizon_s=30.0,
                    forecast_window_s=60.0, replica_capacity_rps=10.0)
    defaults.update(overrides)
    return _autoscaler(scaler, clock, **defaults)


def test_predictive_config_validation():
    with pytest.raises(ValueError):  # waking from zero needs a forecast
        AutoscalerConfig(min_replicas=0, scale_to_zero=True).validate()
    with pytest.raises(ValueError):
        AutoscalerConfig(predictive=True,
                         replica_capacity_rps=0.0).validate()
    with pytest.raises(ValueError):  # min 0 only with scale-to-zero
        AutoscalerConfig(min_replicas=0).validate()
    AutoscalerConfig(min_replicas=0, predictive=True,
                     scale_to_zero=True).validate()


def test_forecast_raises_reactive_ratio_and_records_inputs():
    scaler, clock = FakeScaler(1), FakeClock()
    asc = _predictive(scaler, clock)
    # A 2 rps/s ramp: 30s past now forecasts ~+60 rps -> replicas.
    for t in range(5):
        clock.t = float(t)
        asc.observe_arrivals(10.0 + 2.0 * t)
    d = asc.evaluate([{"queue_wait_ms": 100.0}])  # reactive says hold
    assert d["action"] == "scale_up"
    assert d["reason"] == "forecast"
    # The decision record explains itself: signal values + what the
    # forecaster believed + which clamp bit (satellite: ConfigMap
    # decision records gain inputs).
    inputs = d["inputs"]
    assert inputs["mean_queue_wait_ms"] == 100.0
    assert inputs["forecast"]["samples"] == 5
    assert inputs["forecast"]["replicas"] >= 2
    assert inputs["forecast"]["rate_rps"] > 10.0
    assert inputs["clamp"] == "double_up"  # forecast wanted > 2x


def test_forecast_never_shrinks_what_reactive_keeps():
    scaler, clock = FakeScaler(4), FakeClock()
    asc = _predictive(scaler, clock)
    asc.observe_arrivals(0.0)  # forecast says zero replicas needed
    # Reactive signal in band: predictive mode must not shrink.
    d = asc.evaluate([{"queue_wait_ms": 100.0}])
    assert d["action"] == "hold"
    assert scaler.writes == []


def test_wake_from_zero_on_demand():
    scaler, clock = FakeScaler(0), FakeClock()
    asc = _predictive(scaler, clock, min_replicas=0,
                      scale_to_zero=True)
    # Silent fleet at zero: hold (and say so).
    d = asc.evaluate([])
    assert (d["action"], d["reason"]) == ("hold", "scaled_to_zero")
    # One observed request wakes the fleet without waiting for a fit.
    asc.observe_arrivals(0.5)
    d = asc.evaluate([])
    assert (d["action"], d["reason"]) == ("scale_up", "wake_from_zero")
    assert scaler.replicas == 1


def test_scale_to_zero_needs_provable_quiet():
    scaler, clock = FakeScaler(1), FakeClock()
    asc = _predictive(scaler, clock, min_replicas=0,
                      scale_to_zero=True, idle_quiet_s=120.0,
                      scale_down_cooldown_s=30.0)
    clock.t = 50.0
    assert asc.evaluate([{"queue_wait_ms": 0.0}])["action"] == "hold"
    clock.t = 100.0  # only 50s of silence: not enough
    assert asc.evaluate([{"queue_wait_ms": 0.0}])["action"] == "hold"
    clock.t = 200.0  # 150s of silence >= idle_quiet_s
    d = asc.evaluate([{"queue_wait_ms": 0.0}])
    assert (d["action"], d["desired"], d["reason"]) == \
        ("scale_down", 0, "scale_to_zero")
    assert scaler.replicas == 0


def test_reactive_path_never_reaches_zero():
    scaler, clock = FakeScaler(2), FakeClock()
    # Without scale-to-zero, min_replicas=0 is invalid; with min 1 the
    # normal halve path floors at 1 forever.
    asc = _autoscaler(scaler, clock, min_replicas=1)
    clock.t = 100.0
    d = asc.evaluate([{"queue_wait_ms": 0.0}])
    assert d["action"] == "scale_down" and d["desired"] == 1
    clock.t = 200.0
    d = asc.evaluate([{"queue_wait_ms": 0.0}])
    assert d["action"] == "hold" and d["reason"] == "at_min_replicas"
    assert d["inputs"]["clamp"] == "min_replicas"


# AutoscalerLoop: scrape → rates → decide → publish


def _loop_fixture(tmp_path=None, replicas=1, **config_overrides):
    fake = FakeApiServer()
    _serving_deployment(fake, replicas=replicas)
    scaler = DeploymentScaler(fake, "default", "kft-serving")
    clock = FakeClock()
    asc = _autoscaler(scaler, clock, **config_overrides)
    scrapes = {}

    def scrape(addr):
        value = scrapes[addr]
        if isinstance(value, Exception):
            raise value
        return value

    loop = AutoscalerLoop(
        asc,
        discover=lambda: [(addr, None) for addr in sorted(scrapes)],
        scrape=scrape,
        api=fake, namespace="default",
        write_endpoints_path=(str(tmp_path / "endpoints.json")
                              if tmp_path else None))
    return fake, scaler, clock, scrapes, loop


def test_loop_tick_publishes_fleet_and_decision():
    fake, scaler, clock, scrapes, loop = _loop_fixture()
    scrapes["a:8500"] = _healthz(
        {"m": _stats(queue_depth=2, latency_ms=50.0)})
    scrapes["b:8500"] = ConnectionError("down")
    decision = loop.tick()
    # Only the reachable replica reports; mean wait 100 → in band.
    assert decision["action"] == "hold"
    assert decision["replicas_reporting"] == 1
    cm = fake.get("ConfigMap", "default", FLEET_CONFIGMAP)
    fleet = json.loads(cm["data"][FLEET_KEY])
    rows = {r["address"]: r for r in fleet["replicas"]}
    assert rows["a:8500"]["reachable"]
    assert rows["a:8500"]["queue_wait_ms"] == pytest.approx(100.0)
    assert rows["a:8500"]["resident_models"] == ["m"]
    assert not rows["b:8500"]["reachable"]
    assert fleet["decision"]["action"] == "hold"
    assert "age_s" in fleet["decision"]  # monotonic time never ships
    # Published decisions carry their INPUTS (ISSUE 19): the signal
    # values and clamp that produced the verdict, dashboard-readable.
    inputs = fleet["decision"]["inputs"]
    assert inputs["mean_queue_wait_ms"] == pytest.approx(100.0)
    assert "shed_rate" in inputs and "clamp" in inputs


def test_loop_differentiates_cumulative_shed_counters():
    fake, scaler, clock, scrapes, loop = _loop_fixture()

    def scrape_with(shed):
        scrapes["a:8500"] = _healthz(
            {"m": _stats(queue_depth=0, latency_ms=10.0, shed=shed)})

    scrape_with(5)
    loop.tick()  # first sight: no previous sample, rate 0 → hold
    assert loop.autoscaler.last_decision["action"] == "hold"
    scrape_with(5)
    loop.tick()  # counter flat: still not shedding
    assert loop.autoscaler.last_decision["action"] == "hold"
    scrape_with(9)
    decision = loop.tick()  # delta 4 → nonzero rate → undersized
    assert decision["action"] == "scale_up"
    assert decision["reason"] == "shedding"
    # Counter RESET (replica restart) must clamp at zero, not read as
    # a huge negative (or positive) rate.
    scrape_with(0)
    clock.t = 100.0  # clear the up-cooldown so only the rate matters
    decision = loop.tick()
    assert decision["action"] != "scale_up" or \
        decision["reason"] != "shedding"


def test_loop_writes_endpoints_file_for_proxy(tmp_path):
    fake, scaler, clock, scrapes, loop = _loop_fixture(tmp_path)
    scrapes["a:8500"] = _healthz()
    loop.tick()
    source = FileEndpointSource(str(tmp_path / "endpoints.json"))
    assert source.specs() == [("a:8500", None)]
    # Membership change lands in the next tick's file.
    scrapes["b:8500"] = _healthz()
    loop.tick()
    assert source.specs() == [("a:8500", None), ("b:8500", None)]


def test_loop_closes_the_loop_against_fake_scale(tmp_path):
    """End-to-end control loop: saturated healthz → scale_up actuated
    through the Deployment scale subresource → the fleet file keeps
    the proxy's membership in step."""
    fake, scaler, clock, scrapes, loop = _loop_fixture(
        tmp_path, replicas=1)
    scrapes["a:8500"] = _healthz(
        {"m": _stats(queue_depth=30, latency_ms=20.0)})  # 600 ms wait
    decision = loop.tick()
    assert decision["action"] == "scale_up"
    assert fake.get("Deployment", "default",
                    "kft-serving")["spec"]["replicas"] == 2
    # The autoscaler's own thread loop is Event-paced; run() honors
    # max_cycles so tests never depend on wall time.
    loop.run(max_cycles=1)


def test_loop_survives_scrape_and_publish_chaos():
    fake, scaler, clock, scrapes, loop = _loop_fixture()
    scrapes["a:8500"] = RuntimeError("scrape exploded")
    decision = loop.tick()  # everything unreachable → hold, no raise
    assert decision["action"] == "hold"
    assert decision["reason"] == "no_replica_metrics"


# ---------------------------------------------------------------------------
# Static source sanity (the --rpc_address a,b,c form)


def test_static_source_round_trip():
    source = StaticEndpointSource([("a:1", "a:9"), ("b:1", None)])
    assert source.specs() == [("a:1", "a:9"), ("b:1", None)]
    pool = EndpointPool()
    pool.sync(source.specs())
    assert [ep.address for ep in pool.endpoints()] == ["a:1", "b:1"]


def test_endpoint_snapshot_shape():
    ep = Endpoint("a:1", "a:9")
    ep.saturation = {"m": _stats(queue_depth=1, latency_ms=10.0)}
    snap = ep.snapshot()
    assert snap["address"] == "a:1"
    assert snap["grpc_address"] == "a:9"
    assert snap["health"] == UNKNOWN
    assert snap["resident_models"] == ["m"]
    assert snap["breakers"]["rest"]["state"] == "closed"
    json.dumps(snap)  # JSON-shaped end to end


def test_pool_concurrent_sync_and_reads():
    """Membership churn under concurrent readers must never raise
    (the prober syncs while the IOLoop routes)."""
    pool = EndpointPool.from_addresses(["a:1", "b:1"])
    stop = threading.Event()
    errors = []

    def churn():
        i = 0
        while not stop.is_set():
            i += 1
            try:
                pool.sync([(f"m{i % 7}:1", None), ("a:1", None)])
            except Exception as e:  # noqa: BLE001
                errors.append(e)

    def read():
        while not stop.is_set():
            try:
                for ep in eligible_endpoints(pool):
                    ep.saturation_score()
                pool.snapshot()
            except Exception as e:  # noqa: BLE001
                errors.append(e)

    threads = [threading.Thread(target=f)
               for f in (churn, read, read)]
    for t in threads:
        t.start()
    stop_at = threading.Event()
    stop_at.wait(0.5)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert errors == []
