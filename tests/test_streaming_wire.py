# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Token-streaming wire conformance (docs/streaming.md).

Three layers: the SSE codec itself (framing pinned byte-for-byte),
the router hop (chunk-by-chunk relay proven with a GATED upstream —
a buffering proxy deadlocks the test instead of passing it), and the
full stack over a real model (SSE grammar, REST/gRPC stream payloads
equal to the unary response, per-request budgets, client helpers).
"""

import asyncio
import http.client
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from kubeflow_tpu.serving import wire

# -- SSE codec ------------------------------------------------------------


def test_sse_event_framing_is_pinned():
    assert wire.format_sse_event({"a": 1}) == b'data: {"a": 1}\n\n'
    assert wire.format_sse_event({"t": 5}, event="token") == \
        b'event: token\ndata: {"t": 5}\n\n'
    with pytest.raises(ValueError, match="newline"):
        wire.format_sse_event({}, event="to\nken")


def test_sse_json_newlines_stay_on_one_data_line():
    """json.dumps escapes raw newlines, so any payload stays a single
    data: line — a split frame would desync every consumer."""
    frame = wire.format_sse_event({"s": "a\nb\r\nc"})
    assert frame.count(b"\n") == 2  # data line + terminator
    ((_, data),) = wire.iter_sse_events(frame.splitlines(True))
    assert data["s"] == "a\nb\r\nc"


def test_sse_parser_roundtrip_and_spec_corners():
    lines = [
        b": keep-alive comment\n",
        b"event: token\n",
        b'data: {"token": 3}\n',
        b"\n",
        b'data: {"plain": true}\n',
        b"\n",
        b"event: done\n",
        b'data: {"tokens": [[1]]}\n',  # no trailing blank: EOF flush
    ]
    events = list(wire.iter_sse_events(iter(lines)))
    assert events == [("token", {"token": 3}),
                      ("message", {"plain": True}),  # default name
                      ("done", {"tokens": [[1]]})]


def test_sse_parser_joins_multi_data_lines():
    lines = [b"data: [1,\n", b"data: 2]\n", b"\n"]
    assert list(wire.iter_sse_events(iter(lines))) == \
        [("message", [1, 2])]


def test_sse_event_names_catalog():
    assert wire.SSE_EVENTS == ("token", "error", "done")
    assert wire.SSE_CONTENT_TYPE == "text/event-stream"


# -- the router hop: chunk-by-chunk relay, proven with a gated upstream ---


class _GatedUpstream:
    """A fake model-server REST upstream whose SSE body is emitted in
    test-controlled phases: event 0 flushes immediately; the rest only
    after the test calls release(). A proxy that buffers the full
    response can never hand the first event to the client before
    release() — and the test reads the first event BEFORE releasing,
    so buffering means deadlock-until-timeout, not a silent pass."""

    def __init__(self, fail_after_first: bool = False):
        import tornado.web

        self.released = asyncio.Event()
        self.fail_after_first = fail_after_first
        self.started = threading.Event()
        self.port = None
        self.loop = None
        outer = self

        class Handler(tornado.web.RequestHandler):
            async def post(self, name):
                self.set_header("Content-Type",
                                wire.SSE_CONTENT_TYPE)
                self.write(wire.format_sse_event(
                    {"row": 0, "index": 0, "token": 41},
                    event="token"))
                await self.flush()
                if outer.fail_after_first:
                    # Abort mid-chunked-stream: the relay must report
                    # the break in-band, not hang or 500 after bytes
                    # already reached the client.
                    self.request.connection.stream.close()
                    return
                await outer.released.wait()
                self.write(wire.format_sse_event(
                    {"row": 0, "index": 1, "token": 42},
                    event="token"))
                self.write(wire.format_sse_event(
                    {"model_spec": {"name": name, "version": "1"},
                     "tokens": [[41, 42]]}, event="done"))
                await self.flush()
                self.finish()

        self._handler = Handler

    def __enter__(self):
        import tornado.ioloop
        import tornado.web

        def run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            app = tornado.web.Application([
                (r"/v1/models/([^/:]+):generate", self._handler),
            ])
            server = app.listen(0)
            self.port = next(iter(
                server._sockets.values())).getsockname()[1]
            self.loop = tornado.ioloop.IOLoop.current()
            self.started.set()
            self.loop.start()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        assert self.started.wait(15)
        return self

    def release(self):
        self.loop.add_callback(self.released.set)

    def __exit__(self, *exc):
        self.loop.add_callback(self.loop.stop)
        self._thread.join(timeout=10)


def _start_proxy(upstream_port):
    from kubeflow_tpu.serving.http_proxy import make_app

    started = threading.Event()
    holder = {}

    def run():
        import tornado.ioloop
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        app = make_app(rpc_address=f"127.0.0.1:{upstream_port}")
        server = app.listen(0)
        holder["port"] = next(iter(
            server._sockets.values())).getsockname()[1]
        holder["loop"] = tornado.ioloop.IOLoop.current()
        started.set()
        holder["loop"].start()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(15)
    holder["thread"] = t
    return holder


def _stop_proxy(holder):
    holder["loop"].add_callback(holder["loop"].stop)
    holder["thread"].join(timeout=10)


def _open_stream(port, model="fake", timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port,
                                      timeout=timeout)
    conn.request("POST", f"/model/{model}:generate",
                 body=json.dumps({"instances": [[1, 2]],
                                  "stream": True}),
                 headers={"Content-Type": "application/json"})
    return conn, conn.getresponse()


def _read_one_event(resp):
    """Read exactly one SSE frame off the live socket (blocking reads
    bounded by the socket timeout)."""
    lines = []
    while True:
        line = resp.readline()
        if not line:
            raise AssertionError("stream closed mid-frame")
        lines.append(line)
        if line in (b"\n", b"\r\n"):
            return next(wire.iter_sse_events(iter(lines)))


def test_proxy_relays_stream_chunk_by_chunk_not_buffered():
    with _GatedUpstream() as upstream:
        proxy = _start_proxy(upstream.port)
        try:
            conn, resp = _open_stream(proxy["port"])
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith(
                wire.SSE_CONTENT_TYPE)
            # First token crosses the hop while the upstream response
            # is still OPEN — time-to-first-token survives the router.
            event, data = _read_one_event(resp)
            assert (event, data["token"]) == ("token", 41)
            upstream.release()  # only now may the rest exist at all
            rest = list(wire.iter_sse_events(resp))
            conn.close()
            assert [e for e, _ in rest] == ["token", "done"]
            assert rest[-1][1]["tokens"] == [[41, 42]]
        finally:
            _stop_proxy(proxy)


def test_proxy_reports_mid_stream_upstream_failure_in_band():
    """Once bytes have been relayed the proxy cannot unsend them: an
    upstream that dies mid-stream must surface as a terminal SSE
    ``error`` event (code UNAVAILABLE) on the SAME stream, never as a
    hang or a late status rewrite."""
    with _GatedUpstream(fail_after_first=True) as upstream:
        proxy = _start_proxy(upstream.port)
        try:
            conn, resp = _open_stream(proxy["port"])
            events = list(wire.iter_sse_events(resp))
            conn.close()
            assert events[0] == ("token",
                                 {"row": 0, "index": 0, "token": 41})
            assert events[-1][0] == "error"
            assert events[-1][1]["code"] == "UNAVAILABLE"
        finally:
            _stop_proxy(proxy)


# -- full stack over a real model -----------------------------------------

PROMPT_LEN = 8
NEW_TOKENS = 6
CACHE = 32


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    """Export a tiny generate model and stand up the whole transport
    chain: ModelManager (continuous batching) + REST server + gRPC
    server + pooled proxy, each on a real socket."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models.llama import llama_test
    from kubeflow_tpu.serving.export import export_model
    from kubeflow_tpu.serving.grpc_server import make_server
    from kubeflow_tpu.serving.manager import ModelManager
    from kubeflow_tpu.serving.signature import (
        ModelMetadata,
        Signature,
        TensorSpec,
    )

    base = tmp_path_factory.mktemp("stream") / "m"
    model = llama_test(dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, PROMPT_LEN), jnp.int32))
    meta = ModelMetadata(
        model_name="m", registry_name="llama-test",
        model_kwargs={"dtype": "float32", "cache_size": CACHE},
        signatures={"serving_default": Signature(
            method="generate",
            inputs={"input_ids": TensorSpec("int32",
                                            (-1, PROMPT_LEN))},
            outputs={"tokens": TensorSpec("int32",
                                          (-1, NEW_TOKENS))})},
        generate_config={"max_new_tokens": NEW_TOKENS,
                         "temperature": 0.0,
                         "engine_slots": 2, "engine_page_size": 8,
                         "engine_slice_tokens": 2})
    export_model(str(base), 1, meta, {"params": variables["params"]})

    mgr = ModelManager(poll_interval_s=3600)
    mgr.add_model("m", str(base), max_batch=8,
                  continuous_batching=True)

    def serve(app_factory, holder, started):
        import tornado.ioloop
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        server = app_factory().listen(0)
        holder["port"] = next(iter(
            server._sockets.values())).getsockname()[1]
        holder["loop"] = tornado.ioloop.IOLoop.current()
        started.set()
        holder["loop"].start()

    from kubeflow_tpu.serving.server import make_app as rest_app

    rest, rest_started = {}, threading.Event()
    threading.Thread(target=serve, args=(lambda: rest_app(mgr), rest,
                                         rest_started),
                     daemon=True).start()
    assert rest_started.wait(60)

    gsrv, gport = make_server(mgr, 0)
    gsrv.start()

    from kubeflow_tpu.serving.http_proxy import make_app as proxy_app

    proxy, proxy_started = {}, threading.Event()
    threading.Thread(
        target=serve,
        args=(lambda: proxy_app(
            rpc_address=f"127.0.0.1:{rest['port']}",
            grpc_address=f"127.0.0.1:{gport}"), proxy, proxy_started),
        daemon=True).start()
    assert proxy_started.wait(60)

    yield {"rest": rest["port"], "grpc": gport,
           "proxy": proxy["port"], "manager": mgr}

    proxy["loop"].add_callback(proxy["loop"].stop)
    rest["loop"].add_callback(rest["loop"].stop)
    gsrv.stop(grace=1)
    mgr.stop()


def _unary_tokens(port, prompt_rows):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/m:generate",
        data=json.dumps({"instances": prompt_rows}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        body = json.load(r)
    return [p["tokens"] for p in body["predictions"]]


def _prompt_rows(n, seed=1):
    rng = np.random.RandomState(seed)
    return rng.randint(0, 512, (n, PROMPT_LEN)).tolist()


def test_sse_stream_grammar_and_unary_equality(stack):
    """Wire conformance against the live engine: the event stream is
    token* error* done (one terminal done, token indexes strictly
    sequential per row), and the streamed tokens reassemble into
    exactly the unary :generate answer."""
    rows = _prompt_rows(2)
    ref = _unary_tokens(stack["rest"], rows)

    conn = http.client.HTTPConnection("127.0.0.1", stack["rest"],
                                      timeout=120)
    conn.request("POST", "/v1/models/m:generate",
                 body=json.dumps({"instances": rows, "stream": True}),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.headers["Content-Type"].startswith(
        wire.SSE_CONTENT_TYPE)
    events = list(wire.iter_sse_events(resp))
    conn.close()

    assert [e for e, _ in events if e == "done"] == ["done"]
    assert events[-1][0] == "done", "done must terminate the stream"
    per_row = {0: [], 1: []}
    for event, data in events[:-1]:
        assert event == "token", f"unexpected event {event}"
        assert data["index"] == len(per_row[data["row"]]), \
            "token indexes must be per-row sequential"
        per_row[data["row"]].append(data["token"])
    done = events[-1][1]
    assert done["model_spec"]["name"] == "m"
    for r in (0, 1):
        assert per_row[r] == ref[r], \
            f"row {r}: streamed tokens != unary response"
        assert done["tokens"][r] == ref[r]


def test_streaming_requires_generate_verb(stack):
    conn = http.client.HTTPConnection("127.0.0.1", stack["rest"],
                                      timeout=30)
    conn.request("POST", "/v1/models/m:predict",
                 body=json.dumps({"instances": _prompt_rows(1),
                                  "stream": True}),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    body = json.loads(resp.read())
    conn.close()
    assert resp.status == 400
    assert ":generate" in body["error"]


def test_accept_header_negotiates_streaming(stack):
    """Accept: text/event-stream alone (no body flag) selects SSE —
    the EventSource-style client contract."""
    conn = http.client.HTTPConnection("127.0.0.1", stack["rest"],
                                      timeout=120)
    conn.request("POST", "/v1/models/m:generate",
                 body=json.dumps({"instances": _prompt_rows(1)}),
                 headers={"Content-Type": "application/json",
                          "Accept": wire.SSE_CONTENT_TYPE})
    resp = conn.getresponse()
    assert resp.headers["Content-Type"].startswith(
        wire.SSE_CONTENT_TYPE)
    events = list(wire.iter_sse_events(resp))
    conn.close()
    assert events[-1][0] == "done"


def test_client_helper_streams_through_proxy(stack):
    """serving.client.stream_generate through the pooled proxy: the
    public consumer sees the same tokens the backend decoded, and the
    done frame carries the full arrays."""
    from kubeflow_tpu.serving import client as kclient

    rows = _prompt_rows(1, seed=5)
    ref = _unary_tokens(stack["rest"], rows)
    got, done = [], None
    for event, data in kclient.stream_generate(
            f"127.0.0.1:{stack['proxy']}", "m", rows):
        if event == "token":
            got.append(data["token"])
        elif event == "done":
            done = data
    assert got == ref[0]
    assert done["tokens"][0] == ref[0]


def test_per_request_max_new_tokens_truncates_stream(stack):
    from kubeflow_tpu.serving import client as kclient

    rows = _prompt_rows(1, seed=9)
    ref = _unary_tokens(stack["rest"], rows)
    got = []
    for event, data in kclient.stream_generate(
            f"127.0.0.1:{stack['proxy']}", "m", rows,
            max_new_tokens=3):
        if event == "token":
            got.append(data["token"])
        elif event == "done":
            assert data["tokens"][0] == ref[0][:3]
    assert got == ref[0][:3], \
        "a 3-token budget must retire the slot after 3 tokens"


def test_grpc_generate_stream_matches_unary(stack):
    from kubeflow_tpu.serving import client as kclient

    rows = _prompt_rows(2, seed=13)
    ref = _unary_tokens(stack["rest"], rows)
    per_row = {0: [], 1: []}
    final = None
    for event, data in kclient.grpc_generate_stream(
            f"127.0.0.1:{stack['grpc']}", "m", {"input_ids": rows},
            timeout=120):
        if event == "token":
            assert data["index"] == len(per_row[data["row"]])
            per_row[data["row"]].append(data["token"])
        else:
            final = data
    for r in (0, 1):
        assert per_row[r] == ref[r]
        assert final["tokens"][r] == ref[r]


def test_tokens_arrive_incrementally_not_at_once(stack):
    """The slice cadence is observable on the wire: with
    engine_slice_tokens=2 and 6 tokens, the frames cannot all arrive
    in one flush — there must be at least two distinct socket reads'
    worth of data (the buffered alternative delivers everything with
    the done frame)."""
    rows = _prompt_rows(1, seed=17)
    conn = http.client.HTTPConnection("127.0.0.1", stack["rest"],
                                      timeout=120)
    conn.request("POST", "/v1/models/m:generate",
                 body=json.dumps({"instances": rows, "stream": True}),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    arrivals = []
    events = []
    while True:
        lines = []
        while True:
            line = resp.readline()
            if not line:
                break
            lines.append(line)
            if line in (b"\n", b"\r\n"):
                break
        if not lines:
            break
        arrivals.append(time.monotonic())
        got = list(wire.iter_sse_events(iter(lines)))
        events.extend(got)
        if got and got[-1][0] == "done":
            break
    conn.close()
    tokens = [d["token"] for e, d in events if e == "token"]
    assert len(tokens) == NEW_TOKENS
    # First token must land strictly before the last frame: streaming,
    # not one terminal buffer flush. (Time-based but generous: the
    # engine decodes 3 slices; a buffered path has zero gap.)
    assert arrivals[-1] - arrivals[0] > 0.0005, \
        "all frames arrived in one flush — stream was buffered"
