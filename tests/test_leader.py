# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Lease-based leader election (operator/leader.py): protocol unit
tests, two-replica takeover, controller integration (only the leader
reconciles; followers take over on leader death), and the Lease path
over the production HTTP client."""

import threading
import time

from kubeflow_tpu.manifests.tpujob import KIND
from kubeflow_tpu.operator import FakeApiServer
from kubeflow_tpu.operator.controller import WatchController
from kubeflow_tpu.operator.http_client import HttpApiClient
from kubeflow_tpu.operator.leader import LeaderElector
from kubeflow_tpu.operator.reconciler import JOB_LABEL, Reconciler

from tests._http_apiserver import HttpFakeApiServer
from tests.test_operator import make_job, submit


def _wait_for(predicate, timeout=5.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def test_single_elector_acquires_and_renews():
    api = FakeApiServer()
    el = LeaderElector(api, identity="a", lease_seconds=5)
    assert el._tick() is True
    lease = api.get("Lease", "default", "tpujob-operator")
    assert lease["spec"]["holderIdentity"] == "a"
    first_renew = lease["spec"]["renewTime"]
    assert el._tick() is True  # renew
    lease = api.get("Lease", "default", "tpujob-operator")
    assert lease["spec"]["renewTime"] >= first_renew
    assert lease["spec"]["leaseTransitions"] == 0


def test_second_elector_waits_then_takes_over_expired_lease():
    api = FakeApiServer()
    # leaseDurationSeconds is an int32 on real apiservers; 1 s is the
    # smallest honest test lease.
    a = LeaderElector(api, identity="a", lease_seconds=1)
    b = LeaderElector(api, identity="b", lease_seconds=1)
    assert a._tick() is True
    assert b._tick() is False  # live lease held by a
    time.sleep(1.1)  # a stops renewing; lease expires
    assert b._tick() is True
    lease = api.get("Lease", "default", "tpujob-operator")
    assert lease["spec"]["holderIdentity"] == "b"
    assert lease["spec"]["leaseTransitions"] == 1
    # a cannot renew through b's live lease (optimistic concurrency
    # on the client path; holder check here).
    assert a._tick() is False


def test_takeover_revalidates_at_write_time():
    """TOCTOU (r5 review): the challenger's expiry check reads one
    snapshot, but the read-modify-write patch re-reads the lease — if
    the holder renewed in between, the write must ABORT, not
    overwrite the now-live lease (two simultaneous leaders)."""
    api = FakeApiServer()
    a = LeaderElector(api, identity="a", lease_seconds=1)
    b = LeaderElector(api, identity="b", lease_seconds=1)
    assert a._tick() is True
    time.sleep(1.1)  # expired: b's _tick-time check will pass

    real_patch = api.patch

    def renewing_patch(kind, ns, name, mutate):
        # Interleave: a renews AFTER b's GET but BEFORE b's write.
        if kind == "Lease":
            api.patch = real_patch
            assert a._tick() is True  # a renews first
        return real_patch(kind, ns, name, mutate)

    api.patch = renewing_patch
    assert b._tick() is False  # write-time re-validation aborts
    lease = api.get("Lease", "default", "tpujob-operator")
    assert lease["spec"]["holderIdentity"] == "a"
    assert lease["spec"]["leaseTransitions"] == 0


def test_broken_lease_path_declares_elector_broken():
    """Persistent lease-path ERRORS (403 from stale RBAC, not lost
    races) must not masquerade as followership forever — the elector
    flags itself broken so the controller can crash visibly."""
    api = FakeApiServer()

    def forbidden(*a, **k):
        raise RuntimeError("HTTP 403 Forbidden (leases)")

    api.get = forbidden
    el = LeaderElector(api, identity="a", lease_seconds=1,
                       retry_seconds=0.001)
    el.MAX_CONSECUTIVE_ERRORS = 5
    t = threading.Thread(target=el.loop, daemon=True)
    t.start()
    t.join(timeout=5)
    assert not t.is_alive()
    assert el.broken.is_set()
    assert not el.is_leader()


def test_lost_renewal_drops_leadership_immediately():
    """A Conflict on renewal means another writer won: the elector
    must NOT keep acting as leader through a failed write."""
    api = FakeApiServer()
    el = LeaderElector(api, identity="a", lease_seconds=5)
    assert el._tick() is True

    from kubeflow_tpu.operator.fake import Conflict

    real_patch = api.patch

    def conflicting_patch(kind, ns, name, mutate):
        if kind == "Lease":
            raise Conflict("concurrent holder")
        return real_patch(kind, ns, name, mutate)

    api.patch = conflicting_patch
    assert el._tick() is False


class _CountingReconciler(Reconciler):
    def __init__(self, api, **kw):
        super().__init__(api, **kw)
        self.passes = 0

    def reconcile(self, job):
        self.passes += 1
        return super().reconcile(job)


def test_only_leader_reconciles_and_follower_takes_over():
    """Two controller replicas on one store: exactly one reconciles;
    when its elector dies (stops renewing), the follower inherits
    within the lease window and continues the job."""
    api = FakeApiServer()
    controllers = []
    threads = []
    for ident in ("a", "b"):
        ctl = WatchController(
            api, relist_seconds=0.3,
            reconciler=_CountingReconciler(api),
            elector=LeaderElector(api, identity=ident,
                                  lease_seconds=0.4,
                                  retry_seconds=0.05))
        t = threading.Thread(target=ctl.run, daemon=True)
        controllers.append(ctl)
        threads.append(t)
    controllers[0].elector._tick()  # deterministic first leader: "a"
    for t in threads:
        t.start()
    try:
        assert _wait_for(lambda: controllers[0].elector.is_leader())
        submit(api, make_job(name="lj", workers=2))
        assert _wait_for(lambda: len(
            api.list("Pod", "default", {JOB_LABEL: "lj"})) == 2, 5.0)
        assert controllers[0].reconciler.passes > 0
        # The follower never reconciled while the leader lived.
        assert controllers[1].reconciler.passes == 0

        # Leader dies: its elector stops renewing (loop killed), the
        # lease expires, "b" inherits and handles the next event.
        controllers[0].elector.stop.set()
        controllers[0].stop.set()
        assert _wait_for(lambda: controllers[1].elector.is_leader(),
                         5.0), "follower never took over"
        api.set_all_pod_phases("default", "Running", {JOB_LABEL: "lj"})
        assert _wait_for(
            lambda: api.get(KIND, "default", "lj").get(
                "status", {}).get("phase") == "Running", 5.0)
        assert controllers[1].reconciler.passes > 0
    finally:
        for ctl in controllers:
            ctl.stop.set()
            if ctl.elector:
                ctl.elector.stop.set()
        for t in threads:
            t.join(timeout=10)


def test_clean_shutdown_releases_lease():
    """A cleanly-stopped leader releases the lease so the peer takes
    over immediately instead of waiting out the duration."""
    api = FakeApiServer()
    el = LeaderElector(api, identity="a", lease_seconds=30,
                       retry_seconds=0.05)
    t = threading.Thread(target=el.loop, daemon=True)
    t.start()
    assert _wait_for(el.is_leader)
    el.stop.set()
    t.join(timeout=5)
    # Despite the 30s duration, a successor acquires NOW.
    b = LeaderElector(api, identity="b", lease_seconds=30)
    assert b._tick() is True


def test_shutdown_release_never_clobbers_new_holder():
    """Leadership lost between the last tick and shutdown (lease
    expired, peer took over): the clean-shutdown release must ABORT
    instead of zeroing the live peer's lease — an unconditional
    release would hand a second follower an instant takeover
    (two-leader window, ADVICE r5)."""
    api = FakeApiServer()
    el = LeaderElector(api, identity="a", lease_seconds=1,
                       retry_seconds=0.05)
    assert el._tick() is True
    # Peer "b" takes over after a's lease expires, before a shuts down.
    time.sleep(1.1)
    b = LeaderElector(api, identity="b", lease_seconds=30)
    assert b._tick() is True
    # a still believes it leads (no tick since): run its shutdown path.
    el._leader.set()
    el.stop.set()
    el.loop()
    lease = api.get("Lease", "default", "tpujob-operator")
    assert lease["spec"]["holderIdentity"] == "b", (
        "release clobbered the live peer's lease")
    assert lease["spec"]["renewTime"] is not None


def test_expired_handles_naive_renew_time():
    """A non-Python holder may write an offset-less renewTime; the
    aware-vs-naive comparison must not raise TypeError (which the
    loop counts toward MAX_CONSECUTIVE_ERRORS and eventually declares
    the elector broken over a peer's formatting, ADVICE r5). Naive
    timestamps normalize to UTC: a live one is respected, a stale one
    is expired."""
    import datetime

    live = (datetime.datetime.now(datetime.timezone.utc)
            .replace(tzinfo=None).isoformat())  # naive "now", UTC wall
    assert LeaderElector._expired(
        {"renewTime": live, "leaseDurationSeconds": 3600}) is False
    # client-go's RFC3339 'Z' suffix: Python 3.10 fromisoformat
    # rejects it, and "unparseable = expired" would steal a LIVE
    # Go-held lease every tick. A live Z-stamped lease must be live.
    assert LeaderElector._expired(
        {"renewTime": live + "Z", "leaseDurationSeconds": 3600}) is False
    assert LeaderElector._expired(
        {"renewTime": "2020-01-01T00:00:00Z",
         "leaseDurationSeconds": 15}) is True
    assert LeaderElector._expired(
        {"renewTime": "2020-01-01T00:00:00",
         "leaseDurationSeconds": 15}) is True
    # Garbage stays "expired", never an exception.
    assert LeaderElector._expired({"renewTime": 12345}) is True
    assert LeaderElector._expired({"renewTime": "not-a-time"}) is True


def test_lease_protocol_over_http_client():
    """The Lease kind rides the production wire: coordination.k8s.io
    path mapping, optimistic-concurrency renewal, takeover."""
    with HttpFakeApiServer(token="t") as srv:
        a = LeaderElector(HttpApiClient(srv.url, token="t"),
                          identity="a", lease_seconds=1)
        b = LeaderElector(HttpApiClient(srv.url, token="t"),
                          identity="b", lease_seconds=1)
        assert a._tick() is True
        assert b._tick() is False
        time.sleep(1.1)
        assert b._tick() is True
        lease = srv.fake.get("Lease", "default", "tpujob-operator")
        assert lease["spec"]["holderIdentity"] == "b"
