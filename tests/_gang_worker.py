# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Subprocess body for the multi-process gang tests (test_multiprocess).

Runs the PRODUCTION bootstrap: the operator-injected env
(KFT_COORDINATOR_ADDRESS / KFT_NUM_PROCESSES / KFT_PROCESS_ID) through
``training.launcher.initialize_distributed`` — then a real sharded
train step over the GLOBAL mesh, with each host feeding only its own
rows (``jax.make_array_from_process_local_data``). Prints one line the
parent asserts on.

Modes (KFT_GANG_MODE):
- ``resnet`` (default): flat data=4 mesh, 2 procs × 2 local devices —
  the basic cross-process gradient all-reduce.
- ``bert_dcn``: the BASELINE multi-host BERT row — hierarchical
  (dcn_data=2, data=4) mesh over 2 procs × 4 local devices, where the
  ``dcn_data`` axis lies exactly on the process boundary, so the
  cross-slice gradient reduction truly crosses the jax.distributed
  transport (Gloo over loopback — the DCN stand-in), not a
  single-process emulation. Deliberately no fsdp in this layout: see
  the SPMD-quality note in ``__graft_entry__._dryrun_bert_dcn``.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root (no install needed)
os.environ["JAX_PLATFORMS"] = "cpu"
LOCAL_DEVICES = int(os.environ.get("KFT_LOCAL_DEVICES", "2"))
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count="
        f"{LOCAL_DEVICES}").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from kubeflow_tpu.parallel.mesh import (  # noqa: E402
    MeshSpec,
    batch_sharding,
    build_mesh,
)
from kubeflow_tpu.training.launcher import (  # noqa: E402
    initialize_distributed,
)
from kubeflow_tpu.training.data import host_shard_range  # noqa: E402


def _feed(mesh, host_batch):
    sharding = batch_sharding(mesh)
    return {
        k: jax.make_array_from_process_local_data(sharding, v)
        for k, v in host_batch.items()
    }


def run_resnet() -> float:
    from kubeflow_tpu.models.resnet import resnet18ish
    from kubeflow_tpu.training.train import (
        create_train_state,
        make_train_step,
        place_state,
    )

    mesh = build_mesh(MeshSpec(data=4))
    model = resnet18ish(num_classes=10)
    state = create_train_state(
        model, optax.sgd(0.1), jax.random.PRNGKey(0),
        jnp.zeros((1, 32, 32, 3), jnp.bfloat16))
    state = place_state(mesh, state)

    global_batch = 8
    rows = host_shard_range(global_batch)
    rng = np.random.RandomState(0)  # same stream on both hosts
    images = rng.randn(global_batch, 32, 32, 3).astype(np.float32)
    labels = rng.randint(0, 10, global_batch)
    batch = _feed(mesh, {
        "inputs": images[rows.start:rows.stop].astype(jnp.bfloat16),
        "labels": labels[rows.start:rows.stop],
    })

    step = make_train_step(mesh)
    for _ in range(2):
        state, metrics = step(state, batch)
    return float(metrics["loss"])


def run_bert_dcn() -> float:
    """BASELINE row 3's code path: BERT MLM on the hierarchical
    dcn_data × data mesh with dcn_data spanning the two processes
    (SURVEY §2.5 topology row; no fsdp — see the SPMD-quality note in
    ``__graft_entry__._dryrun_bert_dcn``)."""
    from kubeflow_tpu.models.registry import get_model
    from kubeflow_tpu.training.lm import (
        create_lm_state,
        make_lm_train_step,
    )

    mesh = build_mesh(MeshSpec(dcn_data=2, data=4))
    # The whole point: the outermost (cross-slice) axis must lie on
    # the process boundary, so its gradient reduction crosses the
    # jax.distributed transport.
    dev = np.asarray(mesh.devices)
    slice0 = {d.process_index for d in dev[0].ravel()}
    slice1 = {d.process_index for d in dev[1].ravel()}
    assert slice0 == {0} and slice1 == {1}, (slice0, slice1)

    model = get_model("bert-test").make()
    global_batch, seq_len, vocab = 16, 16, 512
    rng = np.random.RandomState(7)  # same stream on both hosts
    ids = rng.randint(5, vocab, (global_batch, seq_len))
    mask = rng.random_sample((global_batch, seq_len)) < 0.3
    # Global-shaped sample for tracing/init (values identical on both
    # hosts; only shapes matter to the jitted init); this host's rows
    # of the SAME dict feed the step.
    sample = {
        "input_ids": np.where(mask, 3, ids).astype(np.int32),
        "type_ids": np.zeros_like(ids).astype(np.int32),
        "valid": np.ones_like(ids).astype(np.int32),
        "mlm_labels": ids.astype(np.int32),
        "mlm_weights": mask.astype(np.int32),
    }
    host = host_shard_range(global_batch)
    host_batch = {k: v[host.start:host.stop] for k, v in sample.items()}
    state, shardings = create_lm_state(
        model, optax.adamw(1e-3), jax.random.PRNGKey(0), sample, mesh)
    step = make_lm_train_step(mesh, shardings, objective="mlm",
                              donate=False)
    batch = _feed(mesh, host_batch)
    for _ in range(2):
        state, metrics = step(state, batch)
    assert int(jax.device_get(state.step)) == 2
    return float(metrics["loss"])


def run_bert_dcn_megascale() -> float:
    """The multi-slice operator contract end-to-end: 2 slices × 2
    hosts (4 real processes), where the pods' ONLY description of the
    topology is the injected env — MEGASCALE_NUM_SLICES supplies the
    ``dcn_data`` axis inside ``build_mesh`` (the program itself names
    just its within-slice layout), and slice-major KFT_PROCESS_IDs put
    the slice boundary exactly between process pairs. This is the
    test bed VERDICT r4 asked for: >1 host per slice × >1 slice across
    real process boundaries."""
    from kubeflow_tpu.models.registry import get_model
    from kubeflow_tpu.training.launcher import slice_config
    from kubeflow_tpu.training.lm import (
        create_lm_state,
        make_lm_train_step,
    )

    slices = slice_config()
    assert slices is not None and slices["num_slices"] == 2, slices
    assert slices["slice_id"] == jax.process_index() // 2, slices

    # The program describes only the within-slice layout; dcn_data
    # arrives from the operator env.
    mesh = build_mesh(MeshSpec(data=4))
    assert mesh.shape["dcn_data"] == 2, dict(mesh.shape)
    # The cross-slice axis must lie on the slice (= process-pair)
    # boundary: row s of the dcn axis is slice s's processes.
    dev = np.asarray(mesh.devices)
    slice0 = {d.process_index for d in dev[0].ravel()}
    slice1 = {d.process_index for d in dev[1].ravel()}
    assert slice0 == {0, 1} and slice1 == {2, 3}, (slice0, slice1)

    model = get_model("bert-test").make()
    global_batch, seq_len, vocab = 16, 16, 512
    rng = np.random.RandomState(11)  # same stream on all hosts
    ids = rng.randint(5, vocab, (global_batch, seq_len))
    mask = rng.random_sample((global_batch, seq_len)) < 0.3
    sample = {
        "input_ids": np.where(mask, 3, ids).astype(np.int32),
        "type_ids": np.zeros_like(ids).astype(np.int32),
        "valid": np.ones_like(ids).astype(np.int32),
        "mlm_labels": ids.astype(np.int32),
        "mlm_weights": mask.astype(np.int32),
    }
    host = host_shard_range(global_batch)
    host_batch = {k: v[host.start:host.stop] for k, v in sample.items()}
    state, shardings = create_lm_state(
        model, optax.adamw(1e-3), jax.random.PRNGKey(0), sample, mesh)
    step = make_lm_train_step(mesh, shardings, objective="mlm",
                              donate=False)
    batch = _feed(mesh, host_batch)
    for _ in range(2):
        state, metrics = step(state, batch)
    assert int(jax.device_get(state.step)) == 2
    return float(metrics["loss"])


def run_drain():
    """Collective preemption drain: the parent SIGTERMs ONE process
    mid-run; the per-step drain-flag allgather (loop.py
    drain_sync_steps) must make BOTH processes drain at the SAME step
    and complete the collective Orbax save — the multi-host case where
    a unilateral drain would deadlock the gang in the train-step psum
    (r5 review finding)."""
    import itertools

    from kubeflow_tpu.models.registry import get_model
    from kubeflow_tpu.training.checkpoint import CheckpointConfig
    from kubeflow_tpu.training.launcher import DRAIN_EXIT_CODE
    from kubeflow_tpu.training.lm import (
        create_lm_state,
        make_lm_train_step,
    )
    from kubeflow_tpu.training.loop import (
        DrainInterrupt,
        LoopConfig,
        fit,
    )

    mesh = build_mesh(MeshSpec(data=4))
    model = get_model("llama-test").make()
    global_batch = 8
    rng = np.random.RandomState(5)
    ids = rng.randint(0, 512, (global_batch, 16)).astype(np.int32)
    host = host_shard_range(global_batch)
    batch = _feed(mesh, {"input_ids": ids[host.start:host.stop]})
    state, shardings = create_lm_state(
        model, optax.adamw(1e-3), jax.random.PRNGKey(0),
        {"input_ids": ids}, mesh)
    step = make_lm_train_step(mesh, shardings, objective="causal",
                              donate=False)
    config = LoopConfig(
        total_steps=100000, log_every=1,
        checkpoint=CheckpointConfig(
            directory=os.environ["KFT_DRAIN_CKPT"],
            save_interval_steps=50000),
        metrics_path=os.environ.get("KFT_DRAIN_METRICS"),
        drain_sync_steps=2)
    try:
        fit(state, step, itertools.repeat(batch), config)
    except DrainInterrupt as drain:
        print(f"GANG_DRAINED process={jax.process_index()} "
              f"step={drain.step} ckpt={drain.checkpointed}", flush=True)
        sys.exit(DRAIN_EXIT_CODE)
    raise AssertionError("ran 100000 steps without draining")


MODES = {"resnet": run_resnet, "bert_dcn": run_bert_dcn,
         "bert_dcn_megascale": run_bert_dcn_megascale,
         "drain": run_drain}


def main() -> int:
    mode = os.environ.get("KFT_GANG_MODE", "resnet")
    n_proc = int(os.environ["KFT_NUM_PROCESSES"])
    assert initialize_distributed(), "env must describe a multi-process gang"
    assert jax.process_count() == n_proc
    assert len(jax.devices()) == n_proc * LOCAL_DEVICES
    loss = MODES[mode]()
    print(f"GANG_OK mode={mode} process={jax.process_index()} "
          f"devices={len(jax.devices())} loss={loss:.6f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
