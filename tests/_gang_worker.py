"""Subprocess body for the multi-process gang test (test_multiprocess).

Runs the PRODUCTION bootstrap: the operator-injected env
(KFT_COORDINATOR_ADDRESS / KFT_NUM_PROCESSES / KFT_PROCESS_ID) through
``training.launcher.initialize_distributed`` — then a real sharded
train step over the GLOBAL mesh (2 processes × 2 local CPU devices),
with each host feeding only its own rows
(``jax.make_array_from_process_local_data``). Prints one line the
parent asserts on.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root (no install needed)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=2").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from kubeflow_tpu.models.resnet import resnet18ish  # noqa: E402
from kubeflow_tpu.parallel.mesh import (  # noqa: E402
    MeshSpec,
    batch_sharding,
    build_mesh,
)
from kubeflow_tpu.training.launcher import (  # noqa: E402
    initialize_distributed,
)
from kubeflow_tpu.training.data import host_shard_range  # noqa: E402
from kubeflow_tpu.training.train import (  # noqa: E402
    create_train_state,
    make_train_step,
    place_state,
)


def main() -> int:
    assert initialize_distributed(), "env must describe a 2-process gang"
    assert jax.process_count() == 2
    assert len(jax.devices()) == 4  # 2 hosts × 2 local devices

    mesh = build_mesh(MeshSpec(data=4))
    model = resnet18ish(num_classes=10)
    state = create_train_state(
        model, optax.sgd(0.1), jax.random.PRNGKey(0),
        jnp.zeros((1, 32, 32, 3), jnp.bfloat16))
    state = place_state(mesh, state)

    global_batch = 8
    rows = host_shard_range(global_batch)
    rng = np.random.RandomState(0)  # same stream on both hosts
    images = rng.randn(global_batch, 32, 32, 3).astype(np.float32)
    labels = rng.randint(0, 10, global_batch)
    sharding = batch_sharding(mesh)
    batch = {
        "inputs": jax.make_array_from_process_local_data(
            sharding, images[rows.start:rows.stop].astype(jnp.bfloat16)),
        "labels": jax.make_array_from_process_local_data(
            sharding, labels[rows.start:rows.stop]),
    }

    step = make_train_step(mesh)
    for _ in range(2):
        state, metrics = step(state, batch)
    loss = float(metrics["loss"])
    print(f"GANG_OK process={jax.process_index()} "
          f"devices={len(jax.devices())} loss={loss:.6f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
