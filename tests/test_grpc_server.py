# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Native gRPC PredictionService tests: a real grpcio channel drives
Predict, Classify and GetModelMetadata against the running server
(serving/grpc_server.py), plus wire-codec roundtrips for the new
messages. Reference contract: gRPC PredictionService on :9000
(kubeflow/tf-serving/tf-serving.libsonnet:106-111; client
components/k8s-model-server/inception-client/label.py:40-56)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubeflow_tpu.serving import wire
from kubeflow_tpu.serving.export import export_model
from kubeflow_tpu.serving.manager import ModelManager
from kubeflow_tpu.serving.signature import (
    ModelMetadata,
    Signature,
    TensorSpec,
)

grpc = pytest.importorskip("grpc")

LABELS = [f"label_{i}" for i in range(10)]


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    """Exported classify model + manager + running gRPC server on an
    OS-assigned port. Yields (address, manager)."""
    from kubeflow_tpu.serving.grpc_server import make_server

    base = tmp_path_factory.mktemp("grpc-models") / "classnet"
    from kubeflow_tpu.models.resnet import resnet18ish

    model = resnet18ish(num_classes=10)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 32, 32, 3), jnp.bfloat16),
                           train=False)
    metadata = ModelMetadata(
        model_name="classnet",
        registry_name="resnet-test",
        model_kwargs={"num_classes": 10},
        classes=LABELS,
        signatures={"serving_default": Signature(
            method="classify",
            inputs={"images": TensorSpec("float32", (-1, 32, 32, 3))},
            outputs={"classes": TensorSpec("int32", (-1, 5)),
                     "scores": TensorSpec("float32", (-1, 5))},
        )},
    )
    export_model(str(base), 1, metadata, variables)
    manager = ModelManager()
    manager.add_model("classnet", str(base), max_batch=8)
    server, port = make_server(manager, 0)
    server.start()
    yield f"127.0.0.1:{port}", manager
    server.stop(grace=None)
    manager.stop()


def _call(address, method, request):
    with grpc.insecure_channel(address) as channel:
        return channel.unary_unary(
            f"/tensorflow.serving.PredictionService/{method}"
        )(request, timeout=30.0)


def test_grpc_predict(served):
    """Predict executes the named signature (TF-Serving semantics):
    classnet's serving_default is classify-method, so Predict returns
    the signature's declared outputs (classes/scores)."""
    address, _ = served
    x = np.random.RandomState(0).rand(2, 32, 32, 3).astype(np.float32)
    request = wire.encode_predict_request("classnet", {"images": x})
    spec, outputs = wire.decode_predict_response(
        _call(address, "Predict", request))
    assert spec["name"] == "classnet"
    assert spec["version"] == 1
    assert outputs["classes"].shape == (2, 5)
    assert outputs["scores"].shape == (2, 5)


def test_grpc_predict_matches_direct_run(served):
    address, manager = served
    x = np.random.RandomState(7).rand(1, 32, 32, 3).astype(np.float32)
    request = wire.encode_predict_request("classnet", {"images": x})
    _, outputs = wire.decode_predict_response(
        _call(address, "Predict", request))
    direct = manager.get_model("classnet").get().run({"images": x})
    np.testing.assert_allclose(outputs["scores"], direct["scores"],
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(outputs["classes"],
                                  direct["classes"])


def test_grpc_classify_labels_and_scores(served):
    address, _ = served
    rng = np.random.RandomState(1)
    examples = [
        {"images": rng.rand(32 * 32 * 3).astype(np.float32)}
        for _ in range(3)
    ]
    request = wire.encode_classification_request("classnet", examples)
    spec, classifications = wire.decode_classification_response(
        _call(address, "Classify", request))
    assert spec["name"] == "classnet"
    assert len(classifications) == 3
    for row in classifications:
        assert len(row) == 5  # top_k
        labels = [label for label, _ in row]
        assert set(labels) <= set(LABELS)
        scores = [score for _, score in row]
        assert all(np.diff(scores) <= 1e-6), "scores sorted desc"


def test_grpc_get_model_metadata(served):
    """The reference proxy's bootstrap call (server.py:121-160):
    metadata_field=signature_def → SignatureDefMap in an Any."""
    address, _ = served
    request = wire.encode_get_model_metadata_request("classnet")
    spec, signatures = wire.decode_get_model_metadata_response(
        _call(address, "GetModelMetadata", request))
    assert spec["name"] == "classnet"
    sig = signatures["serving_default"]
    assert sig["method_name"] == "tensorflow/serving/classify"
    assert sig["inputs"]["images"]["dtype"] == wire.DT_FLOAT
    assert sig["inputs"]["images"]["shape"] == [-1, 32, 32, 3]
    assert set(sig["outputs"]) == {"classes", "scores"}


def test_grpc_error_codes(served):
    address, _ = served
    # Unknown model → NOT_FOUND.
    request = wire.encode_predict_request(
        "nope", {"images": np.zeros((1, 32, 32, 3), np.float32)})
    with pytest.raises(grpc.RpcError) as err:
        _call(address, "Predict", request)
    assert err.value.code() == grpc.StatusCode.NOT_FOUND
    # Bad input shape → INVALID_ARGUMENT.
    request = wire.encode_predict_request(
        "classnet", {"images": np.zeros((1, 16, 16, 3), np.float32)})
    with pytest.raises(grpc.RpcError) as err:
        _call(address, "Predict", request)
    assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    # Wrong-size example rows → INVALID_ARGUMENT.
    request = wire.encode_classification_request(
        "classnet", [{"images": np.zeros(7, np.float32)}])
    with pytest.raises(grpc.RpcError) as err:
        _call(address, "Classify", request)
    assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    # Unsupported metadata field → INVALID_ARGUMENT.
    request = wire.encode_get_model_metadata_request(
        "classnet", metadata_fields=("something_else",))
    with pytest.raises(grpc.RpcError) as err:
        _call(address, "GetModelMetadata", request)
    assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_client_helpers_against_live_server(served):
    """serving/client.py's native-gRPC path (label.py parity)."""
    from kubeflow_tpu.serving import client

    address, _ = served
    x = np.random.RandomState(2).rand(1, 32, 32, 3).astype(np.float32)
    outputs = client.grpc_predict(address, "classnet", {"images": x})
    assert outputs["scores"].shape == (1, 5)  # signature's outputs
    rows = client.grpc_classify(
        address, "classnet",
        [{"images": x.reshape(-1)}])
    assert len(rows) == 1 and len(rows[0]) == 5
    signatures = client.grpc_get_metadata(address, "classnet")
    assert "serving_default" in signatures


def test_output_filter_on_grpc(served):
    address, _ = served
    x = np.zeros((1, 32, 32, 3), np.float32)
    request = (wire.encode_predict_request("classnet", {"images": x})
               + wire._field_bytes(3, b"scores"))  # output_filter
    _, outputs = wire.decode_predict_response(
        _call(address, "Predict", request))
    assert set(outputs) == {"scores"}


# --- wire codec roundtrips for the new messages ----------------------------


def test_example_roundtrip():
    ex = {
        "floats": np.arange(6, dtype=np.float32),
        "ints": np.array([-3, 0, 9], np.int64),
        "raw": b"jpeg-bytes",
    }
    decoded = wire.decode_example(wire.encode_example(ex))
    np.testing.assert_array_equal(decoded["floats"], ex["floats"])
    np.testing.assert_array_equal(decoded["ints"], ex["ints"])
    assert decoded["raw"] == [b"jpeg-bytes"]


def test_classification_request_roundtrip():
    examples = [{"x": np.ones(4, np.float32)},
                {"x": np.zeros(4, np.float32)}]
    buf = wire.encode_classification_request(
        "m", examples, signature_name="sig", version=3)
    spec, decoded = wire.decode_classification_request(buf)
    assert spec == {"name": "m", "version": 3, "signature_name": "sig"}
    assert len(decoded) == 2
    np.testing.assert_array_equal(decoded[0]["x"], examples[0]["x"])


def test_classification_response_roundtrip():
    rows = [[("cat", 0.9), ("dog", 0.1)], [("dog", 1.0)]]
    spec, decoded = wire.decode_classification_response(
        wire.encode_classification_response(rows, "m", 2))
    assert spec["name"] == "m" and spec["version"] == 2
    assert [[(label, round(score, 6)) for label, score in row]
            for row in decoded] == rows


def test_get_model_metadata_roundtrip():
    signatures = {
        "serving_default": {
            "method": "predict",
            "inputs": {"images": ("float32", (-1, 8, 8, 3))},
            "outputs": {"logits": ("float32", (-1, 10))},
        },
    }
    req = wire.encode_get_model_metadata_request("m", version=5)
    spec, fields = wire.decode_get_model_metadata_request(req)
    assert spec["name"] == "m" and spec["version"] == 5
    assert fields == ["signature_def"]
    resp = wire.encode_get_model_metadata_response("m", 5, signatures)
    spec, decoded = wire.decode_get_model_metadata_response(resp)
    assert spec["version"] == 5
    sig = decoded["serving_default"]
    assert sig["method_name"] == "tensorflow/serving/predict"
    assert sig["inputs"]["images"]["shape"] == [-1, 8, 8, 3]
    assert wire.DT_TO_STR[sig["outputs"]["logits"]["dtype"]] == "float32"


def test_signature_def_map_cross_validates_with_protobuf():
    """If the real protobuf runtime can parse our Any + map encoding,
    the hand-rolled bytes are wire-correct (structure-level check —
    the tensorflow_serving protos themselves aren't compiled here)."""
    pb = pytest.importorskip("google.protobuf")
    from google.protobuf import any_pb2  # noqa: F401

    buf = wire.encode_get_model_metadata_response(
        "m", 1, {"s": {"method": "classify",
                       "inputs": {"x": ("float32", (-1, 2))},
                       "outputs": {"y": ("int32", (-1, 5))}}})
    # Parse the response's metadata map entry value as a real Any.
    entries = [(f, wt, v) for f, wt, v in wire._iter_fields(buf)
               if f == 2 and wt == wire._LEN]
    assert len(entries) == 1
    key = value = None
    for f2, wt2, v2 in wire._iter_fields(entries[0][2]):
        if f2 == 1:
            key = bytes(v2).decode()
        elif f2 == 2:
            value = bytes(v2)
    assert key == "signature_def"
    any_msg = any_pb2.Any()
    any_msg.ParseFromString(value)
    assert any_msg.type_url == wire.SIGNATURE_DEF_TYPE_URL
    assert any_msg.value  # SignatureDefMap payload present
