# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""CI plane: junit emission, workflow manifests, E2E drivers in fake
mode (the full presubmit DAG exercised hermetically)."""

import json
import xml.etree.ElementTree as ET

import pytest

from kubeflow_tpu.citests import deploy as ci_deploy
from kubeflow_tpu.citests import tpujob as ci_tpujob
from kubeflow_tpu.params.registry import get_prototype
from kubeflow_tpu.utils import junit


def test_junit_xml_shape(tmp_path):
    cases = [
        junit.run_case("passes", lambda: None),
        junit.run_case("fails", lambda: (_ for _ in ()).throw(
            AssertionError("nope"))),
        junit.run_case("errors", lambda: (_ for _ in ()).throw(
            RuntimeError("boom"))),
    ]
    path = junit.write_report(str(tmp_path / "junit.xml"), "suite", cases)
    root = ET.parse(path).getroot()
    assert root.tag == "testsuite"
    assert root.get("tests") == "3"
    assert root.get("failures") == "1"
    assert root.get("errors") == "1"
    kinds = {c.get("name"): [e.tag for e in c] for c in root}
    assert kinds["passes"] == []
    assert kinds["fails"] == ["failure"]
    assert kinds["errors"] == ["error"]


def test_e2e_workflow_manifest():
    objs = get_prototype("ci-e2e").build({"name": "pr-123"})
    wf = objs[0]
    assert wf["kind"] == "Workflow"
    assert wf["spec"]["entrypoint"] == "e2e"
    assert wf["spec"]["onExit"] == "exit-handler"
    names = {t["name"] for t in wf["spec"]["templates"]}
    for step in ("checkout", "unit-test", "deploy-test", "tpujob-test",
                 "serving-test", "leader-failover-test",
                 "elastic-kill-test", "serving-chaos",
                 "serving-tenancy", "spec-decode", "fleet-sim",
                 "kv-tier", "teardown", "copy-artifacts", "e2e"):
        assert step in names, step
    dag = next(t for t in wf["spec"]["templates"] if t["name"] == "e2e")
    deps = {t["name"]: t.get("dependencies", [])
            for t in dag["dag"]["tasks"]}
    assert deps["tpujob-test"] == ["deploy-test"]
    assert deps["deploy-test"] == ["checkout"]
    # Hermetic citests ride the checkout alone (no cluster deploy).
    assert deps["leader-failover-test"] == ["checkout"]
    assert deps["elastic-kill-test"] == ["checkout"]
    assert deps["spec-decode"] == ["checkout"]
    # Fleet-sim gate (ISSUE 19): hermetic — stub fleet + pure sim.
    assert deps["fleet-sim"] == ["checkout"]
    spec = next(t for t in wf["spec"]["templates"]
                if t["name"] == "spec-decode")
    assert "--speculative" in spec["container"]["command"]
    sim = next(t for t in wf["spec"]["templates"]
               if t["name"] == "fleet-sim")
    assert "--sim" in sim["container"]["command"]
    # Tiered-KV gate (ISSUE 20): hermetic — tiny model, tiny pool.
    assert deps["kv-tier"] == ["checkout"]
    tier = next(t for t in wf["spec"]["templates"]
                if t["name"] == "kv-tier")
    assert "--prefix" in tier["container"]["command"]
    assert "--working-set-multiple" in tier["container"]["command"]
    failover = next(t for t in wf["spec"]["templates"]
                    if t["name"] == "leader-failover-test")
    assert "kubeflow_tpu.citests.leader_failover" in \
        failover["container"]["command"]
    elastic = next(t for t in wf["spec"]["templates"]
                   if t["name"] == "elastic-kill-test")
    assert "kubeflow_tpu.citests.elastic" in \
        elastic["container"]["command"]


def test_release_workflow_manifest():
    objs = get_prototype("ci-release").build(
        {"name": "rel-1", "version_tag": "v0.2.0"})
    wf = objs[0]
    names = {t["name"] for t in wf["spec"]["templates"]}
    assert "build-model-server" in names
    assert "build-jax-notebook" in names
    build = next(t for t in wf["spec"]["templates"]
                 if t["name"] == "build-model-server")
    assert build["sidecars"][0]["securityContext"]["privileged"]
    assert "v0.2.0" in " ".join(build["container"]["command"])
    # zero-CUDA invariant: no gpu image family anywhere
    assert not any("gpu" in n for n in names)


def test_deploy_and_tpujob_fake_e2e(tmp_path):
    junit_deploy = tmp_path / "junit_deploy.xml"
    rc = ci_deploy.main(["setup", "--fake", "--namespace", "e2e-ns",
                         "--junit_path", str(junit_deploy)])
    assert rc == 0
    root = ET.parse(junit_deploy).getroot()
    assert root.get("failures") == "0" and root.get("errors") == "0"

    junit_job = tmp_path / "junit_tpujob.xml"
    rc = ci_tpujob.main(["--fake", "--namespace", "e2e-ns",
                         "--junit_path", str(junit_job)])
    assert rc == 0
    root = ET.parse(junit_job).getroot()
    assert root.get("failures") == "0" and root.get("errors") == "0"


def test_leader_failover_fake_e2e(tmp_path):
    """The leader-failover-mid-restart citest green in the CI DAG
    (r12 acceptance): the same driver the DAG step runs, end to end."""
    from kubeflow_tpu.citests import leader_failover as ci_failover

    junit_path = tmp_path / "junit_leader_failover.xml"
    rc = ci_failover.main(["--fake", "--junit_path", str(junit_path)])
    assert rc == 0
    root = ET.parse(junit_path).getroot()
    assert root.get("failures") == "0" and root.get("errors") == "0"


def test_elastic_control_plane_fake_e2e(tmp_path):
    """The elastic-kill citest's control-plane half (resize instead
    of restart, zero duplicate pods) — fast, jax-free, tier-1."""
    from kubeflow_tpu.citests import elastic as ci_elastic

    junit_path = tmp_path / "junit_elastic_cp.xml"
    rc = ci_elastic.main(["--fake", "--skip_training",
                          "--junit_path", str(junit_path)])
    assert rc == 0
    root = ET.parse(junit_path).getroot()
    assert root.get("failures") == "0" and root.get("errors") == "0"


@pytest.mark.slow
def test_elastic_kill_fake_e2e(tmp_path):
    """The full elastic-kill citest green as the CI DAG runs it (r16
    acceptance): kill 1 of 4 mid-run, resize, resume from the
    continuous checkpoint on 3 hosts, same seeded loss curve with
    < 2 steps lost."""
    from kubeflow_tpu.citests import elastic as ci_elastic

    junit_path = tmp_path / "junit_elastic.xml"
    rc = ci_elastic.main(["--fake", "--junit_path", str(junit_path)])
    assert rc == 0
    root = ET.parse(junit_path).getroot()
    assert root.get("failures") == "0" and root.get("errors") == "0"


@pytest.mark.slow
def test_serving_fake_e2e(tmp_path):
    from kubeflow_tpu.citests import serving as ci_serving

    junit_path = tmp_path / "junit_serving.xml"
    rc = ci_serving.main(["--fake", "--junit_path", str(junit_path)])
    assert rc == 0


def test_collect_obs_sweeps_tier_stats(tmp_path, monkeypatch):
    """The kv-tier bench's tier-stats calibration dump travels with
    the CI artifacts (ISSUE 20): collect-obs sweeps
    kv_tier_stats.json from the $KFT_OBS_DIR drop-box like every
    other obs JSON, so the fleet sim's prefix-hit service class can
    calibrate from a real run's per-tier hit metrics."""
    from kubeflow_tpu.citests import artifacts as ci_artifacts

    obs = tmp_path / "obs-drop"
    obs.mkdir()
    doc = {"prefix_cache": {"hits": 36, "misses": 0, "hit_rate": 1.0},
           "kv_tier": {"host": {"readopted_blocks": 108},
                       "fetch_hits": 0}}
    (obs / "kv_tier_stats.json").write_text(json.dumps(doc))
    monkeypatch.setenv("KFT_OBS_DIR", str(obs))
    monkeypatch.setenv("KFT_ARTIFACTS_DIR", str(tmp_path / "art"))
    copied = ci_artifacts.collect_obs()
    swept = next(p for p in copied if p.name == "kv_tier_stats.json")
    assert json.loads(swept.read_text()) == doc


def test_dashboard_fake_e2e(tmp_path):
    from kubeflow_tpu.citests import dashboard as ci_dashboard

    junit_path = tmp_path / "junit_dashboard.xml"
    rc = ci_dashboard.main(["--fake", "--junit_path", str(junit_path)])
    assert rc == 0
    assert b"dashboard-ui" in junit_path.read_bytes()
