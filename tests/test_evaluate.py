# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Evaluation harness: exact aggregates, ppl sanity, lora variables."""

import os

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kubeflow_tpu.models.llama import llama_test
from kubeflow_tpu.training.evaluate import evaluate_lm
from kubeflow_tpu.training.finetune import (
    create_lora_state,
    make_lora_train_step,
)


def batches_of(key, n, b=4, l=16, vocab=512):
    for i in range(n):
        yield {"input_ids": jax.random.randint(
            jax.random.fold_in(key, i), (b, l), 0, vocab)}


def test_evaluate_untrained_ppl_near_vocab():
    model = llama_test()
    ids = next(batches_of(jax.random.PRNGKey(0), 1))["input_ids"]
    params = nn.meta.unbox(model.init(jax.random.PRNGKey(1), ids)["params"])
    out = evaluate_lm(model.apply, {"params": params},
                      batches_of(jax.random.PRNGKey(2), 3))
    # Untrained model on uniform tokens: CE ≈ ln(512) → ppl ≈ vocab.
    assert 256 < out["perplexity"] < 1024, out
    assert out["tokens"] == 3 * 4 * 15  # next-token targets: l-1
    assert 0.0 <= out["accuracy"] <= 0.05


def test_evaluate_improves_after_lora_finetune():
    model = llama_test(lora_rank=4)
    batch = next(batches_of(jax.random.PRNGKey(0), 1))
    state, _ = create_lora_state(
        model, optax.adamw(1e-2), jax.random.PRNGKey(1), batch)
    variables0 = {"params": state.base_params, "lora": state.lora}
    eval_stream = lambda: iter([batch])  # eval on the training batch
    before = evaluate_lm(model.apply, variables0, eval_stream())

    step = make_lora_train_step(None, None, donate=False)
    for _ in range(6):
        state, _ = step(state, batch)
    after = evaluate_lm(
        model.apply, {"params": state.base_params, "lora": state.lora},
        eval_stream())
    assert after["loss"] < before["loss"]


def test_evaluate_empty_stream_raises():
    model = llama_test()
    ids = jnp.zeros((1, 8), jnp.int32)
    params = nn.meta.unbox(model.init(jax.random.PRNGKey(1), ids)["params"])
    with pytest.raises(ValueError, match="no weighted tokens"):
        evaluate_lm(model.apply, {"params": params}, iter([]))


def test_evaluate_max_batches_and_exactness():
    """Aggregates must be token-weighted over the whole stream, not
    mean-of-batch-means."""
    model = llama_test()
    ids = jnp.zeros((1, 8), jnp.int32)
    params = nn.meta.unbox(model.init(jax.random.PRNGKey(1), ids)["params"])

    b1 = {"input_ids": jax.random.randint(jax.random.PRNGKey(3), (2, 8), 0, 512)}
    b2 = {"input_ids": jax.random.randint(jax.random.PRNGKey(4), (6, 8), 0, 512)}
    both = evaluate_lm(model.apply, {"params": params}, iter([b1, b2]))
    only1 = evaluate_lm(model.apply, {"params": params}, iter([b1, b2]),
                        max_batches=1)
    assert only1["batches"] == 1.0
    # Exact weighting: combined CE = (ce1*w1 + ce2*w2)/(w1+w2).
    only2 = evaluate_lm(model.apply, {"params": params}, iter([b2]))
    w1, w2 = only1["tokens"], only2["tokens"]
    np.testing.assert_allclose(
        both["loss"],
        (only1["loss"] * w1 + only2["loss"] * w2) / (w1 + w2),
        rtol=1e-6)


def test_evaluate_honors_preshifted_targets():
    """The `targets` batch convention must mean the same thing in
    train and eval (both route through lm_targets)."""
    model = llama_test()
    ids = jax.random.randint(jax.random.PRNGKey(6), (2, 8), 0, 512)
    params = nn.meta.unbox(model.init(jax.random.PRNGKey(1), ids)["params"])

    implicit = evaluate_lm(model.apply, {"params": params},
                           iter([{"input_ids": ids}]))
    explicit = evaluate_lm(model.apply, {"params": params}, iter([{
        "input_ids": ids[:, :-1],
        "targets": ids[:, 1:],
    }]))
    # Same data expressed both ways → identical loss (the explicit
    # form evaluates logits over ids[:-1] against ids[1:], exactly
    # what the implicit shift does).
    np.testing.assert_allclose(implicit["loss"], explicit["loss"],
                               rtol=1e-5)


@pytest.mark.slow
def test_convergence_vision_smoke(tmp_path):
    """The on-chip convergence proof's full path (data gen → shards →
    prefetch → train → eval) on CPU at smoke scale: must beat chance
    clearly on the easy prototype task."""
    import json
    import subprocess
    import sys
    from pathlib import Path

    script = Path(__file__).parent.parent / "scripts" / "convergence_vision.py"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, str(script), "--steps", "40", "--batch", "32",
         "--n_train", "512", "--n_eval", "256", "--lr", "0.05",
         "--data_dir", str(tmp_path), "--min_accuracy", "0.2"],
        env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["eval_accuracy"] >= 0.2  # chance = 0.1
    assert result["eval_examples"] == 256


@pytest.mark.slow
def test_convergence_lm_smoke(tmp_path):
    """The LM convergence proof's full path (Markov shards →
    token_shard_batches → prefetch → causal train → evaluate_lm) on
    CPU at smoke scale: must clearly beat chance (1/64) on the
    p=0.9 Markov language (60 steps measured ≈0.9, the optimum)."""
    import json
    import subprocess
    import sys
    from pathlib import Path

    script = Path(__file__).parent.parent / "scripts" / "convergence_lm.py"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, str(script), "--steps", "60", "--batch", "16",
         "--seq_len", "64", "--n_train", "60000", "--n_eval", "12000",
         "--data_dir", str(tmp_path), "--min_accuracy", "0.5"],
        env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["eval_accuracy"] >= 0.5  # chance = 0.0156
    assert result["eval_perplexity"] < 10.0  # untrained ≈ vocab = 64
