# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Elastic gang training (ISSUE 12): resize through member loss
instead of dying. Schema/builders, the reconciler's coordinated
resize roll (conditions, events, settle timers, zero budget burn,
zero duplicate pods), admission + stall shrink, preemptor
shrink-first, dashboard degradation, and the tier-1 fast e2e over the
HTTP facade under the live watch controller."""

import datetime
import json
import threading
import time

import pytest

from kubeflow_tpu.manifests.tpujob import (
    KIND,
    crd,
    replica_spec,
    termination_policy,
    tpu_job,
)
from kubeflow_tpu.operator import FakeApiServer, Reconciler
from kubeflow_tpu.operator.controller import WatchController
from kubeflow_tpu.operator.http_client import HttpApiClient
from kubeflow_tpu.operator.reconciler import (
    DEADLINE_CONDITION,
    GANG_GENERATION_LABEL,
    JOB_LABEL,
    PREEMPTED_CONDITION,
    RESIZED_CONDITION,
    RESIZING_CONDITION,
    SHRUNK_CONDITION,
    PreemptionPolicy,
    elastic_current_replicas,
    job_elastic_bounds,
)
from kubeflow_tpu.operator.workqueue import ExponentialBackoff
from kubeflow_tpu.training.launcher import DRAIN_EXIT_CODE

from tests._http_apiserver import HttpFakeApiServer


def make_elastic(name, *, workers=4, min_replicas=2, max_replicas=None,
                 deadline=None, priority=0):
    spec = replica_spec(
        "TPU_WORKER", workers, image="img:1",
        tpu_accelerator="tpu-v5-lite-podslice", tpu_topology="1x1",
        chips_per_worker=1)
    job = tpu_job(name, "default", [spec],
                  termination=termination_policy("TPU_WORKER", 0),
                  scheduling_deadline_seconds=deadline,
                  priority=priority,
                  min_replicas=min_replicas,
                  max_replicas=max_replicas)
    job["metadata"]["uid"] = f"uid-{name}"
    return job


def _conds(api, name):
    job = api.get(KIND, "default", name)
    return {c["type"]: c for c in
            job.get("status", {}).get("conditions", [])}


def _run_all(api, name):
    with api.as_kubelet():
        for pod in api._list("Pod", "default", {JOB_LABEL: name}):
            api.set_pod_phase("default", pod["metadata"]["name"],
                              "Running")


def _converge(api, rec, name, *, passes=8):
    """Reconcile + kubelet until the gang settles."""
    for _ in range(passes):
        rec.reconcile(api.get(KIND, "default", name))
        _run_all(api, name)
    return rec.reconcile(api.get(KIND, "default", name))


# -- schema / builders ----------------------------------------------------


def test_crd_carries_elastic_bounds():
    text = json.dumps(crd())
    assert "minReplicas" in text and "maxReplicas" in text


def test_builder_validates_elastic_bounds():
    spec = replica_spec("TPU_WORKER", 4, image="i",
                        tpu_accelerator="a", tpu_topology="2x4")
    job = tpu_job("x", "d", [spec], min_replicas=2, max_replicas=4)
    assert job["spec"]["minReplicas"] == 2
    assert job["spec"]["maxReplicas"] == 4
    rigid = tpu_job("x", "d", [spec])
    assert "minReplicas" not in rigid["spec"]
    with pytest.raises(ValueError):
        tpu_job("x", "d", [spec], min_replicas=5)  # min > replicas
    with pytest.raises(ValueError):
        tpu_job("x", "d", [spec], min_replicas=0)
    with pytest.raises(ValueError):
        tpu_job("x", "d", [spec], max_replicas=4)  # max without min
    with pytest.raises(ValueError):
        tpu_job("x", "d", [spec], min_replicas=2, num_slices=2)


def test_bounds_coercion_degrades_to_rigid():
    job = make_elastic("c")
    assert job_elastic_bounds(job) == (2, 4)
    assert elastic_current_replicas(job) == 4
    # Garbage min → rigid, never a crash or an accidental resize.
    job["spec"]["minReplicas"] = "banana"
    assert job_elastic_bounds(job) is None
    assert elastic_current_replicas(job) is None
    # Incoherent bounds (min > desired) → rigid.
    job["spec"]["minReplicas"] = 9
    assert job_elastic_bounds(job) is None
    # Garbage status.currentReplicas → desired, clamped.
    job["spec"]["minReplicas"] = 2
    job["status"] = {"currentReplicas": "soup"}
    assert elastic_current_replicas(job) == 4
    job["status"] = {"currentReplicas": 99}
    assert elastic_current_replicas(job) == 4  # clamped to max
    job["status"] = {"currentReplicas": 0}
    assert elastic_current_replicas(job) == 2  # clamped to min


def test_prototype_exposes_elastic_params():
    from kubeflow_tpu.params.registry import get_prototype

    objs = get_prototype("tpu-job").build({
        "name": "e", "num_tpu_workers": "4",
        "min_replicas": "2", "max_replicas": "4"})
    job = next(o for o in objs if o["kind"] == "TPUJob")
    assert job["spec"]["minReplicas"] == 2
    assert job["spec"]["maxReplicas"] == 4
    # tpu-lm: elastic requires a checkpoint dir (the resize resumes
    # from the continuous shards — elasticity without recovery is a
    # data-loss trap).
    with pytest.raises(ValueError):
        get_prototype("tpu-lm").build({
            "name": "e2", "num_tpu_workers": "4",
            "min_replicas": "2"})
    objs = get_prototype("tpu-lm").build({
        "name": "e3", "num_tpu_workers": "4", "min_replicas": "2",
        "checkpoint_dir": "/ckpt", "continuous_every": "10"})
    job = next(o for o in objs if o["kind"] == "TPUJob")
    args = job["spec"]["replicaSpecs"][0]["template"]["spec"][
        "containers"][0]["args"]
    assert "--continuous_every=10" in args


# -- reconciler: member-loss resize ---------------------------------------


def test_member_loss_resizes_instead_of_restarting():
    api = FakeApiServer()
    with api.as_kubelet():
        api.create(make_elastic("el"))
    rec = Reconciler(api)
    assert _converge(api, rec, "el") == "Running"
    pods = sorted(p["metadata"]["name"]
                  for p in api.list("Pod", "default", {JOB_LABEL: "el"}))
    api.set_pod_terminated("default", pods[1], DRAIN_EXIT_CODE)

    phase = rec.reconcile(api.get(KIND, "default", "el"))
    assert phase == "Running"
    status = api.get(KIND, "default", "el")["status"]
    assert status["currentReplicas"] == 3
    assert status["restartCount"] == 0
    conds = _conds(api, "el")
    assert conds[RESIZING_CONDITION]["status"] == "True"
    # The roll tore the whole old gang down (env must change on every
    # survivor too).
    assert api.list("Pod", "default", {JOB_LABEL: "el"}) == []
    # The settle timer is armed — the workqueue re-observes without
    # waiting for a relist.
    assert rec.requeue_after is not None

    assert _converge(api, rec, "el") == "Running"
    status = api.get(KIND, "default", "el")["status"]
    conds = _conds(api, "el")
    assert status["restartCount"] == 0
    assert conds[RESIZING_CONDITION]["status"] == "False"
    assert conds[RESIZED_CONDITION]["status"] == "True"
    assert "Restarting" not in conds
    pods = api.list("Pod", "default", {JOB_LABEL: "el"})
    assert len(pods) == 3
    for pod in pods:
        env = {e["name"]: str(e.get("value"))
               for e in pod["spec"]["containers"][0]["env"]}
        assert env["KFT_NUM_PROCESSES"] == "3"
        assert pod["metadata"]["labels"][GANG_GENERATION_LABEL] == "1"
    # Resized Event landed.
    reasons = {e["reason"] for e in api.list("Event", "default")}
    assert RESIZING_CONDITION in reasons
    assert RESIZED_CONDITION in reasons


def test_loss_below_min_takes_the_restart_path():
    """3 of 4 lost with min=2: survivors < min — the elastic contract
    is exhausted, the classic restart machinery owns recovery (at the
    DESIRED size: a restart is a fresh full-size attempt)."""
    api = FakeApiServer()
    with api.as_kubelet():
        api.create(make_elastic("bm", min_replicas=2))
    rec = Reconciler(api)
    assert _converge(api, rec, "bm") == "Running"
    pods = sorted(p["metadata"]["name"]
                  for p in api.list("Pod", "default", {JOB_LABEL: "bm"}))
    for name in pods[1:]:
        api.set_pod_terminated("default", name, DRAIN_EXIT_CODE)
    phase = rec.reconcile(api.get(KIND, "default", "bm"))
    assert phase == "Restarting"
    status = api.get(KIND, "default", "bm")["status"]
    # Drained pods: budget unchanged (the r6 exemption still holds).
    assert status["restartCount"] == 0
    assert _converge(api, rec, "bm") == "Running"
    assert len(api.list("Pod", "default", {JOB_LABEL: "bm"})) == 4


def test_rigid_job_unaffected_by_member_loss_path():
    api = FakeApiServer()
    spec = replica_spec("TPU_WORKER", 4, image="i",
                        tpu_accelerator="a", tpu_topology="1x1",
                        chips_per_worker=1)
    job = tpu_job("rg", "default", [spec],
                  termination=termination_policy("TPU_WORKER", 0))
    job["metadata"]["uid"] = "uid-rg"
    with api.as_kubelet():
        api.create(job)
    rec = Reconciler(api)
    assert _converge(api, rec, "rg") == "Running"
    pods = sorted(p["metadata"]["name"]
                  for p in api.list("Pod", "default", {JOB_LABEL: "rg"}))
    api.set_pod_terminated("default", pods[0], 1)  # genuine crash
    phase = rec.reconcile(api.get(KIND, "default", "rg"))
    assert phase == "Restarting"
    assert api.get(KIND, "default", "rg")["status"]["restartCount"] == 1


def test_chief_loss_resizes_too():
    """Worker 0 (the chief) dying is just another member loss for an
    elastic gang — the roll recreates index 0 with a fresh
    coordinator address."""
    api = FakeApiServer()
    with api.as_kubelet():
        api.create(make_elastic("ch"))
    rec = Reconciler(api)
    assert _converge(api, rec, "ch") == "Running"
    api.set_pod_terminated("default", "ch-tpu-worker-0",
                           DRAIN_EXIT_CODE)
    assert rec.reconcile(api.get(KIND, "default", "ch")) == "Running"
    assert _converge(api, rec, "ch") == "Running"
    status = api.get(KIND, "default", "ch")["status"]
    assert status["currentReplicas"] == 3
    assert status["restartCount"] == 0


def test_deleted_pod_eviction_resizes():
    """A pod OBJECT vanishing from a Running gang (node-level
    eviction) is member loss, not birth: resize, don't re-create at
    the old size."""
    api = FakeApiServer()
    with api.as_kubelet():
        api.create(make_elastic("ev"))
    rec = Reconciler(api)
    assert _converge(api, rec, "ev") == "Running"
    with api.as_kubelet():
        api.delete("Pod", "default", "ev-tpu-worker-3")
    rec.reconcile(api.get(KIND, "default", "ev"))
    assert api.get(KIND, "default", "ev")["status"][
        "currentReplicas"] == 3
    assert _converge(api, rec, "ev") == "Running"
    assert len(api.list("Pod", "default", {JOB_LABEL: "ev"})) == 3


def test_restart_resets_shrunk_gang_to_desired():
    """A full restart (crash, not drain) of a shrunk elastic gang is
    a fresh scheduling attempt at the DESIRED size — counted as a
    grow resize."""
    api = FakeApiServer()
    with api.as_kubelet():
        api.create(make_elastic("gr"))
    rec = Reconciler(api)
    assert _converge(api, rec, "gr") == "Running"
    api.set_pod_terminated("default", "gr-tpu-worker-3",
                           DRAIN_EXIT_CODE)
    rec.reconcile(api.get(KIND, "default", "gr"))  # resize to 3
    assert _converge(api, rec, "gr") == "Running"
    assert rec.resize_counts()["shrink"] == 1
    # Now 2 of the 3 crash at once: survivors (1) < min (2) — the
    # elastic contract is exhausted, the classic whole-slice restart
    # takes over AND resets the gang to its DESIRED size (a restart
    # is a fresh full-size scheduling attempt) — the grow direction.
    api.set_pod_terminated("default", "gr-tpu-worker-1", 1)
    api.set_pod_terminated("default", "gr-tpu-worker-2", 1)
    phase = rec.reconcile(api.get(KIND, "default", "gr"))
    assert phase == "Restarting"
    status = api.get(KIND, "default", "gr")["status"]
    assert status["currentReplicas"] == 4
    assert rec.resize_counts()["grow"] == 1
    assert _converge(api, rec, "gr") == "Running"
    assert len(api.list("Pod", "default", {JOB_LABEL: "gr"})) == 4
    # A genuine crash burns budget as ever; the resize path never did.
    assert api.get(KIND, "default", "gr")["status"]["restartCount"] == 1


# -- admission + stall shrink ---------------------------------------------


def _age_pending(api, name, seconds):
    past = (datetime.datetime.now(datetime.timezone.utc)
            - datetime.timedelta(seconds=seconds)).isoformat()

    def mutate(obj):
        for cond in obj.get("status", {}).get("conditions", []):
            if cond["type"] == "Pending":
                cond["lastTransitionTime"] = past

    with api.as_kubelet():
        api.patch(KIND, "default", name, mutate)


def test_admission_shrink_steps_toward_min():
    """A Pending elastic gang burning its scheduling deadline shrinks
    one worker at the eligibility fraction instead of holding out for
    the full size until the deadline kills it."""
    api = FakeApiServer()
    with api.as_kubelet():
        api.create(make_elastic("ad", deadline=100))
    rec = Reconciler(api)
    rec.reconcile(api.get(KIND, "default", "ad"))  # 4 pods Pending
    rec.reconcile(api.get(KIND, "default", "ad"))
    assert api.get(KIND, "default", "ad")["status"]["phase"] == "Pending"
    _age_pending(api, "ad", 60)  # past fraction (50), before deadline
    phase = rec.reconcile(api.get(KIND, "default", "ad"))
    assert phase == "Pending"
    status = api.get(KIND, "default", "ad")["status"]
    assert status["currentReplicas"] == 3
    assert _conds(api, "ad")[RESIZING_CONDITION]["status"] == "True"
    # Paced: an immediate next pass must NOT shrink again.
    rec.reconcile(api.get(KIND, "default", "ad"))  # roll holds/creates
    rec.reconcile(api.get(KIND, "default", "ad"))
    assert api.get(KIND, "default", "ad")["status"][
        "currentReplicas"] == 3
    # The smaller gang schedules: job runs at 3.
    assert _converge(api, rec, "ad") == "Running"
    assert api.get(KIND, "default", "ad")["status"]["restartCount"] == 0


def test_admission_shrink_stops_at_min_then_deadline_applies():
    api = FakeApiServer()
    with api.as_kubelet():
        api.create(make_elastic("am", workers=2, min_replicas=2,
                                deadline=50))
    rec = Reconciler(api)
    rec.reconcile(api.get(KIND, "default", "am"))
    rec.reconcile(api.get(KIND, "default", "am"))
    _age_pending(api, "am", 60)  # past the whole deadline, at min
    phase = rec.reconcile(api.get(KIND, "default", "am"))
    assert phase == "Failed"
    assert _conds(api, "am")[DEADLINE_CONDITION]["status"] == "True"


def test_post_restart_stall_fails_rigid_and_shrinks_elastic():
    """The spot-storm signature: after a restart the pool only holds
    2 of 4 workers. A rigid gang deadline-fails (releasing chips); an
    elastic one shrinks to the workers that actually scheduled."""
    api = FakeApiServer()
    with api.as_kubelet():
        api.create(make_elastic("st-el", deadline=30))
    spec = replica_spec("TPU_WORKER", 4, image="i",
                        tpu_accelerator="a", tpu_topology="1x1",
                        chips_per_worker=1)
    rigid = tpu_job("st-rg", "default", [spec],
                    termination=termination_policy("TPU_WORKER", 0),
                    scheduling_deadline_seconds=30)
    rigid["metadata"]["uid"] = "uid-st-rg"
    with api.as_kubelet():
        api.create(rigid)
    rec = Reconciler(api)
    for name in ("st-el", "st-rg"):
        assert _converge(api, rec, name) == "Running"
        # Drain the whole gang → restart; then only indices < 2 can
        # schedule again.
        for pod in api.list("Pod", "default", {JOB_LABEL: name}):
            api.set_pod_terminated("default",
                                   pod["metadata"]["name"],
                                   DRAIN_EXIT_CODE)
        rec.reconcile(api.get(KIND, "default", name))  # teardown
        rec.reconcile(api.get(KIND, "default", name))  # recreate
        with api.as_kubelet():
            for pod in api._list("Pod", "default", {JOB_LABEL: name}):
                idx = int(pod["metadata"]["labels"][
                    "kubeflow.org/replica-index"])
                if idx < 2:
                    api.set_pod_phase(
                        "default", pod["metadata"]["name"], "Running")
        # Anchor the stall clock, then backdate it past the deadline.
        rec.reconcile(api.get(KIND, "default", name))
        past = (datetime.datetime.now(datetime.timezone.utc)
                - datetime.timedelta(seconds=60)).isoformat()
        with api.as_kubelet():
            api.patch(KIND, "default", name,
                      lambda o: o["status"].update(
                          {"schedulingSince": past}))
        rec.reconcile(api.get(KIND, "default", name))

    # Elastic: shrank to the 2 running workers, still Running.
    status = api.get(KIND, "default", "st-el")["status"]
    assert status["phase"] == "Running", status
    assert status["currentReplicas"] == 2
    assert _converge(api, rec, "st-el") == "Running"
    assert len(api.list("Pod", "default", {JOB_LABEL: "st-el"})) == 2
    # Rigid: deadline-failed, chips released.
    status = api.get(KIND, "default", "st-rg")["status"]
    assert status["phase"] == "Failed", status
    assert _conds(api, "st-rg")[DEADLINE_CONDITION]["status"] == "True"
    assert api.list("Pod", "default", {JOB_LABEL: "st-rg"}) == []


# -- preemptor shrink-first -----------------------------------------------


def test_preemptor_shrinks_elastic_victim_never_below_min():
    api = FakeApiServer()
    rec = Reconciler(api, preemption=PreemptionPolicy(
        min_interval_seconds=0.0))
    with api.as_kubelet():
        api.create(make_elastic("vic", workers=3, min_replicas=2,
                                max_replicas=3))
    assert _converge(api, rec, "vic") == "Running"
    with api.as_kubelet():
        api.create(make_elastic("hi", workers=1, min_replicas=1,
                                deadline=100, priority=5))
    rec.reconcile(api.get(KIND, "default", "hi"))
    _age_pending(api, "hi", 60)
    rec.reconcile(api.get(KIND, "default", "hi"))

    status = api.get(KIND, "default", "vic")["status"]
    conds = _conds(api, "vic")
    assert status["phase"] == "Running"
    assert status["currentReplicas"] == 2
    assert conds[SHRUNK_CONDITION]["status"] == "True"
    assert conds[RESIZING_CONDITION]["status"] == "True"
    assert PREEMPTED_CONDITION not in conds
    assert rec.preemption.shrunk == 1
    # Victim reconverges at 2 — GangShrunk banner stays (below
    # desired), Resized records the settle.
    assert _converge(api, rec, "vic") == "Running"
    conds = _conds(api, "vic")
    assert conds[SHRUNK_CONDITION]["status"] == "True"
    assert conds[RESIZED_CONDITION]["status"] == "True"

    # Second episode: the victim is now AT min — the kill path takes
    # over (never below min). The preemptor's episode latch must be
    # cleared first (it ran once).
    with api.as_kubelet():
        api.create(make_elastic("hi2", workers=1, min_replicas=1,
                                deadline=100, priority=5))
    rec.reconcile(api.get(KIND, "default", "hi2"))
    _age_pending(api, "hi2", 60)
    rec.reconcile(api.get(KIND, "default", "hi2"))
    status = api.get(KIND, "default", "vic")["status"]
    conds = _conds(api, "vic")
    assert status["currentReplicas"] == 2  # NEVER below min
    assert conds[PREEMPTED_CONDITION]["status"] == "True"
    assert status["phase"] == "Restarting"
    assert status["restartCount"] == 0  # preemption burns no budget


def test_shrink_shares_rate_limit_and_latch():
    """One action per interval across the fleet — a shrink consumes
    the same token a kill would; and the preemptor's episode latch
    covers shrinks (no second action for the same Pending episode)."""
    api = FakeApiServer()
    rec = Reconciler(api, preemption=PreemptionPolicy(
        min_interval_seconds=3600.0))
    with api.as_kubelet():
        api.create(make_elastic("v1", workers=3, min_replicas=2,
                                max_replicas=3))
        api.create(make_elastic("v2", workers=3, min_replicas=2,
                                max_replicas=3))
    assert _converge(api, rec, "v1") == "Running"
    assert _converge(api, rec, "v2") == "Running"
    with api.as_kubelet():
        api.create(make_elastic("p1", workers=1, min_replicas=1,
                                deadline=100, priority=5))
        api.create(make_elastic("p2", workers=1, min_replicas=1,
                                deadline=100, priority=5))
    for name in ("p1", "p2"):
        rec.reconcile(api.get(KIND, "default", name))
        _age_pending(api, name, 60)
    rec.reconcile(api.get(KIND, "default", "p1"))
    # p1 shrank one victim and holds the latch; p2 is rate-limited.
    rec.reconcile(api.get(KIND, "default", "p2"))
    shrunk = [n for n in ("v1", "v2")
              if _conds(api, n).get(SHRUNK_CONDITION, {})
              .get("status") == "True"]
    assert len(shrunk) == 1, shrunk
    assert rec.preemption.shrunk == 1
    assert rec.preemption.rate_limited >= 1
    # p1's latch: another pass of p1 must not act again.
    rec.reconcile(api.get(KIND, "default", "p1"))
    assert rec.preemption.shrunk == 1


# -- dashboard ------------------------------------------------------------


def test_dashboard_summary_elastic_fields_and_degrade():
    from kubeflow_tpu.dashboard.server import job_summary

    job = make_elastic("dj")
    job["status"] = {"phase": "Running", "currentReplicas": 3,
                     "conditions": [
                         {"type": SHRUNK_CONDITION, "status": "True",
                          "reason": "shrunk 4 -> 3"}]}
    summary = job_summary(job)
    assert summary["elastic"] == {"current": 3, "min": 2, "max": 4}
    assert any(w["type"] == SHRUNK_CONDITION
               for w in summary["warnings"])
    # Malformed bounds degrade to the rigid view — never a 500.
    job["spec"]["minReplicas"] = {"nested": "garbage"}
    summary = job_summary(job)
    assert summary["elastic"] is None
    # Rigid jobs carry no elastic block at all.
    spec = replica_spec("TPU_WORKER", 2, image="i",
                        tpu_accelerator="a", tpu_topology="1x1")
    rigid = tpu_job("r", "d", [spec])
    assert job_summary(rigid)["elastic"] is None


# -- acceptance e2e over the HTTP facade (tier-1 fast variant) ------------


def _wait_for(predicate, timeout, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def test_elastic_kill_e2e_over_http():
    """minReplicas=2, maxReplicas=4: killing 1 of 4 hosts mid-run
    keeps the TPUJob Running — no restart-budget burn, the gang rolls
    to 3 with fresh env, Resized lands — through the production HTTP
    client under the live watch controller (the citest's control
    plane at wire level)."""
    fake = FakeApiServer()
    with HttpFakeApiServer(fake=fake, token="el") as srv:
        client = HttpApiClient(srv.url, token="el")
        ctl = WatchController(
            client, relist_seconds=0.3, workers=2,
            backoff=ExponentialBackoff(base=0.02, cap=0.5))
        thread = threading.Thread(target=ctl.run, daemon=True)
        thread.start()
        try:
            client.create(make_elastic("wire", workers=4,
                                       min_replicas=2,
                                       max_replicas=4))
            assert _wait_for(lambda: len(fake._list(
                "Pod", "default", {JOB_LABEL: "wire"})) == 4, 5.0)
            with fake.as_kubelet():
                for pod in fake._list("Pod", "default",
                                      {JOB_LABEL: "wire"}):
                    fake.set_pod_phase("default",
                                       pod["metadata"]["name"],
                                       "Running")
            assert _wait_for(
                lambda: fake.get(KIND, "default", "wire")
                .get("status", {}).get("phase") == "Running", 5.0)

            # Kill one host mid-run (spot drain).
            fake.set_pod_terminated("default", "wire-tpu-worker-2",
                                    DRAIN_EXIT_CODE)

            # The gang must reconverge at 3 — the kubelet keeps
            # admitting whatever the roll creates.
            def settled():
                with fake.as_kubelet():
                    pods = fake._list("Pod", "default",
                                      {JOB_LABEL: "wire"})
                    for pod in pods:
                        if pod.get("status", {}).get("phase") in (
                                None, "Pending"):
                            fake.set_pod_phase(
                                "default", pod["metadata"]["name"],
                                "Running")
                    status = fake.get(KIND, "default", "wire").get(
                        "status", {})
                conds = {c.get("type"): c.get("status")
                         for c in status.get("conditions", [])}
                return (len(pods) == 3
                        and all(p.get("status", {}).get("phase")
                                == "Running" for p in pods)
                        and status.get("phase") == "Running"
                        and conds.get(RESIZED_CONDITION) == "True")

            assert _wait_for(settled, 10.0), fake.get(
                KIND, "default", "wire").get("status")
            status = fake.get(KIND, "default", "wire")["status"]
            conds = {c.get("type"): c.get("status")
                     for c in status.get("conditions", [])}
            assert int(status.get("restartCount", 0)) == 0
            assert int(status.get("currentReplicas", 0)) == 3
            # Never entered Restarting; pods unique.
            assert "Restarting" not in conds
            names = sorted(p["metadata"]["name"] for p in fake._list(
                "Pod", "default", {JOB_LABEL: "wire"}))
            assert len(names) == len(set(names)) == 3
            # Controller surfaced the resize in its stats.
            assert ctl.stats()["gangResizes"]["shrink"] >= 1
        finally:
            ctl.stop.set()
            thread.join(timeout=10)
