# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Continuous-batching engine correctness (inference/engine/).

The contract under test: every row's streamed output is BITWISE equal
to the same request run alone through ``inference.generate.generate``
at B=1 — under adversarial admit/retire orderings (mixed lengths,
mid-decode joins, deadline-evicted neighbors, page-pool contention),
greedy and sampled. Plus the host-side state machines (PageAllocator,
SlotScheduler, GenerateStream) unit-tested without a model.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.inference.engine import (
    DecodeEngine,
    EngineConfig,
    GenerateStream,
    PageAllocator,
    SlotScheduler,
    TokenEvent,
)
from kubeflow_tpu.inference.generate import generate
from kubeflow_tpu.models.llama import llama_test
from kubeflow_tpu.serving.overload import DeadlineExceededError

CACHE = 48
MAX_PROMPT = 16


@pytest.fixture(scope="module")
def model():
    return llama_test(dtype=jnp.float32, cache_size=CACHE)


@pytest.fixture(scope="module")
def params(model):
    ids = jnp.zeros((1, 8), jnp.int32)
    return model.init(jax.random.PRNGKey(0), ids)["params"]


def _prompts(*lengths, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 512, (n,)).astype(np.int32) for n in lengths]


def _keys(n, base=100):
    return [np.asarray(jax.random.PRNGKey(base + i)) for i in range(n)]


def _reference(model, params, prompt, key, max_new_tokens, **sampling):
    """The B=1 ground truth: the same prompt + per-request key through
    the monolithic generate()."""
    tokens, _ = generate(
        model, params, jnp.asarray(prompt)[None, :],
        max_new_tokens=max_new_tokens, rng=jnp.asarray(key)[None, :],
        prompt_lengths=jnp.asarray([len(prompt)]), **sampling)
    return np.asarray(tokens)[0]


def _assert_pool_clean(engine):
    st = engine.stats()
    assert st["active_slots"] == 0, st
    assert st["queue_depth"] == 0, st
    assert st["free_pages"] == st["total_pages"], \
        f"leaked pages: {st}"
    assert st["reserved_pages"] == 0, st


# -- bitwise equality under adversarial orderings -------------------------


def test_mid_decode_joins_mixed_lengths_bitwise_equal_greedy(
        model, params):
    """Rows join a live decode at staggered times with mixed prompt
    lengths AND mixed per-request token budgets; every row must equal
    its B=1 run exactly. (2 slots, 5 requests: admissions necessarily
    interleave with retirements mid-decode.)"""
    # Budgets chosen ≡ 1 (mod slice_tokens): remaining decode steps
    # divide evenly into 4-token slices, so this test compiles ONE
    # slice program (tail-slice K variants get their own dedicated
    # coverage below — each distinct K is a separate XLA compile, the
    # dominant cost of this file on CI).
    cfg = EngineConfig(max_new_tokens=13, max_prompt_len=MAX_PROMPT,
                       temperature=0.0, num_slots=2, page_size=8,
                       slice_tokens=4)
    engine = DecodeEngine(model, params, cfg, name="t-greedy")
    try:
        prompts = _prompts(5, 11, 3, 8, 6)
        keys = _keys(5)
        budgets = [13, 9, 5, 13, 9]
        streams = []
        # First two fill both slots; wait until tokens actually flow
        # so the rest join a decode already in flight.
        for i in range(2):
            streams.append(engine.submit(prompts[i], rng=keys[i],
                                         max_new_tokens=budgets[i]))
        for s in streams:
            assert s.next_event(timeout=120.0) is not None
        for i in range(2, 5):
            streams.append(engine.submit(prompts[i], rng=keys[i],
                                         max_new_tokens=budgets[i]))
            time.sleep(0.01)  # stagger: distinct admit points
        results = [s.result(timeout=120.0) for s in streams]
        for i, (p, k, t) in enumerate(zip(prompts, keys, budgets)):
            want = _reference(model, params, p, k, t)
            np.testing.assert_array_equal(
                results[i], want,
                err_msg=f"row {i} (len={len(p)}, budget={t}) diverged "
                        f"from its B=1 reference")
        _assert_pool_clean(engine)
    finally:
        engine.stop()


def test_sampled_equality_under_churn(model, params):
    """Sampling (temperature + top_k + top_p) rides per-request key
    schedules, so mid-decode joins must not perturb any row's rng
    stream — bitwise, not statistically."""
    sampling = dict(temperature=0.8, top_k=50, top_p=0.95)
    cfg = EngineConfig(max_new_tokens=10, max_prompt_len=MAX_PROMPT,
                       num_slots=2, page_size=8, slice_tokens=3,
                       **sampling)  # 9 decode steps = 3 clean slices
    engine = DecodeEngine(model, params, cfg, name="t-sampled")
    try:
        prompts = _prompts(7, 4, 9, seed=3)
        keys = _keys(3, base=500)
        streams = [engine.submit(prompts[0], rng=keys[0])]
        assert streams[0].next_event(timeout=120.0) is not None
        streams += [engine.submit(p, rng=k)
                    for p, k in zip(prompts[1:], keys[1:])]
        results = [s.result(timeout=120.0) for s in streams]
        for i in range(3):
            want = _reference(model, params, prompts[i], keys[i], 10,
                              **sampling)
            np.testing.assert_array_equal(
                results[i], want, err_msg=f"sampled row {i} diverged")
        _assert_pool_clean(engine)
    finally:
        engine.stop()


def test_deadline_eviction_frees_slot_and_neighbors_unaffected(
        model, params):
    """A slot evicted mid-decode (deadline expiry at a slice boundary)
    fails its stream with DeadlineExceededError, frees its pages, and
    the freed slot admits a NEW request — with the surviving neighbor
    and the late joiner both still bitwise-equal to B=1."""
    cfg = EngineConfig(max_new_tokens=17, max_prompt_len=MAX_PROMPT,
                       temperature=0.0, num_slots=2, page_size=8,
                       slice_tokens=4)  # 16 steps = 4 clean slices
    engine = DecodeEngine(model, params, cfg, name="t-evict")
    try:
        prompts = _prompts(6, 9, 5, seed=7)
        keys = _keys(3, base=900)
        survivor = engine.submit(prompts[0], rng=keys[0])
        victim = engine.submit(prompts[1], rng=keys[1],
                               deadline=time.monotonic() + 3600.0)
        # Wait until the victim is actually decoding, then age its
        # slot's deadline into the past — the engine must evict at the
        # next slice boundary (deterministic, no wall-clock tuning).
        assert victim.next_event(timeout=120.0) is not None
        for slot in engine.scheduler.active_slots():
            if slot.request is not None and \
                    slot.request.stream is victim:
                slot.deadline = time.monotonic() - 0.001
                slot.request.deadline = slot.deadline
        with pytest.raises(DeadlineExceededError, match="mid-decode"):
            victim.result(timeout=120.0)
        # The freed slot admits a new request...
        joiner = engine.submit(prompts[2], rng=keys[2])
        np.testing.assert_array_equal(
            joiner.result(timeout=120.0),
            _reference(model, params, prompts[2], keys[2], 17),
            err_msg="joiner into the evicted slot diverged")
        # ...and the survivor never noticed.
        np.testing.assert_array_equal(
            survivor.result(timeout=120.0),
            _reference(model, params, prompts[0], keys[0], 17),
            err_msg="survivor diverged after neighbor eviction")
        assert engine.scheduler.retired_by.get("deadline") == 1
        _assert_pool_clean(engine)
    finally:
        engine.stop()


def test_queued_request_expires_and_cancel_frees_slot(model, params):
    """Three single-slot scenarios on one engine (one compile set):
    (a) a request whose deadline lapses while it waits for a slot
    fails from the QUEUE — never prefills, never binds — while the
    slot holder decodes on undisturbed; (b) a cancelled stream retires
    its slot at the next slice boundary and frees every page; (c) the
    queue-capacity bound sheds deadline-FREE submits with
    OverloadedError (the r8 invariant the deadline gate alone would
    drop)."""
    cfg = EngineConfig(max_new_tokens=13, max_prompt_len=MAX_PROMPT,
                       temperature=0.0, num_slots=1, page_size=8,
                       slice_tokens=2,  # 12 steps = 6 clean slices
                       queue_capacity=2)
    engine = DecodeEngine(model, params, cfg, name="t-qexpire")
    try:
        prompts = _prompts(6, 5, seed=11)
        keys = _keys(2, base=1300)
        holder = engine.submit(prompts[0], rng=keys[0])
        assert holder.next_event(timeout=120.0) is not None
        queued = engine.submit(prompts[1], rng=keys[1],
                               deadline=time.monotonic() + 3600.0)
        admitted_before = engine.scheduler.admitted
        # Age the queued deadline (white-box, like the eviction test).
        assert engine.scheduler.pending, "request should be queued"
        engine.scheduler.pending[0].deadline = time.monotonic() - 0.001
        with pytest.raises(DeadlineExceededError, match="queued"):
            queued.result(timeout=120.0)
        assert engine.scheduler.admitted == admitted_before, \
            "expired-in-queue request burned a prefill"
        np.testing.assert_array_equal(
            holder.result(timeout=120.0),
            _reference(model, params, prompts[0], keys[0], 13))

        # (b) cancel mid-decode.
        victim = engine.submit(prompts[1], rng=keys[1])
        assert victim.next_event(timeout=120.0) is not None
        victim.cancel()
        with pytest.raises(RuntimeError, match="cancelled"):
            victim.result(timeout=60.0)
        deadline = time.monotonic() + 30.0
        while engine.scheduler.occupancy() and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        assert engine.scheduler.retired_by.get("cancelled") == 1
        _assert_pool_clean(engine)

        # (c) deadline-free queue bound: slot holder + 2 queued fill
        # capacity; the next submit sheds synchronously.
        from kubeflow_tpu.serving.overload import OverloadedError

        holder2 = engine.submit(prompts[0], rng=keys[0])
        assert holder2.next_event(timeout=120.0) is not None
        q = [engine.submit(prompts[1], rng=keys[1]) for _ in range(2)]
        with pytest.raises(OverloadedError, match="queue full"):
            engine.submit(prompts[1], rng=keys[1])
        for s in [holder2] + q:
            s.result(timeout=120.0)
        _assert_pool_clean(engine)
    finally:
        engine.stop()


def test_page_pool_contention_serializes_but_stays_correct(
        model, params):
    """A pool too small for two concurrent requests gates admission on
    reservations (FIFO holds the line); all requests still complete,
    correct, and the pool drains back to full."""
    # bucket(prompt<=8)=8, +13 new = 21 positions -> 3 pages of 8.
    # num_pages=4 => 3 usable: exactly one resident request.
    cfg = EngineConfig(max_new_tokens=13, max_prompt_len=MAX_PROMPT,
                       temperature=0.0, num_slots=2, page_size=8,
                       slice_tokens=4, num_pages=4)
    engine = DecodeEngine(model, params, cfg, name="t-pages")
    try:
        prompts = _prompts(4, 7, 6, seed=23)
        keys = _keys(3, base=1700)
        streams = [engine.submit(p, rng=k)
                   for p, k in zip(prompts, keys)]
        results = [s.result(timeout=180.0) for s in streams]
        for i in range(3):
            np.testing.assert_array_equal(
                results[i],
                _reference(model, params, prompts[i], keys[i], 13),
                err_msg=f"page-contended row {i} diverged")
        st = engine.stats()
        assert st["admitted"] == 3 and st["retired"] == {"budget": 3}
        _assert_pool_clean(engine)
    finally:
        engine.stop()


def test_early_eos_retires_early_and_pads_like_generate(model, params):
    """EOS mid-stream: the slot retires at the latch (stream stops
    emitting), the result is padded to the request budget with the EOS
    id — the exact latched shape generate() returns at B=1."""
    prompts = _prompts(6, seed=31)
    keys = _keys(1, base=2100)
    # Pick an EOS id the greedy decode actually emits at step 2.
    free_run = _reference(model, params, prompts[0], keys[0], 10)
    eos = int(free_run[2])
    if eos in (int(free_run[0]), int(free_run[1])):
        pytest.skip("degenerate repeated token; eos pick ambiguous")
    cfg = EngineConfig(max_new_tokens=10, max_prompt_len=MAX_PROMPT,
                       temperature=0.0, num_slots=2, page_size=8,
                       slice_tokens=4, eos_id=eos)
    engine = DecodeEngine(model, params, cfg, name="t-eos")
    try:
        stream = engine.submit(prompts[0], rng=keys[0])
        events = [ev for ev in stream.events(timeout_per_event=120.0)]
        token_events = [ev for ev in events if not ev.final]
        assert len(token_events) == 3, \
            f"expected emission to stop at EOS (index 2), got " \
            f"{[ev.token for ev in token_events]}"
        want = _reference(model, params, prompts[0], keys[0], 10,
                          eos_id=eos)
        np.testing.assert_array_equal(stream.result(timeout=5.0), want)
        assert engine.scheduler.retired_by.get("eos") == 1
        _assert_pool_clean(engine)
    finally:
        engine.stop()


def test_short_join_finishes_well_before_long_neighbor(model, params):
    """The goodput story in one assertion: a 3-token request admitted
    while a 21-token neighbor decodes must complete while the
    neighbor is still mid-decode — the static coalescer made it ride
    until the LONGEST row finished."""
    cfg = EngineConfig(max_new_tokens=21, max_prompt_len=MAX_PROMPT,
                       temperature=0.0, num_slots=2, page_size=8,
                       slice_tokens=4)  # 20 steps = 5 clean slices
    engine = DecodeEngine(model, params, cfg, name="t-ttft")
    try:
        prompts = _prompts(8, 4, seed=43)
        keys = _keys(2, base=2500)
        # Warm every compile path first so the measured join is pure
        # steady-state scheduling, not compile noise: both prompt
        # buckets' prefills, the K=4 slice, AND the short request's
        # whole path — jax.random.split(key, 3) inside submit() and
        # the K=2 tail slice each cost a compile the first time, which
        # would otherwise delay the join past the neighbor's entire
        # warm decode (~30ms).
        engine.submit(prompts[0], rng=keys[0]).result(timeout=180.0)
        engine.submit(prompts[1], rng=keys[1],
                      max_new_tokens=3).result(timeout=180.0)
        long_s = engine.submit(prompts[0], rng=keys[0])
        assert long_s.next_event(timeout=60.0) is not None
        short_s = engine.submit(prompts[1], rng=keys[1],
                                max_new_tokens=3)
        # Snapshot the neighbor's progress ON THE ENGINE THREAD at the
        # moment the short stream finishes — reading it after result()
        # races the engine, which on a warm box finishes the long row
        # inside the consumer's wakeup latency.
        snap = {}

        def on_emit():
            if short_s.done and "progress" not in snap:
                snap["progress"] = len(long_s.tokens_so_far)

        short_s.set_notify(on_emit)
        short_result = short_s.result(timeout=60.0)
        long_progress = snap.get("progress",
                                 len(long_s.tokens_so_far))
        assert long_progress < 21, (
            f"short request only completed after its long neighbor's "
            f"full decode ({long_progress}/21 tokens)")
        np.testing.assert_array_equal(
            short_result,
            _reference(model, params, prompts[1], keys[1], 3))
        long_ref = _reference(model, params, prompts[0], keys[0], 21)
        np.testing.assert_array_equal(long_s.result(timeout=120.0),
                                      long_ref)
        _assert_pool_clean(engine)
    finally:
        engine.stop()


def test_submit_rejects_request_that_can_never_fit_the_pool(
        model, params):
    """A worst-case reservation larger than the whole pool must fail
    at submit — otherwise it parks at the FIFO head forever and
    (strict FIFO) wedges every request behind it."""
    cfg = EngineConfig(max_new_tokens=24, max_prompt_len=MAX_PROMPT,
                       temperature=0.0, num_slots=2, page_size=8,
                       slice_tokens=4, num_pages=3)  # 2 usable pages
    engine = DecodeEngine(model, params, cfg, name="t-never")
    try:
        with pytest.raises(ValueError, match="worst-case"):
            engine.submit(np.zeros((8,), np.int32))
        # A request that DOES fit still flows.
        with pytest.raises(ValueError, match="worst-case"):
            engine.submit(np.zeros((8,), np.int32),
                          max_new_tokens=24)
        stream = engine.submit(np.zeros((8,), np.int32),
                               max_new_tokens=5)  # 8+5=13 -> 2 pages
        assert stream.result(timeout=120.0).shape == (5,)
        _assert_pool_clean(engine)
    finally:
        engine.stop()


# -- host-side state machines (no model, no jax dispatch) -----------------


class _FakeReq:
    def __init__(self, deadline=None, max_new_tokens=8):
        self.deadline = deadline
        self.max_new_tokens = max_new_tokens
        self.step_keys = np.arange(2 * max_new_tokens,
                                   dtype=np.uint32).reshape(-1, 2)


def test_page_allocator_reservation_invariants():
    alloc = PageAllocator(6)  # null + 5 usable
    assert alloc.free_pages == 5 and alloc.available() == 5
    assert alloc.reserve(3)
    assert alloc.available() == 2
    assert not alloc.reserve(3)  # would oversubscribe
    pages = alloc.alloc(2)
    assert len(pages) == 2 and 0 not in pages
    assert alloc.reserved_pages == 1 and alloc.free_pages == 3
    with pytest.raises(ValueError, match="without reservation"):
        alloc.alloc(2)  # only 1 page still reserved
    alloc.free(pages)
    alloc.unreserve(1)
    assert alloc.available() == 5
    with pytest.raises(ValueError, match="null page"):
        alloc.free([0])
    with pytest.raises(ValueError, match="exceeds"):
        alloc.unreserve(1)
    with pytest.raises(ValueError, match=">= 2 pages"):
        PageAllocator(1)


def test_slot_scheduler_fifo_holds_for_big_head():
    """A head request whose reservation doesn't fit must BLOCK later
    (smaller) arrivals — FIFO fairness, no starvation of big
    prompts."""
    alloc = PageAllocator(4)  # 3 usable
    sched = SlotScheduler(2, alloc)
    big, small = _FakeReq(), _FakeReq()
    sched.pending.extend([big, small])
    sizes = {id(big): 5, id(small): 1}
    assert sched.next_admittable(lambda r: sizes[id(r)]) is None
    assert list(sched.pending) == [big, small], \
        "FIFO must not skip the blocked head"
    # Once the pool can cover the head, it admits in order.
    sizes[id(big)] = 3
    assert sched.next_admittable(lambda r: sizes[id(r)]) is big


def test_slot_scheduler_bind_retire_roundtrip():
    alloc = PageAllocator(8)
    sched = SlotScheduler(2, alloc)
    req = _FakeReq()
    assert alloc.reserve(2)
    slot = sched.bind(req, prompt_width=8, pad_len=2, first_token=7,
                      done=False, budget_pages=2, deadline=None)
    assert slot.active and sched.occupancy() == 1
    assert slot.write_pos == 8 and slot.steps_done == 1
    assert slot.remaining == req.max_new_tokens - 1
    sched.retire(slot, "eos")
    assert not slot.active and sched.occupancy() == 0
    assert sched.retired_by == {"eos": 1}
    with pytest.raises(AssertionError):
        sched.retire(slot, "eos")  # double retire


def test_slot_scheduler_expired_pending_preserves_order():
    sched = SlotScheduler(1, PageAllocator(4))
    now = 1000.0
    live1 = _FakeReq(deadline=now + 5)
    dead = _FakeReq(deadline=now - 1)
    live2 = _FakeReq(deadline=None)
    sched.pending.extend([live1, dead, live2])
    assert sched.expired_pending(now=now) == [dead]
    assert list(sched.pending) == [live1, live2]


def test_slice_keys_clamp_past_schedule_end():
    req = _FakeReq(max_new_tokens=4)  # keys 0..3
    sched = SlotScheduler(1, PageAllocator(4))
    alloc_ok = sched._allocator.reserve(1)
    assert alloc_ok
    slot = sched.bind(req, prompt_width=4, pad_len=0, first_token=1,
                      done=False, budget_pages=1, deadline=None)
    slot.steps_done = 3
    keys = SlotScheduler.slice_keys(slot, 4)
    np.testing.assert_array_equal(keys[0], req.step_keys[3])
    # Overshoot steps clamp to the final key (computed, discarded).
    np.testing.assert_array_equal(keys[1], req.step_keys[3])
    np.testing.assert_array_equal(keys[3], req.step_keys[3])


def test_generate_stream_event_flow_and_notify():
    stream = GenerateStream(max_new_tokens=3)
    seen = []
    stream.set_notify(lambda: seen.append(len(stream.tokens_so_far)))
    stream._emit(TokenEvent(token=5, index=0))
    stream._emit(TokenEvent(token=9, index=1))
    assert stream.tokens_so_far == [5, 9]
    assert not stream.done
    ev = stream.next_event(timeout=1.0)
    assert (ev.token, ev.index, ev.final) == (5, 0, False)
    stream._finish(np.asarray([5, 9, 9], np.int32))
    assert stream.done
    rest = stream.drain()
    assert [e.token for e in rest] == [9, None]
    assert rest[-1].final
    np.testing.assert_array_equal(stream.result(timeout=1.0),
                                  [5, 9, 9])
    assert seen  # notify fired per emit


def test_generate_stream_failure_propagates():
    stream = GenerateStream(max_new_tokens=4)
    stream._fail(DeadlineExceededError("expired mid-decode"))
    with pytest.raises(DeadlineExceededError):
        stream.result(timeout=1.0)
    # Terminal event is poppable exactly once, then the queue is dry.
    ev = stream.next_event(timeout=0.1)
    assert ev is not None and ev.final and ev.error is not None
    assert stream.next_event(timeout=0.05) is None
    # Post-final emissions are dropped, not queued.
    stream._emit(TokenEvent(token=1, index=9))
    assert stream.next_event(timeout=0.05) is None


def test_generate_stream_events_iterator_timeout():
    stream = GenerateStream(max_new_tokens=2)
    with pytest.raises(TimeoutError):
        for _ in stream.events(timeout_per_event=0.05):
            pass


def test_generate_stream_concurrent_consumer():
    """A consumer thread draining while the producer emits sees every
    token exactly once, in order."""
    stream = GenerateStream(max_new_tokens=64)
    got = []

    def consume():
        for ev in stream.events(timeout_per_event=5.0):
            if not ev.final:
                got.append(ev.token)

    t = threading.Thread(target=consume)
    t.start()
    for i in range(64):
        stream._emit(TokenEvent(token=i, index=i))
        if i % 7 == 0:
            time.sleep(0.001)
    stream._finish(np.arange(64, dtype=np.int32))
    t.join(timeout=10)
    assert not t.is_alive()
    assert got == list(range(64))
