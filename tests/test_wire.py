# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""PredictionService wire-format parity tests.

The codec (serving/wire.py) is hand-rolled against the public
tensorflow/tensorflow_serving proto schemas; these tests pin the wire
bytes both ways — including cross-validation against tensorflow's own
TensorProto implementation, which is installed in the test environment
(the serving images don't need it)."""

import numpy as np
import pytest

from kubeflow_tpu.serving import wire


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32,
                                   np.int64, np.uint8, np.bool_])
def test_tensor_roundtrip(dtype):
    rng = np.random.RandomState(0)
    arr = (rng.rand(2, 3, 4) * 100).astype(dtype)
    out = wire.decode_tensor(wire.encode_tensor(arr))
    assert out.dtype == arr.dtype and out.shape == arr.shape
    np.testing.assert_array_equal(out, arr)


def test_predict_request_roundtrip():
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    buf = wire.encode_predict_request(
        "inception", {"images": x}, signature_name="predict_images",
        version=7)
    spec, inputs, _ = wire.decode_predict_request(buf)
    assert spec == {"name": "inception", "version": 7,
                    "signature_name": "predict_images"}
    np.testing.assert_array_equal(inputs["images"], x)


def test_predict_response_roundtrip():
    outputs = {"classes": np.array([[1, 2, 3]], np.int32),
               "scores": np.array([[0.5, 0.3, 0.2]], np.float32)}
    buf = wire.encode_predict_response(outputs, "m", 3)
    spec, decoded = wire.decode_predict_response(buf)
    assert spec["name"] == "m" and spec["version"] == 3
    for k in outputs:
        np.testing.assert_array_equal(decoded[k], outputs[k])


def test_framing_roundtrip():
    msg = b"hello-proto"
    body = wire.frame_message(msg) + wire.trailers_frame(0)
    frames = wire.unframe_messages(body)
    assert frames[0] == (0, msg)
    assert frames[1][0] & 0x80
    assert b"grpc-status:0" in frames[1][1]


@pytest.mark.slow
def test_tensor_bytes_match_tensorflow():
    """Byte-level cross-validation against tf.make_tensor_proto —
    the reference client's exact encoder (label.py uses
    tf.contrib.util.make_tensor_proto)."""
    tf = pytest.importorskip("tensorflow")

    rng = np.random.RandomState(1)
    for arr in (rng.rand(2, 5).astype(np.float32),
                rng.randint(0, 100, (3, 2)).astype(np.int32),
                rng.rand(4).astype(np.float64)):
        # tf's encoding decodes with our codec...
        theirs = tf.make_tensor_proto(arr).SerializeToString()
        np.testing.assert_array_equal(wire.decode_tensor(theirs), arr)
        # ...and our encoding decodes with tf's.
        from tensorflow.core.framework import tensor_pb2

        proto = tensor_pb2.TensorProto.FromString(wire.encode_tensor(arr))
        np.testing.assert_array_equal(tf.make_ndarray(proto), arr)


@pytest.mark.slow
def test_small_tensor_val_fields_decode():
    """tf.make_tensor_proto emits *_val fields (not tensor_content)
    for scalars/small tensors; the decoder must handle both."""
    tf = pytest.importorskip("tensorflow")

    scalar = tf.make_tensor_proto(np.float32(2.5)).SerializeToString()
    out = wire.decode_tensor(scalar)
    assert out.shape == () and float(out) == 2.5
    filled = tf.make_tensor_proto(
        np.full((2, 2), 7, np.int32)).SerializeToString()
    np.testing.assert_array_equal(
        wire.decode_tensor(filled), np.full((2, 2), 7, np.int32))


# -- fleet KV pull-through codec (ISSUE 20) --------------------------------


def _kv_blocks(n=2, page=4, seed=0):
    rng = np.random.RandomState(seed)
    blocks = []
    for j in range(n):
        tokens = tuple(int(t) for t in rng.randint(0, 100, (page,)))
        layers = [rng.rand(page, 2, 3).astype(np.float32),
                  rng.rand(page, 2, 3).astype(np.float32)]
        blocks.append((tokens, layers))
    return blocks


def test_kv_blocks_roundtrip_byte_exact():
    """encode_kv_blocks → decode_kv_blocks is byte-exact on the KV
    arrays (the same msgpack property that keeps handoff adoption
    bitwise) and preserves token chains and block order."""
    blocks = _kv_blocks(n=3)
    data = wire.encode_kv_blocks("llama_test", 7, 4, blocks)
    out = wire.decode_kv_blocks(data, model="llama_test", version=7,
                                page_size=4)
    assert len(out) == 3
    for (tok_in, lay_in), (tok_out, lay_out) in zip(blocks, out):
        assert tok_out == tok_in
        assert len(lay_out) == len(lay_in)
        for a, b in zip(lay_in, lay_out):
            assert b.dtype == a.dtype
            np.testing.assert_array_equal(b, a)


def test_kv_blocks_roundtrip_bf16_byte_exact():
    """bf16 KV survives the wire bit-for-bit — the dtype real pools
    run; any up/down-cast would silently break the bitwise-equal
    acceptance on the fetch path."""
    jnp = pytest.importorskip("jax.numpy")

    layer = np.asarray(jnp.linspace(-3.0, 3.0, 24,
                                    dtype=jnp.bfloat16)).reshape(4, 2, 3)
    data = wire.encode_kv_blocks(
        "m", 1, 4, [((1, 2, 3, 4), [layer])])
    [(tokens, layers)] = wire.decode_kv_blocks(data, model="m",
                                               version=1, page_size=4)
    assert tokens == (1, 2, 3, 4)
    assert layers[0].dtype == layer.dtype
    np.testing.assert_array_equal(layers[0], layer)


def test_kv_blocks_rejects_geometry_and_identity_mismatch():
    """A fetched payload splices into live attention state — every
    identity/geometry mismatch must be a hard ValueError (the client
    swallows it and prefills cold), never a silent partial parse."""
    data = wire.encode_kv_blocks("llama_test", 7, 4, _kv_blocks())
    # Happy path parses with unpinned version/page_size.
    assert len(wire.decode_kv_blocks(data, model="llama_test")) == 2
    with pytest.raises(ValueError, match="model"):
        wire.decode_kv_blocks(data, model="other-model")
    with pytest.raises(ValueError, match="version"):
        wire.decode_kv_blocks(data, model="llama_test", version=8)
    with pytest.raises(ValueError, match="page"):
        wire.decode_kv_blocks(data, model="llama_test", page_size=8)
    with pytest.raises(ValueError, match="malformed"):
        wire.decode_kv_blocks(b"not msgpack at all", model="llama_test")
    # Wrong token count inside a block (truncated chain link).
    bad = wire.encode_kv_blocks(
        "llama_test", 7, 4,
        [((1, 2, 3), [np.zeros((3, 2, 2), np.float32)])])
    with pytest.raises(ValueError, match="tokens"):
        wire.decode_kv_blocks(bad, model="llama_test")
    # A block with no KV layers carries nothing adoptable.
    empty = wire.encode_kv_blocks("llama_test", 7, 4,
                                  [((1, 2, 3, 4), [])])
    with pytest.raises(ValueError, match="no KV layers"):
        wire.decode_kv_blocks(empty, model="llama_test")
    # Format/kind gate: a foreign or future format is a clear 400.
    from flax import serialization
    alien = serialization.msgpack_serialize(
        {"format": np.int32(99), "kind": "kv_blocks", "model": "m",
         "version": np.int32(1), "page_size": np.int32(4), "blocks": []})
    with pytest.raises(ValueError, match="format"):
        wire.decode_kv_blocks(alien, model="m")
