# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""PredictionService wire-format parity tests.

The codec (serving/wire.py) is hand-rolled against the public
tensorflow/tensorflow_serving proto schemas; these tests pin the wire
bytes both ways — including cross-validation against tensorflow's own
TensorProto implementation, which is installed in the test environment
(the serving images don't need it)."""

import numpy as np
import pytest

from kubeflow_tpu.serving import wire


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32,
                                   np.int64, np.uint8, np.bool_])
def test_tensor_roundtrip(dtype):
    rng = np.random.RandomState(0)
    arr = (rng.rand(2, 3, 4) * 100).astype(dtype)
    out = wire.decode_tensor(wire.encode_tensor(arr))
    assert out.dtype == arr.dtype and out.shape == arr.shape
    np.testing.assert_array_equal(out, arr)


def test_predict_request_roundtrip():
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    buf = wire.encode_predict_request(
        "inception", {"images": x}, signature_name="predict_images",
        version=7)
    spec, inputs, _ = wire.decode_predict_request(buf)
    assert spec == {"name": "inception", "version": 7,
                    "signature_name": "predict_images"}
    np.testing.assert_array_equal(inputs["images"], x)


def test_predict_response_roundtrip():
    outputs = {"classes": np.array([[1, 2, 3]], np.int32),
               "scores": np.array([[0.5, 0.3, 0.2]], np.float32)}
    buf = wire.encode_predict_response(outputs, "m", 3)
    spec, decoded = wire.decode_predict_response(buf)
    assert spec["name"] == "m" and spec["version"] == 3
    for k in outputs:
        np.testing.assert_array_equal(decoded[k], outputs[k])


def test_framing_roundtrip():
    msg = b"hello-proto"
    body = wire.frame_message(msg) + wire.trailers_frame(0)
    frames = wire.unframe_messages(body)
    assert frames[0] == (0, msg)
    assert frames[1][0] & 0x80
    assert b"grpc-status:0" in frames[1][1]


@pytest.mark.slow
def test_tensor_bytes_match_tensorflow():
    """Byte-level cross-validation against tf.make_tensor_proto —
    the reference client's exact encoder (label.py uses
    tf.contrib.util.make_tensor_proto)."""
    tf = pytest.importorskip("tensorflow")

    rng = np.random.RandomState(1)
    for arr in (rng.rand(2, 5).astype(np.float32),
                rng.randint(0, 100, (3, 2)).astype(np.int32),
                rng.rand(4).astype(np.float64)):
        # tf's encoding decodes with our codec...
        theirs = tf.make_tensor_proto(arr).SerializeToString()
        np.testing.assert_array_equal(wire.decode_tensor(theirs), arr)
        # ...and our encoding decodes with tf's.
        from tensorflow.core.framework import tensor_pb2

        proto = tensor_pb2.TensorProto.FromString(wire.encode_tensor(arr))
        np.testing.assert_array_equal(tf.make_ndarray(proto), arr)


@pytest.mark.slow
def test_small_tensor_val_fields_decode():
    """tf.make_tensor_proto emits *_val fields (not tensor_content)
    for scalars/small tensors; the decoder must handle both."""
    tf = pytest.importorskip("tensorflow")

    scalar = tf.make_tensor_proto(np.float32(2.5)).SerializeToString()
    out = wire.decode_tensor(scalar)
    assert out.shape == () and float(out) == 2.5
    filled = tf.make_tensor_proto(
        np.full((2, 2), 7, np.int32)).SerializeToString()
    np.testing.assert_array_equal(
        wire.decode_tensor(filled), np.full((2, 2), 7, np.int32))
