# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Role-split routing (ISSUE 10): the role dimension on the r10
fleet — endpoints-file schema v2, role-aware balancing, the engine's
KV-handoff seam, prefill→decode orchestration through the pooled
proxy (bitwise equal to the single-replica path), and per-pool
autoscaling signals."""

import json
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubeflow_tpu.inference.engine import DecodeEngine, EngineConfig
from kubeflow_tpu.models.llama import llama_test
from kubeflow_tpu.scaling.balancer import (
    RoleAwareBalancer,
    make_balancer,
)
from kubeflow_tpu.scaling.endpoints import (
    Endpoint,
    EndpointPool,
    FileEndpointSource,
    normalize_spec,
    write_endpoints_file,
)
from kubeflow_tpu.serving import wire

PROMPT_LEN = 8
NEW_TOKENS = 6
CACHE = 32


def _ep(address, role="any", score=0.0):
    ep = Endpoint(address, register_metrics=False, role=role)
    if score:
        ep.saturation = {"m": {"queue_depth": score,
                               "est_batch_latency_ms": 1.0}}
    return ep


# --- endpoints-file schema v2 ---------------------------------------------

def test_endpoints_file_v2_roundtrips_roles(tmp_path):
    path = tmp_path / "endpoints.json"
    write_endpoints_file(str(path), [
        ("a:8500", "a:9000", "prefill"),
        ("b:8500", None, "decode"),
        ("c:8500", None),  # role-less stays the classic 2-tuple
    ])
    doc = json.loads(path.read_text())
    assert doc["version"] == 2
    source = FileEndpointSource(str(path))
    assert source.specs() == [("a:8500", "a:9000", "prefill"),
                              ("b:8500", None, "decode"),
                              ("c:8500", None)]


def test_v1_file_reads_role_any(tmp_path):
    # A pre-role writer's file: no version key, no roles.
    path = tmp_path / "v1.json"
    path.write_text(json.dumps({"endpoints": [
        {"address": "a:8500", "grpc_address": "a:9000"}]}))
    specs = FileEndpointSource(str(path)).specs()
    assert specs == [("a:8500", "a:9000")]
    assert normalize_spec(specs[0]) == ("a:8500", "a:9000", "any")


def test_unknown_role_degrades_to_any(tmp_path):
    # A NEWER writer's role vocabulary must not break this reader.
    path = tmp_path / "future.json"
    path.write_text(json.dumps({"version": 3, "endpoints": [
        {"address": "a:8500", "role": "embedding"}]}))
    specs = FileEndpointSource(str(path)).specs()
    assert normalize_spec(specs[0])[2] == "any"


def test_pool_sync_applies_role_changes():
    pool = EndpointPool()
    pool.sync([("a:1", None, "prefill")])
    assert pool.get("a:1").role == "prefill"
    pool.sync([("a:1", None, "decode")])  # mid-rollout retag
    assert pool.get("a:1").role == "decode"
    pool.sync([("a:1", None)])  # role dropped → any
    assert pool.get("a:1").role == "any"


def test_effective_role_backfills_from_healthz():
    ep = _ep("a:1")  # discovery says nothing
    ep.mark_probe_success({"status": "ok", "role": "decode",
                           "saturation": {}})
    assert ep.effective_role() == "decode"
    assert ep.serves_phase("decode") and not ep.serves_phase("prefill")
    # Discovery wins over the reported role once it names one.
    ep.role = "prefill"
    assert ep.effective_role() == "prefill"
    # Malformed healthz role degrades.
    ep2 = _ep("b:1")
    ep2.mark_probe_success({"status": "ok", "role": 42,
                            "saturation": {}})
    assert ep2.effective_role() == "any"


def test_snapshot_carries_role_and_shards():
    ep = _ep("a:1", role="decode")
    ep.saturation = {"m": {"sharding": {"num_shards": 2}},
                     "n": {"sharding": "garbage"}}  # degrades
    snap = ep.snapshot()
    assert snap["role"] == "decode"
    assert snap["shard_count"] == 2


# --- role-aware balancer ---------------------------------------------------

def test_role_balancer_routes_by_phase():
    b = make_balancer("role")
    assert isinstance(b, RoleAwareBalancer)
    pre, dec, anyr = (_ep("p:1", "prefill"), _ep("d:1", "decode"),
                      _ep("x:1", "any"))
    cands = [pre, dec, anyr]
    for _ in range(4):
        assert b.pick(cands, phase="prefill") in (pre, anyr)
        assert b.pick(cands, phase="decode") in (dec, anyr)
    # Phase-less requests may land anywhere.
    assert b.pick(cands) in cands


def test_role_balancer_falls_back_when_pool_missing():
    b = RoleAwareBalancer()
    dec = _ep("d:1", "decode")
    # No prefill replica discovered: availability beats specialization.
    assert b.pick([dec], phase="prefill") is dec


def test_role_balancer_overflows_on_overload():
    b = RoleAwareBalancer(overload_ms=10.0)
    pre = _ep("p:1", "prefill", score=1000.0)  # saturated
    dec = _ep("d:1", "decode", score=0.0)
    assert b.pick([pre, dec], phase="prefill") is dec
    # Everyone overloaded: still prefer the matching pool.
    dec.saturation = {"m": {"queue_depth": 1000,
                            "est_batch_latency_ms": 1.0}}
    assert b.pick([pre, dec], phase="prefill") is pre


def test_classify_generate_phase():
    from kubeflow_tpu.serving.http_proxy import classify_generate_phase

    assert classify_generate_phase([[1] * 160], 8) == "prefill"
    assert classify_generate_phase([[1] * 8], 64) == "decode"
    assert classify_generate_phase([[1] * 32], None) == "prefill"
    assert classify_generate_phase("garbage", 8) == "decode"
    # A malformed budget must classify (→ 400 from the backend),
    # never raise out of the proxy (→ 500).
    assert classify_generate_phase([[1] * 8], "abc") == "decode"
    assert classify_generate_phase([[1] * 8], [3]) == "decode"


def test_endpoints_file_non_dict_entry_keeps_last_good(tmp_path):
    path = tmp_path / "endpoints.json"
    write_endpoints_file(str(path), [("a:8500", None)])
    source = FileEndpointSource(str(path))
    assert source.specs() == [("a:8500", None)]
    # Hand-edited garbage entry (a bare int): the reader must keep
    # the last good membership, not raise AttributeError on .get.
    path.write_text(json.dumps({"endpoints": [
        {"address": "b:8500"}, 42]}))
    assert source.specs() == [("a:8500", None)]


def test_collector_plus_slot_occupancy_refused():
    from kubeflow_tpu.scaling.autoscaler import (
        Autoscaler,
        AutoscalerConfig,
        AutoscalerLoop,
    )

    with pytest.raises(ValueError, match="slot_occupancy"):
        AutoscalerLoop(
            Autoscaler(AutoscalerConfig(signal="slot_occupancy"),
                       _FakeScaler()),
            discover=lambda: [], collector=object())


# --- the engine handoff seam ----------------------------------------------

@pytest.fixture(scope="module")
def toy():
    model = llama_test(dtype=jnp.float32, cache_size=CACHE)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, PROMPT_LEN), jnp.int32))
    return model, variables["params"]


def _engine(toy, name, temperature=0.8):
    model, params = toy
    return DecodeEngine(model, params, EngineConfig(
        max_new_tokens=NEW_TOKENS, max_prompt_len=PROMPT_LEN,
        temperature=temperature, num_slots=2, page_size=4,
        slice_tokens=2, seed=0), name=name)


def test_handoff_resumes_bitwise_across_engines(toy):
    eng_a, eng_b = _engine(toy, "a"), _engine(toy, "b")
    try:
        prompt = np.asarray(jax.random.randint(
            jax.random.PRNGKey(3), (PROMPT_LEN,), 0, 512))
        key = np.asarray(jax.random.PRNGKey(7))
        local = eng_a.submit(prompt, rng=key).result(timeout=120)
        handoff = eng_a.run_prefill(prompt, rng=key)
        blob = wire.encode_kv_handoff("m", 1, handoff)
        resumed = eng_b.submit(
            handoff=wire.decode_kv_handoff(blob, model="m",
                                           version=1)
        ).result(timeout=120)
        np.testing.assert_array_equal(local, resumed)
    finally:
        eng_a.stop()
        eng_b.stop()


def test_handoff_greedy_and_short_budget(toy):
    eng = _engine(toy, "g", temperature=0.0)
    try:
        prompt = np.asarray([5, 6, 7], np.int32)
        local = eng.submit(prompt, max_new_tokens=3).result(timeout=120)
        handoff = eng.run_prefill(prompt, max_new_tokens=3)
        resumed = eng.submit(handoff=handoff).result(timeout=120)
        np.testing.assert_array_equal(local, resumed)
        # A caller budget that disagrees with the handoff's schedule
        # is rejected (it would fork the rng stream).
        with pytest.raises(ValueError, match="step-key"):
            eng.submit(handoff=handoff, max_new_tokens=5)
    finally:
        eng.stop()


def test_handoff_blob_validation(toy):
    eng = _engine(toy, "v")
    try:
        handoff = eng.run_prefill(np.asarray([5, 6, 7], np.int32))
        blob = wire.encode_kv_handoff("m", 3, handoff)
        with pytest.raises(ValueError, match="model"):
            wire.decode_kv_handoff(blob, model="other")
        with pytest.raises(ValueError, match="version 3"):
            wire.decode_kv_handoff(blob, model="m", version=4)
        with pytest.raises(ValueError, match="malformed"):
            wire.decode_kv_handoff(b"junk", model="m")
    finally:
        eng.stop()


# --- proxy orchestration e2e ----------------------------------------------

@pytest.fixture(scope="module")
def role_stack(tmp_path_factory):
    """Two REAL servers over one export — a prefill-role and a
    decode-role replica — plus the pooled proxy with the role
    balancer and KV-handoff splitting enabled."""
    import asyncio

    from kubeflow_tpu.serving.export import export_model
    from kubeflow_tpu.serving.manager import ModelManager
    from kubeflow_tpu.serving.signature import (
        ModelMetadata,
        Signature,
        TensorSpec,
    )

    base = tmp_path_factory.mktemp("role") / "m"
    model = llama_test(dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, PROMPT_LEN), jnp.int32))
    meta = ModelMetadata(
        model_name="m", registry_name="llama-test",
        model_kwargs={"dtype": "float32", "cache_size": CACHE},
        signatures={"serving_default": Signature(
            "generate",
            {"input_ids": TensorSpec("int32", (-1, PROMPT_LEN))},
            {"tokens": TensorSpec("int32", (-1, NEW_TOKENS))})},
        generate_config={"max_new_tokens": NEW_TOKENS,
                         "temperature": 0.8, "seed": 11,
                         "deterministic": True,
                         "engine_slots": 2, "engine_page_size": 8,
                         "engine_slice_tokens": 2})
    export_model(str(base), 1, meta, {"params": variables["params"]})

    from kubeflow_tpu.serving.http_proxy import make_app as proxy_app
    from kubeflow_tpu.serving.server import make_app as rest_app

    managers, holders = [], []

    def serve(factory, holder, started):
        import tornado.ioloop

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        server = factory().listen(0)
        holder["port"] = next(iter(
            server._sockets.values())).getsockname()[1]
        holder["loop"] = tornado.ioloop.IOLoop.current()
        started.set()
        holder["loop"].start()

    for role in ("prefill", "decode"):
        mgr = ModelManager(poll_interval_s=3600)
        mgr.add_model("m", str(base), max_batch=4,
                      continuous_batching=True)
        managers.append(mgr)
        holder, started = {"role": role}, threading.Event()
        threading.Thread(
            target=serve,
            args=(lambda m=mgr, r=role: rest_app(m, role=r), holder,
                  started),
            daemon=True).start()
        assert started.wait(60)
        holders.append(holder)

    pool = EndpointPool()
    for holder in holders:
        pool.add(f"127.0.0.1:{holder['port']}", None, holder["role"])
    proxy, started = {}, threading.Event()
    threading.Thread(
        target=serve,
        args=(lambda: proxy_app(pool=pool, balancer="role",
                                probe_interval_s=3600.0), proxy,
              started),
        daemon=True).start()
    assert started.wait(60)
    yield {"base": base, "proxy": proxy, "holders": holders,
           "managers": managers, "pool": pool}
    for holder in holders + [proxy]:
        holder["loop"].add_callback(holder["loop"].stop)
    for mgr in managers:
        mgr.stop()


def _proxy_generate(stack, instances, timeout=60):
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{stack['proxy']['port']}/model/m:generate",
        data=json.dumps({"instances": instances}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def test_split_generate_bitwise_and_actually_split(role_stack):
    """The acceptance wiring: a :generate through the role proxy runs
    prefill on the prefill replica, hands the KV off, decodes on the
    decode replica — and the sampled tokens are bitwise equal to a
    single-replica run."""
    from kubeflow_tpu.serving.model import load_version

    pre_mgr, dec_mgr = role_stack["managers"]
    pre_engine = pre_mgr.get_model("m").get_resident().engine
    dec_engine = dec_mgr.get_model("m").get_resident().engine
    # Warmup traffic at load admitted slots on both; the REQUEST's
    # footprint is the delta.
    pre_before = pre_engine.stats()["admitted"]
    dec_before = dec_engine.stats()["admitted"]
    prompt = [[7] * PROMPT_LEN]
    out = _proxy_generate(role_stack, prompt)
    single = load_version(str(role_stack["base"] / "1"), max_batch=4)
    expect = single.run({"input_ids": np.asarray(prompt)})["tokens"]
    np.testing.assert_array_equal(
        np.asarray(out["predictions"][0]["tokens"]), expect[0])
    single.close()
    # White-box: the decode replica admitted the slot; the prefill
    # replica ran prefill-only (no slot taken).
    assert dec_engine.stats()["admitted"] == dec_before + 1
    assert pre_engine.stats()["admitted"] == pre_before


def test_split_survives_short_prompt_and_more_rows(role_stack):
    from kubeflow_tpu.serving.model import load_version

    single = load_version(str(role_stack["base"] / "1"), max_batch=4)
    for instances in ([[3, 4, 5]], [[9] * PROMPT_LEN, [1] * PROMPT_LEN]):
        out = _proxy_generate(role_stack, instances)
        expect = single.run(
            {"input_ids": np.asarray(instances)})["tokens"]
        got = np.asarray([row["tokens"] for row in out["predictions"]])
        np.testing.assert_array_equal(got, expect)
    single.close()


def test_split_streaming_tokens_bitwise(role_stack):
    """SSE streaming through the role proxy: prefill hop on the
    prefill replica, token stream relayed from the decode replica —
    same tokens as the single-replica path."""
    import http.client

    from kubeflow_tpu.serving.model import load_version

    prompt = [[2, 3, 4, 5]]
    conn = http.client.HTTPConnection(
        "127.0.0.1", role_stack["proxy"]["port"], timeout=60)
    conn.request(
        "POST", "/model/m:generate",
        body=json.dumps({"instances": prompt, "stream": True}),
        headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.headers["Content-Type"].startswith("text/event-stream")
    tokens, done = [], None
    for event, data in wire.iter_sse_events(resp):
        if event == "token":
            tokens.append(data["token"])
        elif event == "done":
            done = data
    conn.close()
    assert done is not None, "stream ended without the done event"
    single = load_version(str(role_stack["base"] / "1"), max_batch=4)
    expect = single.run({"input_ids": np.asarray(prompt)})["tokens"]
    np.testing.assert_array_equal(np.asarray(done["tokens"][0]),
                                  expect[0])
    np.testing.assert_array_equal(
        np.asarray(tokens), expect[0][:len(tokens)])
    single.close()


def test_prefill_only_without_engine_is_unimplemented(tmp_path):
    """A model NOT served with continuous batching answers the
    handoff verbs with the structured UNIMPLEMENTED code — the signal
    the proxy uses to remember 'skip the split', distinct from a
    per-request 400 (which must NOT poison split routing)."""
    import tornado.testing

    from kubeflow_tpu.serving.export import export_model
    from kubeflow_tpu.serving.manager import ModelManager
    from kubeflow_tpu.serving.server import make_app
    from kubeflow_tpu.serving.signature import (
        ModelMetadata,
        Signature,
        TensorSpec,
    )

    base = tmp_path / "plain"
    model = llama_test(dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, PROMPT_LEN), jnp.int32))
    meta = ModelMetadata(
        model_name="plain", registry_name="llama-test",
        model_kwargs={"dtype": "float32", "cache_size": CACHE},
        signatures={"serving_default": Signature(
            "generate",
            {"input_ids": TensorSpec("int32", (-1, PROMPT_LEN))},
            {"tokens": TensorSpec("int32", (-1, NEW_TOKENS))})},
        generate_config={"max_new_tokens": NEW_TOKENS,
                         "temperature": 0.0})
    export_model(str(base), 1, meta, {"params": variables["params"]})

    class _Case(tornado.testing.AsyncHTTPTestCase):
        def get_app(self):
            mgr = ModelManager(poll_interval_s=3600)
            mgr.add_model("plain", str(base), max_batch=4)
            self.mgr = mgr
            return make_app(mgr)

        def runTest(self):
            resp = self.fetch(
                "/v1/models/plain:generate", method="POST",
                body=json.dumps({"instances": [[1, 2]],
                                 "prefill_only": True}))
            assert resp.code == 400
            assert json.loads(resp.body)["code"] == "UNIMPLEMENTED"
            resp = self.fetch(
                "/v1/models/plain:generate", method="POST",
                body=json.dumps({"handoffs": ["AAAA"]}))
            assert resp.code == 400
            assert json.loads(resp.body)["code"] == "UNIMPLEMENTED"
            self.mgr.stop()

    case = _Case()
    case.setUp()
    try:
        case.runTest()
    finally:
        case.tearDown()


def test_proxy_healthz_lists_roles(role_stack):
    import urllib.request

    with urllib.request.urlopen(
            f"http://127.0.0.1:{role_stack['proxy']['port']}/healthz",
            timeout=10) as resp:
        payload = json.loads(resp.read())
    roles = sorted(ep["role"] for ep in payload["endpoints"].values())
    assert roles == ["decode", "prefill"]


# --- per-pool autoscaling ---------------------------------------------------

class _FakeScaler:
    def __init__(self, replicas=2):
        self.replicas = replicas
        self.sets = []

    def get_replicas(self):
        return self.replicas

    def set_replicas(self, n):
        self.sets.append(n)
        self.replicas = n


def test_autoscaler_slot_occupancy_signal():
    from kubeflow_tpu.scaling.autoscaler import (
        Autoscaler,
        AutoscalerConfig,
    )

    scaler = _FakeScaler(replicas=2)
    clock = [0.0]
    autoscaler = Autoscaler(
        AutoscalerConfig(min_replicas=1, max_replicas=8,
                         signal="slot_occupancy",
                         target_slot_occupancy=0.8,
                         scale_up_cooldown_s=0.0),
        scaler, clock=lambda: clock[0])
    # Full slots → occupancy 1.0 / 0.8 = 1.25 > 1.2 → scale up.
    decision = autoscaler.evaluate(
        [{"slot_occupancy": 1.0, "queue_wait_ms": 0.0},
         {"slot_occupancy": 1.0, "queue_wait_ms": 0.0}])
    assert decision["action"] == "scale_up"
    assert decision["signal"] == "slot_occupancy"
    # A replica WITHOUT engine stats reads fully occupied (blind
    # capacity is never counted as headroom).
    clock[0] += 100.0
    decision = autoscaler.evaluate(
        [{"queue_wait_ms": 0.0}, {"queue_wait_ms": 0.0}])
    assert decision["action"] in ("scale_up", "hold")
    assert decision["ratio"] >= 1.0


def test_replica_sample_extracts_engine_signals():
    from kubeflow_tpu.scaling.autoscaler import (
        Autoscaler,
        AutoscalerConfig,
        AutoscalerLoop,
    )

    loop = AutoscalerLoop(
        Autoscaler(AutoscalerConfig(), _FakeScaler()),
        discover=lambda: [])
    row = loop._replica_sample("a:1", {
        "status": "ok", "role": "decode",
        "saturation": {"m": {
            "queue_depth": 0, "est_batch_latency_ms": 5.0,
            "shed": 0, "expired": 0,
            "engine": {"slots": 4, "active_slots": 3,
                       "queue_depth": 2, "est_ttft_ms": 10.0},
            "sharding": {"num_shards": 2},
        }}}, now=1.0)
    assert row["slot_occupancy"] == 0.75
    assert row["role"] == "decode"
    assert row["shards"] == 2
    assert row["queue_wait_ms"] == 20.0  # engine queue priced in
    # Malformed engine stats degrade, never raise.
    row2 = loop._replica_sample("b:1", {
        "status": "ok",
        "saturation": {"m": {"engine": {"slots": "x"}}}}, now=2.0)
    assert row2["reachable"]


def test_role_split_loop_merges_endpoints_and_decisions(tmp_path):
    from kubeflow_tpu.scaling.autoscaler import (
        Autoscaler,
        AutoscalerConfig,
        AutoscalerLoop,
        RoleSplitAutoscalerLoop,
    )

    def loop_for(role, payload):
        return AutoscalerLoop(
            Autoscaler(AutoscalerConfig(
                signal=("slot_occupancy" if role == "decode"
                        else "queue_wait")), _FakeScaler()),
            discover=lambda r=role: [(f"{r}:8500", None)],
            scrape=lambda addr, p=payload: p)

    pools = {
        "prefill": loop_for("prefill", {
            "status": "ok",
            "saturation": {"m": {"queue_depth": 1,
                                 "est_batch_latency_ms": 10.0}}}),
        "decode": loop_for("decode", {
            "status": "ok",
            "saturation": {"m": {
                "engine": {"slots": 4, "active_slots": 2}}}}),
    }
    path = tmp_path / "endpoints.json"
    coordinator = RoleSplitAutoscalerLoop(
        pools, write_endpoints_path=str(path))
    decisions = coordinator.tick()
    assert set(decisions) == {"prefill", "decode"}
    assert decisions["decode"]["signal"] == "slot_occupancy"
    specs = FileEndpointSource(str(path)).specs()
    assert sorted(normalize_spec(s) for s in specs) == [
        ("decode:8500", None, "decode"),
        ("prefill:8500", None, "prefill")]
    roles = {row["role"] for row in coordinator.last_fleet}
    assert roles == {"prefill", "decode"}
    coordinator.stop()


def test_role_split_loop_refuses_publishing_pools(tmp_path):
    from kubeflow_tpu.scaling.autoscaler import (
        Autoscaler,
        AutoscalerConfig,
        AutoscalerLoop,
        RoleSplitAutoscalerLoop,
    )

    bad = AutoscalerLoop(
        Autoscaler(AutoscalerConfig(), _FakeScaler()),
        discover=lambda: [],
        write_endpoints_path=str(tmp_path / "x.json"))
    with pytest.raises(ValueError, match="coordinator owns"):
        RoleSplitAutoscalerLoop({"prefill": bad})


# --- dashboard degrade ------------------------------------------------------

def test_dashboard_fleet_section_renders_roles_and_degrades():
    from kubeflow_tpu.dashboard.server import _fleet_section_html

    html = _fleet_section_html({
        "replicas": [
            {"address": "a:8500", "reachable": True, "role": "decode",
             "slot_occupancy": 0.5, "shards": 2,
             "queue_wait_ms": 1.0, "shed_rate": 0.0,
             "resident_models": ["m"]},
            {"address": "b:8500", "reachable": True,
             "role": "mystery-role", "shards": "garbage",
             "queue_wait_ms": 1.0, "shed_rate": 0.0,
             "resident_models": []},
        ],
        "decisions": {
            "decode": {"action": "hold", "reason": "within",
                       "signal": "slot_occupancy", "current": 2,
                       "desired": 2, "mean_queue_wait_ms": 0.0,
                       "target_queue_wait_ms": 100.0, "age_s": 1.0},
        },
    })
    assert "decode (50% slots)" in html
    assert "mystery-role" not in html  # degraded to any
    assert "slot_occupancy" in html
    # Malformed fleet never raises out of the renderer.
    assert "unreadable" in _fleet_section_html(
        {"replicas": object()})
