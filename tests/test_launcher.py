# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Launcher tests: env parsing, subprocess streaming, stock fallback."""

import logging

from kubeflow_tpu.training import launcher


def test_distributed_config_absent():
    assert launcher.distributed_config(env={}) is None


def test_distributed_config_parsed():
    env = {
        launcher.ENV_COORD: "job-tpu-worker-0.job:8476",
        launcher.ENV_NPROC: "4",
        launcher.ENV_PID: "2",
    }
    cfg = launcher.distributed_config(env=env)
    assert cfg == {
        "coordinator_address": "job-tpu-worker-0.job:8476",
        "num_processes": 4,
        "process_id": 2,
    }


def test_initialize_single_process_noop():
    assert launcher.initialize_distributed(env={}) is False
    # num_processes=1 also short-circuits (no coordinator dial-out).
    assert launcher.initialize_distributed(env={
        launcher.ENV_COORD: "x:1", launcher.ENV_NPROC: "1",
        launcher.ENV_PID: "0"}) is False


def test_run_and_stream_logs_and_exit_code(caplog):
    with caplog.at_level(logging.INFO):
        rc = launcher.run_and_stream(
            ["python", "-c", "print('hello-from-child'); print('line2')"])
    assert rc == 0
    messages = [r.message for r in caplog.records]
    assert "hello-from-child" in messages
    assert "line2" in messages


def test_run_and_stream_nonzero_exit():
    rc = launcher.run_and_stream(["python", "-c", "import sys; sys.exit(3)"])
    assert rc == 3


def test_launch_runs_user_command(monkeypatch):
    rc = launcher.launch(["python", "-c", "pass"], env={})
    assert rc == 0
