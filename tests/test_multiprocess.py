"""Real multi-process gang tests: 2 jax.distributed processes (Gloo
over loopback — the DCN stand-in), operator env contract → launcher
bootstrap → SPMD train steps on the global mesh.

This is the tier the reference could only run on a live GKE cluster
(SURVEY §4); here it's hermetic. Both processes must converge to the
SAME loss — the gradient all-reduce across processes is the thing
under test. Two layouts:

- flat data-parallel resnet (2×2 devices);
- the BASELINE multi-host BERT row: hierarchical dcn_data=2 × data=4
  mesh (2×4 devices) with the cross-slice axis on the process
  boundary — the coordinator + DCN-spanning-mesh combination, not its
  single-process dryrun emulation (VERDICT-r3 weak #2).
"""

import os
import re
import socket
import subprocess
import sys
from pathlib import Path

import pytest

WORKER = Path(__file__).parent / "_gang_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_gang(mode: str, local_devices: int):
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            KFT_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            KFT_NUM_PROCESSES="2",
            KFT_PROCESS_ID=str(pid),
            KFT_REPLICA_TYPE="TPU_WORKER",
            KFT_REPLICA_INDEX=str(pid),
            KFT_GANG_MODE=mode,
            KFT_LOCAL_DEVICES=str(local_devices),
        )
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={local_devices}")
        procs.append(subprocess.Popen(
            [sys.executable, str(WORKER)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outputs = []
    for p in procs:
        out, _ = p.communicate(timeout=420)
        outputs.append(out)
        assert p.returncode == 0, out[-2000:]
    losses = []
    for out in outputs:
        m = re.search(
            rf"GANG_OK mode={mode} process=(\d) "
            rf"devices={2 * local_devices} loss=([0-9.]+)", out)
        assert m, out[-2000:]
        losses.append(float(m.group(2)))
    return losses


@pytest.mark.slow
def test_two_process_gang_trains_to_identical_loss():
    losses = _run_gang("resnet", local_devices=2)
    # The all-reduce makes the state identical on both hosts.
    assert losses[0] == losses[1], losses


@pytest.mark.slow
def test_two_process_bert_dcn_hierarchical_mesh():
    """BASELINE row 3 end-to-end: BERT MLM over a dcn_data=2 × data=4
    mesh whose outer axis crosses the process boundary. The
    cross-slice gradient reduction rides the jax.distributed
    transport; both processes end at the same loss."""
    losses = _run_gang("bert_dcn", local_devices=4)
    assert losses[0] == losses[1], losses
