# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Real multi-process gang tests: jax.distributed processes (Gloo
over loopback — the DCN stand-in), operator env contract → launcher
bootstrap → SPMD train steps on the global mesh.

This is the tier the reference could only run on a live GKE cluster
(SURVEY §4); here it's hermetic. All processes must converge to the
SAME loss — the gradient all-reduce across processes is the thing
under test. Three layouts:

- flat data-parallel resnet (2 procs × 2 devices);
- the BASELINE multi-host BERT row: hierarchical dcn_data=2 × data=4
  mesh (2 procs × 4 devices) with the cross-slice axis on the process
  boundary — the coordinator + DCN-spanning-mesh combination, not its
  single-process dryrun emulation (VERDICT-r3 weak #2);
- the multi-slice (megascale) operator contract: 4 procs as 2 slices
  × 2 hosts, dcn_data derived from the injected MEGASCALE env
  (VERDICT-r4 next #1/#7).
"""

import os
import re
import signal
import socket
import subprocess
import sys
from pathlib import Path

import pytest

WORKER = Path(__file__).parent / "_gang_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_gang(mode: str, local_devices: int, n_procs: int = 2,
              num_slices: int = 1):
    # The subprocess env comes from the RECONCILER's own pod specs
    # (tests/test_env_contract.py reconciled_pod_envs — the contract's
    # single source of truth; the pre-r7 version hand-mirrored the
    # operator's env construction here). Only the network addresses
    # are substituted: pod-DNS coordinators become loopback ports.
    from tests.test_env_contract import (
        make_contract_job,
        reconciled_pod_envs,
    )

    assert n_procs % num_slices == 0
    pod_envs = reconciled_pod_envs(make_contract_job(
        name="gang", workers=n_procs // num_slices,
        num_slices=num_slices))
    assert len(pod_envs) == n_procs
    port = _free_port()
    procs = []
    # Launch in the operator's slice-major process-id order.
    for pod_name, pod_env in sorted(
            pod_envs.items(),
            key=lambda kv: int(kv[1]["KFT_PROCESS_ID"])):
        env = dict(os.environ)
        env.update(pod_env)
        env.update(
            JAX_PLATFORMS="cpu",
            KFT_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            KFT_GANG_MODE=mode,
            KFT_LOCAL_DEVICES=str(local_devices),
            XLA_FLAGS=(
                f"--xla_force_host_platform_device_count={local_devices}"),
        )
        if "MEGASCALE_COORDINATOR_ADDRESS" in env:
            env["MEGASCALE_COORDINATOR_ADDRESS"] = \
                f"127.0.0.1:{port + 1}"
        # TPU_WORKER_HOSTNAMES carries pod DNS names that don't
        # resolve here; the CPU backend ignores TPU runtime vars, but
        # drop them anyway so a future TPU-sim path can't half-bind.
        env.pop("TPU_WORKER_HOSTNAMES", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(WORKER)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outputs = []
    for p in procs:
        out, _ = p.communicate(timeout=420)
        outputs.append(out)
        assert p.returncode == 0, out[-2000:]
    losses = []
    for out in outputs:
        m = re.search(
            rf"GANG_OK mode={mode} process=(\d) "
            rf"devices={n_procs * local_devices} loss=([0-9.]+)", out)
        assert m, out[-2000:]
        losses.append(float(m.group(2)))
    return losses


@pytest.mark.slow
def test_two_process_gang_trains_to_identical_loss():
    losses = _run_gang("resnet", local_devices=2)
    # The all-reduce makes the state identical on both hosts.
    assert losses[0] == losses[1], losses


@pytest.mark.slow
def test_two_process_bert_dcn_hierarchical_mesh():
    """BASELINE row 3 end-to-end: BERT MLM over a dcn_data=2 × data=4
    mesh whose outer axis crosses the process boundary. The
    cross-slice gradient reduction rides the jax.distributed
    transport; both processes end at the same loss."""
    losses = _run_gang("bert_dcn", local_devices=4)
    assert losses[0] == losses[1], losses


@pytest.mark.slow
def test_pretrain_cli_joins_megascale_gang(tmp_path):
    """The REAL pod command end-to-end: 4 × `python -m
    kubeflow_tpu.training.pretrain` processes under the exact env the
    operator injects for a 2-slice × 2-host tpu-lm job. The CLI must
    join the jax.distributed gang ITSELF (r5 fix: neither trainer CLI
    called initialize_distributed — each host silently trained an
    independent model copy; the earlier gang tests masked it by
    bootstrapping in the test worker) and derive dcn_data=2 from the
    MEGASCALE env. Identical per-step losses across all four hosts
    prove the cross-host gradient sync."""
    import json

    from tests.test_env_contract import (
        make_contract_job,
        reconciled_pod_envs,
    )

    port = _free_port()
    procs = []
    pod_envs = reconciled_pod_envs(make_contract_job(
        name="gang", workers=2, num_slices=2))
    for pid, (pod_name, pod_env) in enumerate(sorted(
            pod_envs.items(),
            key=lambda kv: int(kv[1]["KFT_PROCESS_ID"]))):
        assert int(pod_env["KFT_PROCESS_ID"]) == pid
        env = dict(os.environ)
        env.update(pod_env)
        env.update(
            JAX_PLATFORMS="cpu",
            KFT_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            MEGASCALE_COORDINATOR_ADDRESS=f"127.0.0.1:{port + 1}",
            XLA_FLAGS="--xla_force_host_platform_device_count=2",
        )
        env.pop("TPU_WORKER_HOSTNAMES", None)
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "kubeflow_tpu.training.pretrain",
             "--model", "bert-test", "--global_batch", "16",
             "--seq_len", "16", "--steps", "3", "--log_every", "1",
             "--mesh", "data=4",
             "--metrics_path", str(tmp_path / f"m{pid}.jsonl")],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=str(Path(__file__).parent.parent)))
    outputs = []
    for p in procs:
        out, _ = p.communicate(timeout=420)
        outputs.append(out)
        assert p.returncode == 0, out[-2000:]
    # Process 0 reports the resolved mesh: the dcn axis came from env.
    summary = json.loads(outputs[0].strip().splitlines()[-1])
    assert summary["mesh"]["dcn_data"] == 2, summary
    assert summary["mesh"]["data"] == 4, summary
    assert summary["final_step"] == 3
    final_losses = []
    for pid in range(4):
        lines = (tmp_path / f"m{pid}.jsonl").read_text().splitlines()
        final_losses.append(json.loads(lines[-1])["loss"])
    assert len(set(final_losses)) == 1, final_losses


@pytest.mark.slow
def test_two_process_gang_drains_collectively(tmp_path):
    """Preemption hits ONE host of a 2-process gang (SIGTERM to
    process 1 only). The drain-flag allgather must propagate the
    verdict so BOTH processes exit DRAIN_EXIT_CODE at the SAME step
    with the collective checkpoint durable — a unilateral drain would
    instead deadlock the peer inside the train-step psum until
    SIGKILL (budget-burning crash)."""
    import json
    import time

    from kubeflow_tpu.training.launcher import DRAIN_EXIT_CODE

    port = _free_port()
    ckpt_dir = tmp_path / "ckpt"
    metrics = [tmp_path / "m0.jsonl", tmp_path / "m1.jsonl"]
    procs = []
    for pid in range(2):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            KFT_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            KFT_NUM_PROCESSES="2",
            KFT_PROCESS_ID=str(pid),
            KFT_REPLICA_TYPE="TPU_WORKER",
            KFT_REPLICA_INDEX=str(pid),
            KFT_GANG_MODE="drain",
            KFT_LOCAL_DEVICES="2",
            KFT_DRAIN_CKPT=str(ckpt_dir),
            KFT_DRAIN_METRICS=str(metrics[pid]),
            XLA_FLAGS="--xla_force_host_platform_device_count=2",
        )
        procs.append(subprocess.Popen(
            [sys.executable, str(WORKER)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    # Wait for demonstrable progress on both hosts, then preempt ONE.
    deadline = time.time() + 300
    while time.time() < deadline:
        if all(m.exists() and len(m.read_text().splitlines()) >= 3
               for m in metrics):
            break
        for p in procs:
            if p.poll() is not None:
                out, _ = p.communicate()
                raise AssertionError(f"worker died early:\n{out[-2000:]}")
        time.sleep(0.3)
    else:
        for p in procs:
            p.kill()
        raise AssertionError("gang never reached step 3")
    procs[1].send_signal(signal.SIGTERM)

    steps = []
    for p in procs:
        out, _ = p.communicate(timeout=180)
        assert p.returncode == DRAIN_EXIT_CODE, out[-2000:]
        m = re.search(r"GANG_DRAINED process=(\d) step=(\d+) ckpt=True",
                      out)
        assert m, out[-2000:]
        steps.append(int(m.group(2)))
    # Both hosts agreed on the drain step (the allgather worked).
    assert steps[0] == steps[1], steps
    # The collective checkpoint is durable at exactly that step.
    latest = json.loads((tmp_path / "m0.jsonl").read_text()
                        .splitlines()[-1])
    assert latest["step"] <= steps[0]
    step_dirs = [d.name for d in ckpt_dir.iterdir() if d.is_dir()]
    assert str(steps[0]) in step_dirs, (steps, step_dirs)


@pytest.mark.slow
def test_four_process_two_slice_megascale_gang():
    """The multi-slice operator contract across REAL process
    boundaries: 4 processes as 2 slices × 2 hosts, topology described
    ONLY by the injected MEGASCALE_* + KFT_* env (exactly what the
    reconciler writes into a numSlices=2 job's pods). The worker
    derives its dcn_data axis from the env inside build_mesh, asserts
    the slice boundary falls between process pairs, and trains BERT
    MLM; all four processes must end at the same loss — the
    cross-slice gradient all-reduce is the thing under test."""
    losses = _run_gang("bert_dcn_megascale", local_devices=2,
                       n_procs=4, num_slices=2)
    assert len(set(losses)) == 1, losses
