"""Real multi-process gang test: 2 jax.distributed processes (Gloo
over loopback — the DCN stand-in), operator env contract → launcher
bootstrap → one SPMD train step on the global 4-device mesh.

This is the tier the reference could only run on a live GKE cluster
(SURVEY §4); here it's hermetic. Both processes must converge to the
SAME loss — the gradient all-reduce across processes is the thing
under test."""

import os
import re
import socket
import subprocess
import sys
from pathlib import Path

import pytest

WORKER = Path(__file__).parent / "_gang_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_gang_trains_to_identical_loss():
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            KFT_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            KFT_NUM_PROCESSES="2",
            KFT_PROCESS_ID=str(pid),
            KFT_REPLICA_TYPE="TPU_WORKER",
            KFT_REPLICA_INDEX=str(pid),
        )
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        procs.append(subprocess.Popen(
            [sys.executable, str(WORKER)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outputs = []
    for p in procs:
        out, _ = p.communicate(timeout=420)
        outputs.append(out)
        assert p.returncode == 0, out[-2000:]
    losses = []
    for out in outputs:
        m = re.search(r"GANG_OK process=(\d) devices=4 loss=([0-9.]+)", out)
        assert m, out[-2000:]
        losses.append(float(m.group(2)))
    # The all-reduce makes the state identical on both hosts.
    assert losses[0] == losses[1], losses
