# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Operator tests against the fake apiserver: gang creation, env
injection, whole-slice restart, chief success, restart budget."""

import pytest

from kubeflow_tpu.manifests.tpujob import replica_spec, termination_policy, tpu_job
from kubeflow_tpu.operator import FakeApiServer, Reconciler
from kubeflow_tpu.operator.controller import run_controller
from kubeflow_tpu.operator.gang import Decision, PodPhase, decide
from kubeflow_tpu.operator.reconciler import JOB_LABEL


def make_job(name="job1", workers=4, recovery="restart-slice",
             coordinator=False):
    specs = []
    if coordinator:
        specs.append(replica_spec("COORDINATOR", 1, image="img:1"))
    specs.append(replica_spec(
        "TPU_WORKER", workers, image="img:1",
        tpu_accelerator="tpu-v5-lite-podslice", tpu_topology="2x4"))
    chief = ("COORDINATOR", 0) if coordinator else ("TPU_WORKER", 0)
    job = tpu_job(name, "default", specs,
                  termination=termination_policy(*chief), recovery=recovery)
    job["metadata"]["uid"] = "uid-1"
    return job


def submit(api, job):
    api.create(job)
    return api.get("TPUJob", "default", job["metadata"]["name"])


# -- gang kernel ----------------------------------------------------------


def test_gang_decide_native_create_and_none():
    P = PodPhase
    assert decide([P.MISSING] * 4, 0, allow_restart=True, restarts=0,
                  max_restarts=3) == Decision.CREATE_MISSING
    assert decide([P.RUNNING] * 4, 0, allow_restart=True, restarts=0,
                  max_restarts=3) == Decision.NONE


def test_gang_decide_chief_success_wins():
    P = PodPhase
    # chief done, another worker failed: success wins (job completed).
    assert decide([P.SUCCEEDED, P.FAILED], 0, allow_restart=True,
                  restarts=0, max_restarts=3) == Decision.SUCCEED


def test_gang_decide_nonchief_success_holds_then_faults():
    P = PodPhase
    # A non-chief exiting while chief still runs is AMBIGUOUS —
    # completion skew on a finishing job or a genuine early exit. With
    # grace: hold and re-observe; with grace exhausted: it broke the
    # collective, restart.
    assert decide([P.RUNNING, P.SUCCEEDED], 0, allow_restart=True,
                  restarts=0, max_restarts=3,
                  completion_grace=True) == Decision.HOLD_COMPLETION
    assert decide([P.RUNNING, P.SUCCEEDED], 0, allow_restart=True,
                  restarts=0, max_restarts=3,
                  completion_grace=False) == Decision.RESTART_SLICE
    # A real pod failure never holds, grace or not.
    assert decide([P.RUNNING, P.SUCCEEDED, P.FAILED], 0,
                  allow_restart=True, restarts=0, max_restarts=3,
                  completion_grace=True) == Decision.RESTART_SLICE


def test_gang_decide_restart_budget():
    P = PodPhase
    assert decide([P.FAILED, P.RUNNING], 0, allow_restart=True,
                  restarts=2, max_restarts=3) == Decision.RESTART_SLICE
    assert decide([P.FAILED, P.RUNNING], 0, allow_restart=True,
                  restarts=3, max_restarts=3) == Decision.FAIL
    assert decide([P.FAILED, P.RUNNING], 0, allow_restart=False,
                  restarts=0, max_restarts=3) == Decision.FAIL


def test_gang_decide_degenerate():
    assert decide([], 0, allow_restart=True, restarts=0,
                  max_restarts=3) == Decision.FAIL


# -- reconciler -----------------------------------------------------------


def test_gang_created_atomically_with_env():
    api = FakeApiServer()
    job = submit(api, make_job(workers=4))
    r = Reconciler(api)
    assert r.reconcile(job) == "Pending"

    pods = api.list("Pod", "default", {JOB_LABEL: "job1"})
    assert len(pods) == 4  # whole gang in one pass
    svc = api.get("Service", "default", "job1")
    assert svc["spec"]["clusterIP"] == "None"

    pod0 = api.get("Pod", "default", "job1-tpu-worker-0")
    env = {e["name"]: e["value"] for e in
           pod0["spec"]["containers"][0]["env"]}
    assert env["KFT_COORDINATOR_ADDRESS"] == \
        "job1-tpu-worker-0.job1.default:8476"
    assert env["KFT_NUM_PROCESSES"] == "4"
    assert env["KFT_PROCESS_ID"] == "0"
    assert env["TPU_WORKER_ID"] == "0"
    assert len(env["TPU_WORKER_HOSTNAMES"].split(",")) == 4
    pod3 = api.get("Pod", "default", "job1-tpu-worker-3")
    env3 = {e["name"]: e["value"] for e in
            pod3["spec"]["containers"][0]["env"]}
    assert env3["KFT_PROCESS_ID"] == "3"
    assert env3["KFT_COORDINATOR_ADDRESS"] == env["KFT_COORDINATOR_ADDRESS"]
    # kubelet must not restart gang members individually
    assert pod0["spec"]["restartPolicy"] == "Never"
    assert pod0["spec"]["subdomain"] == "job1"


def test_running_then_chief_success_cleans_up():
    api = FakeApiServer()
    job = submit(api, make_job(workers=2))
    r = Reconciler(api)
    r.reconcile(job)
    api.set_all_pod_phases("default", "Running", {JOB_LABEL: "job1"})
    job = api.get("TPUJob", "default", "job1")
    assert r.reconcile(job) == "Running"

    # all workers succeed together (SPMD program finished everywhere)
    api.set_all_pod_phases("default", "Succeeded", {JOB_LABEL: "job1"})
    job = api.get("TPUJob", "default", "job1")
    assert r.reconcile(job) == "Succeeded"
    # terminal: no further reconcile effects
    assert r.reconcile(api.get("TPUJob", "default", "job1")) == "Succeeded"


def test_staggered_completion_does_not_burn_restarts():
    """Pod-status propagation is not atomic: a reconcile pass that
    sees worker-1 Succeeded while chief worker-0 still reads Running
    must NOT restart the slice (the round-2 verdict's completion
    race). The job must end Succeeded with restartCount == 0."""
    api = FakeApiServer()
    job = submit(api, make_job(workers=2))
    r = Reconciler(api)
    r.reconcile(job)
    api.set_all_pod_phases("default", "Running", {JOB_LABEL: "job1"})
    job = api.get("TPUJob", "default", "job1")
    assert r.reconcile(job) == "Running"

    # Worker 1's status lands first; chief still Running.
    api.set_pod_phase("default", "job1-tpu-worker-1", "Succeeded")
    job = api.get("TPUJob", "default", "job1")
    assert r.reconcile(job) == "Running"  # held, not restarted
    job = api.get("TPUJob", "default", "job1")
    assert job["status"]["restartCount"] == 0
    assert job["status"]["completionSkewPasses"] == 1
    # Both pods still exist — nothing was deleted.
    assert len(api.list("Pod", "default", {JOB_LABEL: "job1"})) == 2

    # Chief's status catches up on the next pass → clean success.
    api.set_pod_phase("default", "job1-tpu-worker-0", "Succeeded")
    job = api.get("TPUJob", "default", "job1")
    assert r.reconcile(job) == "Succeeded"
    job = api.get("TPUJob", "default", "job1")
    assert job["status"]["restartCount"] == 0


def test_completion_grace_exhaustion_is_a_slice_fault():
    """A worker that really did exit early (chief keeps Running well
    past the grace window) is a slice fault: collectives lost a
    participant, so the gang restarts once patience runs out."""
    api = FakeApiServer()
    job = submit(api, make_job(workers=2))
    r = Reconciler(api, completion_grace_passes=3)
    r.reconcile(job)
    api.set_all_pod_phases("default", "Running", {JOB_LABEL: "job1"})
    job = api.get("TPUJob", "default", "job1")
    r.reconcile(job)
    api.set_pod_phase("default", "job1-tpu-worker-1", "Succeeded")
    for expected_skew in (1, 2, 3):
        job = api.get("TPUJob", "default", "job1")
        assert r.reconcile(job) == "Running"
        job = api.get("TPUJob", "default", "job1")
        assert job["status"]["completionSkewPasses"] == expected_skew
    job = api.get("TPUJob", "default", "job1")
    assert r.reconcile(job) == "Restarting"
    job = api.get("TPUJob", "default", "job1")
    assert job["status"]["restartCount"] == 1
    # The hold counter resets on the non-hold decision.
    assert job["status"]["completionSkewPasses"] == 0


def test_slice_restart_on_worker_failure():
    api = FakeApiServer()
    job = submit(api, make_job(workers=4))
    r = Reconciler(api)
    r.reconcile(job)
    api.set_all_pod_phases("default", "Running", {JOB_LABEL: "job1"})
    api.set_pod_phase("default", "job1-tpu-worker-2", "Failed")

    job = api.get("TPUJob", "default", "job1")
    assert r.reconcile(job) == "Restarting"
    # ALL pods deleted, not just the failed one.
    assert api.list("Pod", "default", {JOB_LABEL: "job1"}) == []
    assert job["status"]["restartCount"] == 1

    # next pass recreates the full gang
    job = api.get("TPUJob", "default", "job1")
    assert r.reconcile(job) == "Running"  # restartCount>0 ⇒ Running state
    assert len(api.list("Pod", "default", {JOB_LABEL: "job1"})) == 4


def test_restart_budget_exhaustion_fails_job():
    api = FakeApiServer()
    job = submit(api, make_job(workers=2))
    r = Reconciler(api, max_restarts=1)
    r.reconcile(job)
    api.set_pod_phase("default", "job1-tpu-worker-0", "Failed")
    job = api.get("TPUJob", "default", "job1")
    assert r.reconcile(job) == "Restarting"
    job = api.get("TPUJob", "default", "job1")
    r.reconcile(job)  # recreate
    api.set_pod_phase("default", "job1-tpu-worker-1", "Failed")
    job = api.get("TPUJob", "default", "job1")
    assert r.reconcile(job) == "Failed"
    assert "exhausted" in job["status"]["reason"]


def test_recovery_none_fails_immediately():
    api = FakeApiServer()
    job = submit(api, make_job(workers=2, recovery="none"))
    r = Reconciler(api)
    r.reconcile(job)
    api.set_pod_phase("default", "job1-tpu-worker-0", "Failed")
    job = api.get("TPUJob", "default", "job1")
    assert r.reconcile(job) == "Failed"


def test_coordinator_chief_and_controller_loop():
    api = FakeApiServer()
    job = submit(api, make_job(workers=2, coordinator=True))
    run_controller(api, max_iterations=1)
    pods = api.list("Pod", "default", {JOB_LABEL: "job1"})
    assert len(pods) == 3
    coord = api.get("Pod", "default", "job1-coordinator-0")
    env = {e["name"]: e["value"] for e in
           coord["spec"]["containers"][0]["env"]}
    # Coordinator is not a TPU process: it gets its own 1-process view.
    assert env["KFT_NUM_PROCESSES"] == "1"
    # chief = coordinator; its success ends the job
    api.set_pod_phase("default", "job1-coordinator-0", "Succeeded")
    run_controller(api, max_iterations=1)
    assert api.get("TPUJob", "default", "job1")["status"]["phase"] == \
        "Succeeded"


def test_fake_apiserver_conflict_and_notfound():
    from kubeflow_tpu.operator.fake import Conflict, NotFound

    api = FakeApiServer()
    api.create({"kind": "Pod", "metadata": {"name": "p", "namespace": "ns"}})
    with pytest.raises(Conflict):
        api.create({"kind": "Pod",
                    "metadata": {"name": "p", "namespace": "ns"}})
    with pytest.raises(NotFound):
        api.get("Pod", "ns", "ghost")
    with pytest.raises(NotFound):
        api.delete("Pod", "ns", "ghost")


def test_restarting_holds_while_pods_terminate():
    """A real cluster deletes pods asynchronously: while the old gang
    lingers in Terminating (still listed, phase Failed), a resync must
    NOT burn another restart or recreate pods early."""
    api = FakeApiServer()
    job = submit(api, make_job(workers=2))
    r = Reconciler(api)
    r.reconcile(job)
    api.set_pod_phase("default", "job1-tpu-worker-0", "Failed")
    job = api.get("TPUJob", "default", "job1")
    assert r.reconcile(job) == "Restarting"

    # Simulate slow termination: put the old (failed) pods back, as a
    # real apiserver would still list them during the grace period.
    from kubeflow_tpu.operator.reconciler import ReplicaMember, expected_members
    for m in expected_members(job):
        pod = r._member_pod(job, m, expected_members(job))
        pod.setdefault("status", {})["phase"] = "Failed"
        api.create(pod)

    for _ in range(5):  # many resyncs while terminating
        job = api.get("TPUJob", "default", "job1")
        assert r.reconcile(job) == "Restarting"
    assert job["status"]["restartCount"] == 1  # no budget burned

    # Termination completes → next pass recreates the gang.
    for m in expected_members(job):
        api.delete("Pod", "default", m.pod_name("job1"))
    job = api.get("TPUJob", "default", "job1")
    assert r.reconcile(job) == "Running"
    assert len(api.list("Pod", "default", {JOB_LABEL: "job1"})) == 2


def test_status_conditions_track_lifecycle():
    """k8s-conventional status.conditions (the tf-operator's
    TFJobCondition surface): one entry per entered phase, exactly one
    True, transition times only move on transitions."""
    api = FakeApiServer()
    job = submit(api, make_job(workers=2))
    r = Reconciler(api)
    r.reconcile(job)
    job = api.get("TPUJob", "default", "job1")
    conds = {c["type"]: c for c in job["status"]["conditions"]}
    assert conds["Pending"]["status"] == "True"
    assert "Running" not in conds  # never entered yet

    api.set_all_pod_phases("default", "Running", {JOB_LABEL: "job1"})
    r.reconcile(api.get("TPUJob", "default", "job1"))
    job = api.get("TPUJob", "default", "job1")
    conds = {c["type"]: c for c in job["status"]["conditions"]}
    assert conds["Running"]["status"] == "True"
    assert conds["Pending"]["status"] == "False"
    running_t0 = conds["Running"]["lastTransitionTime"]

    # A second identical pass must not move the transition time.
    r.reconcile(api.get("TPUJob", "default", "job1"))
    job = api.get("TPUJob", "default", "job1")
    conds = {c["type"]: c for c in job["status"]["conditions"]}
    assert conds["Running"]["lastTransitionTime"] == running_t0

    # Failure path: worker dies → Restarting condition with reason.
    api.set_pod_phase("default", "job1-tpu-worker-1", "Failed")
    r.reconcile(api.get("TPUJob", "default", "job1"))
    job = api.get("TPUJob", "default", "job1")
    conds = {c["type"]: c for c in job["status"]["conditions"]}
    assert conds["Restarting"]["status"] == "True"
    assert "slice fault" in conds["Restarting"]["reason"]
    assert conds["Running"]["status"] == "False"
    assert sum(c["status"] == "True"
               for c in job["status"]["conditions"]) == 1


def test_resync_before_kubelet_status_is_idempotent():
    """Regression (found by tests/test_operator_fuzz.py): a resync in
    the window between gang creation and the kubelet's first status
    write must read status-less pods as PENDING, not MISSING — the
    MISSING reading made the second pass re-create live pods and
    crash on Conflict."""
    api = FakeApiServer()
    job = submit(api, make_job(workers=3))
    r = Reconciler(api)
    assert r.reconcile(job) == "Pending"
    # Immediately resync: pods exist but carry no status.phase yet.
    job = api.get("TPUJob", "default", "job1")
    assert r.reconcile(job) == "Pending"  # no Conflict, no re-create
    pods = api.list("Pod", "default", {JOB_LABEL: "job1"})
    assert len(pods) == 3
    job = api.get("TPUJob", "default", "job1")
    assert job["status"]["restartCount"] == 0


def test_kubectl_client_error_taxonomy(monkeypatch):
    """KubectlClient maps kubectl stderr onto the same exception
    taxonomy as the fake store — without the Conflict mapping the
    reconciler's idempotent-create handling would only work in
    tests (found by review of the fuzz fix)."""
    import subprocess
    from types import SimpleNamespace

    from kubeflow_tpu.operator.controller import KubectlClient
    from kubeflow_tpu.operator.fake import Conflict, NotFound

    stderrs = {}

    def fake_run(cmd, **kwargs):
        return SimpleNamespace(returncode=1, stdout="",
                               stderr=stderrs["value"])

    monkeypatch.setattr(subprocess, "run", fake_run)
    client = KubectlClient()

    stderrs["value"] = 'Error: pods "x" not found (NotFound)'
    with pytest.raises(NotFound):
        client._run("get", "pods", "x")
    stderrs["value"] = ('Error from server (AlreadyExists): '
                        'pods "x" already exists')
    with pytest.raises(Conflict):
        client._run("create", "-f", "-")
    stderrs["value"] = "Error from server (Forbidden): nope"
    with pytest.raises(RuntimeError):
        client._run("get", "pods", "x")


def test_gang_pod_disruption_budget():
    """The reconciler guards the gang with a PDB (minAvailable = the
    whole gang): voluntary evictions have no partial-degradation mode
    on an SPMD slice, so the apiserver should refuse them instead of
    burning a slice restart."""
    api = FakeApiServer()
    job = submit(api, make_job(workers=3, coordinator=True))
    Reconciler(api).reconcile(job)
    pdb = api.get("PodDisruptionBudget", "default", "job1")
    assert pdb["spec"]["minAvailable"] == 4  # coordinator + 3 workers
    assert pdb["spec"]["selector"]["matchLabels"] == {JOB_LABEL: "job1"}
    owner = pdb["metadata"]["ownerReferences"][0]
    assert owner["kind"] == "TPUJob" and owner["name"] == "job1"
    # Idempotent across resyncs.
    Reconciler(api).reconcile(api.get("TPUJob", "default", "job1"))
    assert len(api.list("PodDisruptionBudget", "default")) == 1


def test_phase_transitions_emit_events():
    """tf-operator parity: lifecycle Events on every phase transition
    (`kubectl describe tpujob` surface) — Normal for healthy phases,
    Warning for Restarting/Failed, repeated identical transitions
    aggregate via count instead of piling up objects."""
    api = FakeApiServer()
    job = submit(api, make_job(workers=2))
    r = Reconciler(api)
    r.reconcile(job)
    events = {e["metadata"]["name"]: e for e in api.list("Event")}
    assert "job1.pending.r0" in events
    pend = events["job1.pending.r0"]
    assert pend["type"] == "Normal"
    assert pend["involvedObject"]["kind"] == "TPUJob"
    assert pend["involvedObject"]["name"] == "job1"

    api.set_all_pod_phases("default", "Running", {JOB_LABEL: "job1"})
    r.reconcile(api.get("TPUJob", "default", "job1"))
    api.set_pod_phase("default", "job1-tpu-worker-0", "Failed")
    r.reconcile(api.get("TPUJob", "default", "job1"))
    events = {e["metadata"]["name"]: e for e in api.list("Event")}
    assert events["job1.running.r0"]["type"] == "Normal"
    restarting = events["job1.restarting.r1"]
    assert restarting["type"] == "Warning"
    assert "slice fault" in restarting["message"]
    # Recreate pass: Restarting → Running is a transition, with its
    # own event at the new restart count...
    r.reconcile(api.get("TPUJob", "default", "job1"))
    events = {e["metadata"]["name"]: e for e in api.list("Event")}
    assert "job1.running.r1" in events
    # ...but a steady-state pass emits nothing new.
    n = len(events)
    r.reconcile(api.get("TPUJob", "default", "job1"))
    assert len(api.list("Event")) == n


def test_recreated_job_gets_its_own_events():
    """A new same-name job must not bump the deleted predecessor's
    Events (kubectl describe filters by involvedObject.uid): the
    collision records under a uid-suffixed name instead (r5 review)."""
    api = FakeApiServer()
    job = submit(api, make_job(workers=1))
    Reconciler(api).reconcile(job)
    assert api.list("Event")[0]["involvedObject"]["uid"] == "uid-1"

    api.delete("TPUJob", "default", "job1")  # old Events outlive it
    job2 = make_job(workers=1)
    job2["metadata"]["uid"] = "uid-2"
    submit(api, job2)
    Reconciler(api).reconcile(api.get("TPUJob", "default", "job1"))
    events = api.list("Event")
    old = next(e for e in events
               if e["metadata"]["name"] == "job1.pending.r0")
    assert old["involvedObject"]["uid"] == "uid-1"
    assert old["count"] == 1  # NOT bumped by the new incarnation
    fresh = next(e for e in events
                 if e["metadata"]["name"] == "job1.pending.r0.uid-2")
    assert fresh["involvedObject"]["uid"] == "uid-2"


def test_repeated_drain_events_aggregate_count():
    """Two preemption drains at the same restart count: one Event
    whose count reaches 2 (k8s aggregation), not two objects."""
    from kubeflow_tpu.training.launcher import DRAIN_EXIT_CODE

    api = FakeApiServer()
    job = submit(api, make_job(workers=1))
    r = Reconciler(api)
    r.reconcile(job)
    for _ in range(2):
        api.set_all_pod_phases("default", "Running", {JOB_LABEL: "job1"})
        r.reconcile(api.get("TPUJob", "default", "job1"))
        api.set_pod_terminated("default", "job1-tpu-worker-0",
                               DRAIN_EXIT_CODE)
        r.reconcile(api.get("TPUJob", "default", "job1"))  # Restarting
        r.reconcile(api.get("TPUJob", "default", "job1"))  # recreate
    drains = [e for e in api.list("Event")
              if e["metadata"]["name"] == "job1.restarting.r0"]
    assert len(drains) == 1
    assert drains[0]["count"] == 2
    assert "preemption drain" in drains[0]["message"]


def test_preemption_drain_does_not_burn_restart_budget():
    """A pod SIGTERM-drained by the platform (spot reclaim, node
    maintenance) exits with DRAIN_EXIT_CODE after checkpointing
    (training/loop.py); the slice restarts — all-or-nothing as ever —
    but WITHOUT consuming a restart-budget slot: preemption is the
    platform's fault, not the job's."""
    api = FakeApiServer()
    job = submit(api, make_job(workers=2))
    r = Reconciler(api, max_restarts=1)
    r.reconcile(job)
    api.set_all_pod_phases("default", "Running", {JOB_LABEL: "job1"})
    r.reconcile(api.get("TPUJob", "default", "job1"))

    from kubeflow_tpu.training.launcher import DRAIN_EXIT_CODE

    # Repeated preemptions never exhaust the budget (max_restarts=1).
    for round_i in range(3):
        api.set_pod_terminated("default", "job1-tpu-worker-0",
                               DRAIN_EXIT_CODE)
        job = api.get("TPUJob", "default", "job1")
        assert r.reconcile(job) == "Restarting", round_i
        assert job["status"]["restartCount"] == 0
        assert "preemption drain" in job["status"]["reason"]
        assert api.list("Pod", "default", {JOB_LABEL: "job1"}) == []
        job = api.get("TPUJob", "default", "job1")
        # The recreate pass reports Running (the job HAS restarted,
        # even though the budget counter stayed at 0): a preempted
        # long-running job must not regress to Pending on dashboards.
        assert r.reconcile(job) == "Running"
        api.set_all_pod_phases("default", "Running", {JOB_LABEL: "job1"})
        r.reconcile(api.get("TPUJob", "default", "job1"))

    # A REAL crash still burns the budget and, at max_restarts=1,
    # the next one fails the job.
    api.set_pod_terminated("default", "job1-tpu-worker-1", 139)
    job = api.get("TPUJob", "default", "job1")
    assert r.reconcile(job) == "Restarting"
    assert job["status"]["restartCount"] == 1
    r.reconcile(api.get("TPUJob", "default", "job1"))  # recreate
    api.set_pod_terminated("default", "job1-tpu-worker-0", 1)
    job = api.get("TPUJob", "default", "job1")
    assert r.reconcile(job) == "Failed"


def test_mixed_drain_and_crash_burns_budget():
    """One drained pod + one genuinely crashed pod is a slice fault,
    not a preemption: the crash rules, the budget decrements."""
    api = FakeApiServer()
    job = submit(api, make_job(workers=2))
    r = Reconciler(api)
    r.reconcile(job)
    api.set_all_pod_phases("default", "Running", {JOB_LABEL: "job1"})
    r.reconcile(api.get("TPUJob", "default", "job1"))

    from kubeflow_tpu.training.launcher import DRAIN_EXIT_CODE

    api.set_pod_terminated("default", "job1-tpu-worker-0",
                           DRAIN_EXIT_CODE)
    api.set_pod_terminated("default", "job1-tpu-worker-1", 134)
    job = api.get("TPUJob", "default", "job1")
    assert r.reconcile(job) == "Restarting"
    assert job["status"]["restartCount"] == 1
    assert "slice fault" in job["status"]["reason"]


def make_multislice_job(name="ms1", workers=2, num_slices=2):
    spec = replica_spec(
        "TPU_WORKER", workers, image="img:1",
        tpu_accelerator="tpu-v5-lite-podslice", tpu_topology="2x4")
    job = tpu_job(name, "default", [spec],
                  termination=termination_policy("TPU_WORKER", 0),
                  num_slices=num_slices)
    job["metadata"]["uid"] = "uid-ms"
    return job


def test_multislice_gang_naming_env_and_pdb():
    """numSlices=2 provisions the replicaSpecs once per slice with
    slice-major global process ids, per-slice TPU runtime env, and the
    MEGASCALE_* cross-slice contract (SURVEY §2.4) — one PDB over the
    union."""
    api = FakeApiServer()
    job = submit(api, make_multislice_job(workers=2, num_slices=2))
    r = Reconciler(api)
    assert r.reconcile(job) == "Pending"

    pods = api.list("Pod", "default", {JOB_LABEL: "ms1"})
    assert sorted(p["metadata"]["name"] for p in pods) == [
        "ms1-s0-tpu-worker-0", "ms1-s0-tpu-worker-1",
        "ms1-s1-tpu-worker-0", "ms1-s1-tpu-worker-1"]

    def env_of(pod_name):
        pod = api.get("Pod", "default", pod_name)
        return {e["name"]: e["value"]
                for e in pod["spec"]["containers"][0]["env"]}

    # Slice 1's second worker: global process id 3 of a FLAT 4-process
    # jax gang, but slice-local TPU runtime identity.
    env = env_of("ms1-s1-tpu-worker-1")
    assert env["KFT_NUM_PROCESSES"] == "4"
    assert env["KFT_PROCESS_ID"] == "3"
    assert env["KFT_COORDINATOR_ADDRESS"] == \
        "ms1-s0-tpu-worker-0.ms1.default:8476"
    assert env["TPU_WORKER_ID"] == "1"
    # TPU_WORKER_HOSTNAMES lists only THIS slice's workers (each
    # slice's runtime bootstraps its own ICI domain).
    hosts = env["TPU_WORKER_HOSTNAMES"].split(",")
    assert hosts == ["ms1-s1-tpu-worker-0.ms1.default",
                     "ms1-s1-tpu-worker-1.ms1.default"]
    assert env["MEGASCALE_COORDINATOR_ADDRESS"] == \
        "ms1-s0-tpu-worker-0.ms1.default:8477"
    assert env["MEGASCALE_NUM_SLICES"] == "2"
    assert env["MEGASCALE_SLICE_ID"] == "1"
    # Slice 0 worker 0 is process 0 / slice 0.
    env0 = env_of("ms1-s0-tpu-worker-0")
    assert env0["KFT_PROCESS_ID"] == "0"
    assert env0["MEGASCALE_SLICE_ID"] == "0"

    pod = api.get("Pod", "default", "ms1-s1-tpu-worker-0")
    assert pod["metadata"]["labels"]["kubeflow.org/slice-index"] == "1"
    # One disruption budget over the union of slices.
    assert api.get("PodDisruptionBudget", "default",
                   "ms1")["spec"]["minAvailable"] == 4


def test_single_slice_job_has_no_megascale_env():
    """Single-slice jobs keep the pre-r5 pod names and carry no
    MEGASCALE_* vars (build_mesh treats their absence as 1 slice)."""
    api = FakeApiServer()
    job = submit(api, make_job(workers=2))
    Reconciler(api).reconcile(job)
    pod = api.get("Pod", "default", "job1-tpu-worker-0")
    names = {e["name"] for e in pod["spec"]["containers"][0]["env"]}
    assert not any(n.startswith("MEGASCALE_") for n in names)


def test_multislice_failure_restarts_every_slice():
    """All-or-nothing across the UNION: one failed pod on slice 1
    deletes both slices' gangs and burns one restart; the next pass
    recreates everything."""
    api = FakeApiServer()
    job = submit(api, make_multislice_job(workers=2, num_slices=2))
    r = Reconciler(api)
    r.reconcile(job)
    api.set_all_pod_phases("default", "Running", {JOB_LABEL: "ms1"})
    job = api.get("TPUJob", "default", "ms1")
    assert r.reconcile(job) == "Running"

    api.set_pod_phase("default", "ms1-s1-tpu-worker-0", "Failed")
    job = api.get("TPUJob", "default", "ms1")
    assert r.reconcile(job) == "Restarting"
    assert api.list("Pod", "default", {JOB_LABEL: "ms1"}) == []
    assert job["status"]["restartCount"] == 1

    job = api.get("TPUJob", "default", "ms1")
    assert r.reconcile(job) == "Running"
    assert len(api.list("Pod", "default", {JOB_LABEL: "ms1"})) == 4


def test_multislice_chief_is_slice0_worker0():
    """One chief per JOB (slice 0's worker 0), not one per slice: its
    success completes the job and tears down the other slices."""
    api = FakeApiServer()
    job = submit(api, make_multislice_job(workers=2, num_slices=2))
    r = Reconciler(api)
    r.reconcile(job)
    api.set_all_pod_phases("default", "Running", {JOB_LABEL: "ms1"})
    r.reconcile(api.get("TPUJob", "default", "ms1"))
    api.set_pod_phase("default", "ms1-s0-tpu-worker-0", "Succeeded")
    job = api.get("TPUJob", "default", "ms1")
    assert r.reconcile(job) == "Succeeded"
    # Non-chief pods (incl. all of slice 1) were torn down; only the
    # Succeeded chief remains.
    left = api.list("Pod", "default", {JOB_LABEL: "ms1"})
    assert [p["metadata"]["name"] for p in left] == [
        "ms1-s0-tpu-worker-0"]


def test_gang_pdb_tracks_rescaled_gang():
    """A rescaled gang must re-size its disruption budget — a stale
    minAvailable would permit evicting the difference."""
    api = FakeApiServer()
    job = submit(api, make_job(workers=2))
    r = Reconciler(api)
    r.reconcile(job)
    assert api.get("PodDisruptionBudget", "default",
                   "job1")["spec"]["minAvailable"] == 2
    api.patch("TPUJob", "default", "job1",
              lambda o: o["spec"]["replicaSpecs"][0].update(
                  {"replicas": 4}))
    r.reconcile(api.get("TPUJob", "default", "job1"))
    assert api.get("PodDisruptionBudget", "default",
                   "job1")["spec"]["minAvailable"] == 4
