# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""SLO burn-rate alerting: error-ratio math (ratio + latency forms),
the multi-window condition, and the alert state machine (for-duration,
flap damping, resolve hold, Event/ConfigMap/gauge publishing)."""

import pytest

from kubeflow_tpu.obs import metrics as obs_metrics
from kubeflow_tpu.obs.collector import TimeSeriesStore
from kubeflow_tpu.obs.slo import (
    ALERTS_CONFIGMAP,
    ALERTS_KEY,
    FAST_PAGE,
    SLO,
    SLOW_TICKET,
    AlertManager,
    BurnWindow,
    default_slos,
)
from kubeflow_tpu.operator.fake import FakeApiServer


def _ratio_slo(windows=None, objective=0.99):
    kw = {"windows": windows} if windows else {}
    return SLO(name="deadline", objective=objective,
               bad_metrics=("bad_total",),
               total_metrics=("good_total", "bad_total"), **kw)


def _feed(store, ts, good, bad):
    store.ingest("good_total", {"instance": "a"}, good, ts,
                 kind="counter")
    store.ingest("bad_total", {"instance": "a"}, bad, ts,
                 kind="counter")


# -- SLO definition + ratio math ---------------------------------------------


def test_slo_validation():
    with pytest.raises(ValueError):
        SLO(name="x", objective=1.5, bad_metrics=("b",),
            total_metrics=("t",))
    with pytest.raises(ValueError):
        SLO(name="x", objective=0.99)  # neither form
    with pytest.raises(ValueError):
        SLO(name="x", objective=0.99, bad_metrics=("b",),
            total_metrics=("t",), histogram="h",
            threshold_s=0.1)  # both forms
    with pytest.raises(ValueError):
        SLO(name="x", objective=0.99, histogram="h")  # no threshold


def test_ratio_error_and_burn():
    store = TimeSeriesStore()
    # 100 good + 2 bad per second: error ratio ~2/102.
    for ts in range(0, 11):
        _feed(store, ts, good=100.0 * ts, bad=2.0 * ts)
    slo = _ratio_slo()
    ratio = slo.error_ratio(store, window_s=20, now=10)
    assert ratio == pytest.approx(2.0 / 102.0)
    # burn = ratio / budget (budget 1%).
    assert slo.burn_rate(store, 20, 10) == pytest.approx(ratio / 0.01)


def test_no_data_is_none_not_zero():
    store = TimeSeriesStore()
    slo = _ratio_slo()
    assert slo.error_ratio(store, 20, 10) is None
    assert slo.burn_rate(store, 20, 10) is None
    # Total present but flat-zero traffic → 0 errors, not None.
    for ts in range(3):
        _feed(store, ts, good=0.0, bad=0.0)
    assert slo.error_ratio(store, 20, 2) == 0.0


def test_latency_form_fraction_over_threshold():
    store = TimeSeriesStore()
    reg = obs_metrics.Registry()
    h = obs_metrics.Histogram("ttft_seconds", "t",
                              buckets=(0.05, 0.2, 1.0), registry=reg)
    for ts in range(0, 6):
        # 9 fast + 1 slow per tick → 10% above the 0.2 s threshold.
        for _ in range(9):
            h.observe(0.01)
        h.observe(0.5)
        store.ingest_exposition(
            obs_metrics.parse_exposition(reg.render()), ts,
            {"instance": "a"})
    slo = SLO(name="ttft", objective=0.95, histogram="ttft_seconds",
              threshold_s=0.2)
    assert slo.error_ratio(store, window_s=10, now=5) \
        == pytest.approx(0.1)
    # p95 > 0.2s: 10% violations vs a 5% budget → burn 2.
    assert slo.burn_rate(store, 10, 5) == pytest.approx(2.0)


def test_default_slos_shapes():
    slos = default_slos(ttft_p95_s=0.5, reconcile_p99_s=1.0)
    names = [s.name for s in slos]
    assert names == ["serving-deadline", "serving-ttft-p95",
                     "operator-reconcile-p99"]
    assert slos[0].windows == (FAST_PAGE, SLOW_TICKET)
    assert FAST_PAGE.long_s > FAST_PAGE.short_s
    assert SLOW_TICKET.long_s > SLOW_TICKET.short_s
    # default: only the deadline SLO.
    assert [s.name for s in default_slos()] == ["serving-deadline"]


# -- the state machine -------------------------------------------------------


_WIN = BurnWindow("fast", long_s=60.0, short_s=10.0, factor=10.0,
                  severity="page")


def _manager(store, api=None, for_s=2.0, resolve_s=5.0):
    return AlertManager(store, [_ratio_slo(windows=(_WIN,))],
                        api=api, for_s=for_s, resolve_s=resolve_s,
                        clock=lambda: 0.0)


def _run_phases(store, manager, *, t0, steps, bad_per_s,
                good_per_s=100.0, start_good=None, start_bad=None):
    """Feed counters + evaluate once per second; returns last rows."""
    g = start_good if start_good is not None else t0 * good_per_s
    b = start_bad if start_bad is not None else 0.0
    rows = []
    for step in range(steps):
        ts = t0 + step
        g += good_per_s
        b += bad_per_s
        _feed(store, ts, g, b)
        rows = manager.evaluate(now=ts)
    return rows, g, b


def test_alert_lifecycle_pending_firing_resolved():
    store = TimeSeriesStore()
    fake = FakeApiServer()
    manager = _manager(store, api=fake)
    # Healthy minute: inactive.
    rows, g, b = _run_phases(store, manager, t0=0, steps=30,
                             bad_per_s=0.0)
    assert rows[0]["state"] == "inactive"
    # Burst: 50% errors ≫ 10× the 1% budget. First over-threshold
    # evaluation → pending; after for_s → firing.
    rows, g, b = _run_phases(store, manager, t0=30, steps=10,
                             bad_per_s=100.0, start_good=g,
                             start_bad=b)
    assert rows[0]["state"] == "firing"
    transitions = [h["to"] for h in manager.history]
    assert transitions[:2] == ["pending", "firing"]
    # Firing published: Event + ConfigMap + gauge.
    events = fake.list("Event", "default")
    assert any(e["reason"] == "AlertFiring" and e["type"] == "Warning"
               for e in events)
    cm = fake.get("ConfigMap", "default", ALERTS_CONFIGMAP)
    assert ALERTS_KEY in cm["data"]
    fams = obs_metrics.parse_exposition(obs_metrics.render())
    states = {(labels["slo"], labels["severity"]): v for _, labels, v
              in fams["kft_alert_state"]["samples"]}
    assert states[("deadline", "page")] == 2.0
    # Recovery: errors stop; short window clears, then the long one;
    # after resolve_s of clear → resolved (Event Normal), then
    # inactive.
    rows, g, b = _run_phases(store, manager, t0=40, steps=80,
                             bad_per_s=0.0, start_good=g, start_bad=b)
    transitions = [h["to"] for h in manager.history]
    assert transitions == ["pending", "firing", "resolved"]
    assert rows[0]["state"] == "inactive"
    events = fake.list("Event", "default")
    assert any(e["reason"] == "AlertResolved"
               and e["type"] == "Normal" for e in events)
    fams = obs_metrics.parse_exposition(obs_metrics.render())
    states = {(labels["slo"], labels["severity"]): v for _, labels, v
              in fams["kft_alert_state"]["samples"]}
    assert states[("deadline", "page")] == 0.0


def test_pending_blip_never_fires():
    """A burst shorter than for_s drops back to inactive without an
    Event — the for-duration is the first flap damper."""
    store = TimeSeriesStore()
    fake = FakeApiServer()
    # The short window retains a one-tick blip for its 10 s span;
    # for_s beyond that means only a SUSTAINED burn can fire.
    manager = _manager(store, api=fake, for_s=15.0)
    _run_phases(store, manager, t0=0, steps=30, bad_per_s=0.0)
    g, b = 30 * 100.0, 0.0
    _feed(store, 30, g + 100, b + 5000)
    manager.evaluate(now=30)
    rows, _, _ = _run_phases(store, manager, t0=31, steps=30,
                             bad_per_s=0.0, start_good=g + 100,
                             start_bad=b + 5000)
    transitions = [h["to"] for h in manager.history]
    assert "firing" not in transitions
    assert rows[0]["state"] == "inactive"
    assert not any(e["reason"] == "AlertFiring"
                   for e in fake.list("Event", "default"))


def test_firing_holds_through_flapping_condition():
    """Condition oscillating around the threshold must not resolve
    per dip: the resolve hold (resolve_s) keeps the alert firing
    until the burn stays clear."""
    store = TimeSeriesStore()
    manager = _manager(store, for_s=0.0, resolve_s=20.0)
    _run_phases(store, manager, t0=0, steps=5, bad_per_s=0.0)
    rows, g, b = _run_phases(store, manager, t0=5, steps=10,
                             bad_per_s=100.0, start_good=5 * 100.0,
                             start_bad=0.0)
    assert rows[0]["state"] == "firing"
    # Alternate 3 quiet / 3 hot seconds: dips shorter than resolve_s.
    for chunk in range(4):
        bad = 0.0 if chunk % 2 == 0 else 100.0
        rows, g, b = _run_phases(store, manager, t0=15 + chunk * 3,
                                 steps=3, bad_per_s=bad,
                                 start_good=g, start_bad=b)
        assert rows[0]["windows"][0]["state"] == "firing", chunk
    assert [h["to"] for h in manager.history].count("resolved") == 0


def test_blind_store_holds_state():
    """No data (all series aged out / scrapes down) holds the current
    state: alerting on blindness — either direction — is wrong."""
    store = TimeSeriesStore()
    manager = _manager(store, for_s=0.0)
    rows, g, b = _run_phases(store, manager, t0=0, steps=10,
                             bad_per_s=100.0)
    assert rows[0]["state"] == "firing"
    # Far future: every sample outside both windows → burns are None.
    rows = manager.evaluate(now=10_000)
    assert rows[0]["windows"][0]["long_burn"] is None
    assert rows[0]["state"] == "firing"  # held, not resolved


def test_multi_window_requires_both():
    """Long window hot from an old burst but short window clear must
    NOT alert (the SRE rule: the short window proves the problem is
    still happening)."""
    store = TimeSeriesStore()
    manager = _manager(store, for_s=0.0)
    # 30s burst, then quiet; at t=45 the 60s-long window still sees
    # the burst, the 10s-short window does not.
    rows, g, b = _run_phases(store, manager, t0=0, steps=30,
                             bad_per_s=100.0)
    rows, _, _ = _run_phases(store, manager, t0=30, steps=15,
                             bad_per_s=0.0, start_good=g, start_bad=b)
    w = rows[0]["windows"][0]
    assert w["long_burn"] > _WIN.factor
    assert w["short_burn"] < _WIN.factor


def test_publish_survives_broken_api():
    class _Boom:
        def create(self, *a, **k):
            raise RuntimeError("apiserver down")

        def patch(self, *a, **k):
            raise RuntimeError("apiserver down")

    store = TimeSeriesStore()
    manager = _manager(store, api=_Boom(), for_s=0.0)
    rows, _, _ = _run_phases(store, manager, t0=0, steps=10,
                             bad_per_s=100.0)
    assert rows[0]["state"] == "firing"  # evaluation kept going


def test_state_snapshot_for_artifacts():
    store = TimeSeriesStore()
    manager = _manager(store, for_s=0.0)
    _run_phases(store, manager, t0=0, steps=10, bad_per_s=100.0)
    snap = manager.state()
    assert snap["slos"][0]["slo"] == "deadline"
    assert [h["to"] for h in snap["history"]] == ["pending", "firing"]
    assert {"for_s", "resolve_s"} <= set(snap)


def test_configmap_published_only_on_state_change():
    """A quiet fleet must not write the apiserver every evaluation:
    the kft-alerts ConfigMap is published on state-machine changes
    only (and its history carries transition-stamped wall times, no
    per-cycle-recomputed fields)."""

    class _CountingApi:
        def __init__(self):
            self.fake = FakeApiServer()
            self.writes = 0

        def create(self, obj):
            self.writes += 1
            return self.fake.create(obj)

        def patch(self, *a, **k):
            self.writes += 1
            return self.fake.patch(*a, **k)

        def get(self, *a, **k):
            return self.fake.get(*a, **k)

        def list(self, *a, **k):
            return self.fake.list(*a, **k)

    api = _CountingApi()
    store = TimeSeriesStore()
    manager = _manager(store, api=api, for_s=0.0)
    # The very first evaluation creates the ConfigMap (the sidecar
    # surface must exist even with zero alerts)...
    _run_phases(store, manager, t0=0, steps=1, bad_per_s=0.0)
    baseline_writes = api.writes
    # ...then a quiet fleet writes NOTHING per cycle.
    _run_phases(store, manager, t0=1, steps=29, bad_per_s=0.0,
                start_good=100.0)
    assert api.writes == baseline_writes
    _run_phases(store, manager, t0=30, steps=5, bad_per_s=100.0,
                start_good=3000.0, start_bad=0.0)
    fired_writes = api.writes  # pending + firing: CM + Event writes
    assert fired_writes > 0
    # Steady firing: no further writes per cycle.
    _run_phases(store, manager, t0=35, steps=20, bad_per_s=100.0,
                start_good=3500.0, start_bad=500.0)
    assert api.writes == fired_writes
    import json as _json

    cm = api.fake.get("ConfigMap", "default", ALERTS_CONFIGMAP)
    doc = _json.loads(cm["data"][ALERTS_KEY])
    assert all("at" in h and "age_s" not in h for h in doc["history"])
