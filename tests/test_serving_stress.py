# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Concurrency stress for the serving data plane.

Two tiers: the C++ sanitizer stress binary (tsan/asan — the CI gate,
run here too when a toolchain is present) and a pure-Python hammering
of ServedModel.submit/_batch_loop/stop with a stub model, targeting
the _pending bookkeeping races VERDICT r1 called out."""

import shutil
import subprocess
import threading
from pathlib import Path

import numpy as np
import pytest

from kubeflow_tpu.serving.manager import ServedModel

NATIVE = Path(__file__).resolve().parent.parent / "native"


@pytest.mark.slow
@pytest.mark.skipif(shutil.which("g++") is None, reason="needs g++")
def test_native_sanitizer_stress():
    r = subprocess.run(["make", "-C", str(NATIVE), "check-sanitizers"],
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "stress_test: all ok" in r.stdout


class _StubLoaded:
    """Stands in for LoadedModel: echoes row indices so slicing bugs
    (wrong offsets, cross-request mixing) are detectable."""

    def __init__(self):
        self.calls = 0

    def signature(self, name=None):
        class Sig:
            inputs = {"x": None}
        return Sig()

    def run(self, inputs, sig_name=None, method=None):
        self.calls += 1
        return {"y": np.asarray(inputs["x"]) * 2.0}


def _make_model():
    m = ServedModel("stub", "/nonexistent", max_batch=16,
                    batch_window_s=0.001)
    stub = _StubLoaded()
    m._versions[1] = stub
    m._latest = 1
    return m, stub


def test_concurrent_submit_correctness():
    m, stub = _make_model()
    errors = []
    results = {}
    lock = threading.Lock()

    def client(tid):
        try:
            for i in range(50):
                value = float(tid * 1000 + i)
                x = np.full((2, 3), value, np.float32)
                out = m.submit({"x": x}, None, None, None).result(10)
                np.testing.assert_array_equal(out["y"], x * 2.0)
            with lock:
                results[tid] = True
        except Exception as e:  # noqa: BLE001
            with lock:
                errors.append((tid, repr(e)))

    threads = [threading.Thread(target=client, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errors, errors[:3]
    assert len(results) == 8
    # (Whether requests coalesced into batches is timing-dependent on a
    # loaded runner; batching behavior itself is covered
    # deterministically by test_serving.py::test_served_model_batching.)
    assert stub.calls >= 1
    m.stop()
    assert not m._pending


def test_concurrent_first_requests_single_batcher():
    m, _ = _make_model()
    barrier = threading.Barrier(8)

    def client():
        barrier.wait()
        x = np.ones((1, 2), np.float32)
        m.submit({"x": x}, None, None, None).result(10)

    threads = [threading.Thread(target=client) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(15)
    # Exactly one batcher thread may exist.
    batchers = [t for t in threading.enumerate()
                if t.name.startswith("batcher-stub")]
    assert len(batchers) == 1, batchers
    m.stop()


def test_batch_stats_count_split_executions():
    """pop_batch caps REQUEST count, not row count: a group whose rows
    exceed max_batch splits into ceil(rows/max_batch) XLA executions
    inside LoadedModel.run — batch_stats must count those, never
    report an impossible fill > max_batch."""
    m = ServedModel("stub", "/nonexistent", max_batch=2,
                    batch_window_s=0.001)
    m._versions[1] = _StubLoaded()
    m._latest = 1
    out = m.submit({"x": np.ones((5, 3), np.float32)},
                   None, None, None).result(10)
    assert out["y"].shape == (5, 3)
    stats = m.batch_stats()
    assert stats["rows"] == 5
    assert stats["batches"] == 3  # ceil(5/2)
    assert stats["mean_fill"] <= m.max_batch
    m.stop()


def test_stop_fails_undrained_requests():
    m, _ = _make_model()
    m.start_batcher()
    m.stop()
    # After stop, submits fail fast instead of hanging forever.
    fut = m.submit({"x": np.ones((1, 2), np.float32)}, None, None, None)
    with pytest.raises(RuntimeError):
        fut.result(5)
