# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Concurrency stress for the serving data plane.

Two tiers: the C++ sanitizer stress binary (tsan/asan — the CI gate,
run here too when a toolchain is present) and a pure-Python hammering
of ServedModel.submit/_batch_loop/stop with a stub model, targeting
the _pending bookkeeping races VERDICT r1 called out."""

import shutil
import subprocess
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from kubeflow_tpu.serving.manager import ServedModel
from kubeflow_tpu.serving.overload import (
    DeadlineExceededError,
    OverloadedError,
    deadline_after,
)

NATIVE = Path(__file__).resolve().parent.parent / "native"


@pytest.mark.slow
@pytest.mark.skipif(shutil.which("g++") is None, reason="needs g++")
def test_native_sanitizer_stress():
    r = subprocess.run(["make", "-C", str(NATIVE), "check-sanitizers"],
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "stress_test: all ok" in r.stdout


class _StubLoaded:
    """Stands in for LoadedModel: echoes row indices so slicing bugs
    (wrong offsets, cross-request mixing) are detectable."""

    def __init__(self):
        self.calls = 0

    def signature(self, name=None):
        class Sig:
            inputs = {"x": None}
        return Sig()

    def run(self, inputs, sig_name=None, method=None):
        self.calls += 1
        return {"y": np.asarray(inputs["x"]) * 2.0}


def _make_model():
    m = ServedModel("stub", "/nonexistent", max_batch=16,
                    batch_window_s=0.001)
    stub = _StubLoaded()
    m._versions[1] = stub
    m._latest = 1
    return m, stub


def test_concurrent_submit_correctness():
    m, stub = _make_model()
    errors = []
    results = {}
    lock = threading.Lock()

    def client(tid):
        try:
            for i in range(50):
                value = float(tid * 1000 + i)
                x = np.full((2, 3), value, np.float32)
                out = m.submit({"x": x}, None, None, None).result(10)
                np.testing.assert_array_equal(out["y"], x * 2.0)
            with lock:
                results[tid] = True
        except Exception as e:  # noqa: BLE001
            with lock:
                errors.append((tid, repr(e)))

    threads = [threading.Thread(target=client, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errors, errors[:3]
    assert len(results) == 8
    # (Whether requests coalesced into batches is timing-dependent on a
    # loaded runner; batching behavior itself is covered
    # deterministically by test_serving.py::test_served_model_batching.)
    assert stub.calls >= 1
    m.stop()
    assert not m._pending


def test_concurrent_first_requests_single_batcher():
    m, _ = _make_model()
    barrier = threading.Barrier(8)

    def client():
        barrier.wait()
        x = np.ones((1, 2), np.float32)
        m.submit({"x": x}, None, None, None).result(10)

    threads = [threading.Thread(target=client) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(15)
    # Exactly one batcher thread may exist.
    batchers = [t for t in threading.enumerate()
                if t.name.startswith("batcher-stub")]
    assert len(batchers) == 1, batchers
    m.stop()


def test_batch_stats_count_split_executions():
    """pop_batch caps REQUEST count, not row count: a group whose rows
    exceed max_batch splits into ceil(rows/max_batch) XLA executions
    inside LoadedModel.run — batch_stats must count those, never
    report an impossible fill > max_batch."""
    m = ServedModel("stub", "/nonexistent", max_batch=2,
                    batch_window_s=0.001)
    m._versions[1] = _StubLoaded()
    m._latest = 1
    out = m.submit({"x": np.ones((5, 3), np.float32)},
                   None, None, None).result(10)
    assert out["y"].shape == (5, 3)
    stats = m.batch_stats()
    assert stats["rows"] == 5
    assert stats["batches"] == 3  # ceil(5/2)
    assert stats["mean_fill"] <= m.max_batch
    m.stop()


def test_stop_fails_undrained_requests():
    m, _ = _make_model()
    m.start_batcher()
    m.stop()
    # After stop, submits fail fast instead of hanging forever.
    fut = m.submit({"x": np.ones((1, 2), np.float32)}, None, None, None)
    with pytest.raises(RuntimeError):
        fut.result(5)


class _JitterStub:
    """Slow model with bimodal latency (fast batches punctuated by
    slow ones), recording the first column of every dispatched batch —
    the EWMA lags the slow bursts, so admitted requests DO expire in
    queue, which is exactly the case eviction exists for."""

    version = 1

    def __init__(self):
        self.calls = 0
        self.seen = []
        self._lock = threading.Lock()

    def signature(self, name=None):
        class Sig:
            method = "predict"
            inputs = {"x": None}
        return Sig()

    def run(self, inputs, sig_name=None, method=None):
        with self._lock:
            self.calls += 1
            calls = self.calls
            self.seen.extend(np.asarray(inputs["x"])[:, 0].tolist())
        time.sleep(0.1 if calls % 3 == 0 else 0.005)
        return {"y": np.asarray(inputs["x"]) * 2.0}


def test_overload_expired_and_shed_never_dispatch():
    """Deadline-aware overload stress (ISSUE 3 acceptance): hammer a
    slow model with a mix of deadline-free and tight-deadline
    requests. Hard invariants, asserted via batch_stats + the stub's
    dispatch log: a request the server shed or expired NEVER reaches
    the model; every dispatched row is accounted; the counters match
    what clients observed."""
    m = ServedModel("stub", "/nonexistent", max_batch=4,
                    batch_window_s=0.001, queue_capacity=64)
    stub = _JitterStub()
    m._versions[1] = stub
    m._latest = 1

    outcomes = {"ok": [], "shed": [], "expired": [], "other": []}
    lock = threading.Lock()

    def client(tid):
        for i in range(30):
            value = float(tid * 1000 + i)
            x = np.full((1, 2), value, np.float32)
            # Every other request carries a tight 30-90ms budget.
            deadline = (deadline_after(0.03 + 0.02 * (i % 4))
                        if i % 2 == 0 else None)
            fut = m.submit({"x": x}, None, None, None, deadline=deadline)
            try:
                out = fut.result(30)
                np.testing.assert_array_equal(out["y"], x * 2.0)
                bucket = "ok"
            except OverloadedError:
                bucket = "shed"
            except DeadlineExceededError:
                bucket = "expired"
            except Exception as e:  # noqa: BLE001
                bucket = "other"
                value = (value, repr(e))
            with lock:
                outcomes[bucket].append(value)

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not any(t.is_alive() for t in threads)
    assert not outcomes["other"], outcomes["other"][:3]

    stats = m.batch_stats()
    m.stop()
    dispatched = set(stub.seen)
    total = 8 * 30
    # Conservation: every request resolved exactly one way.
    assert (len(outcomes["ok"]) + len(outcomes["shed"])
            + len(outcomes["expired"])) == total
    # The tentpole guarantee: shed/expired payloads never dispatched.
    assert not dispatched & set(outcomes["shed"])
    assert not dispatched & set(outcomes["expired"])
    assert dispatched == set(outcomes["ok"])
    # batch_stats agrees with both sides of the ledger.
    assert stats["rows"] == len(outcomes["ok"]) == len(stub.seen)
    assert stats["shed"] == len(outcomes["shed"])
    assert stats["expired"] == len(outcomes["expired"])
    # The drive genuinely overloaded the server (30-90ms budgets vs
    # 100ms slow batches): some requests were turned away early.
    assert stats["shed"] + stats["expired"] > 0, stats


def test_fleet_failover_reroutes_in_deadline_requests():
    """ISSUE 5 e2e acceptance: with 3 live backends behind the pooled
    proxy, killing one mid-load sheds NO in-deadline request — the
    router fails the transport attempt over to a live replica, the
    victim's breaker opens sub-second and the prober ejects it; after
    revival the prober readmits it and it takes new work again."""
    import urllib.error

    from kubeflow_tpu.scaling.benchmark import (
        StubBackendFleet,
        _post_infer,
    )

    fleet = StubBackendFleet(3, service_time_s=0.02, proxy_kwargs={
        "balancer": "least_saturation", "breaker_failures": 1,
        "breaker_reset_s": 0.5, "probe_interval_s": 0.1}).start()
    try:
        for _ in range(6):  # warm the signature caches on all paths
            _post_infer(fleet.proxy_port, deadline_ms=5000)
        pool = fleet.proxy_app.settings["pool"]
        victim = pool.get(f"127.0.0.1:{fleet.ports[0]}")

        stop = threading.Event()
        errors, ok = [], []
        lock = threading.Lock()

        def client():
            while not stop.is_set():
                try:
                    dt = _post_infer(fleet.proxy_port,
                                     deadline_ms=5000)
                except urllib.error.HTTPError as e:
                    with lock:
                        errors.append(f"HTTP {e.code}")
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(repr(e))
                else:
                    with lock:
                        ok.append(dt)

        threads = [threading.Thread(target=client, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()

        def wait_until(cond, timeout_s):
            deadline = time.monotonic() + timeout_s
            while not cond() and time.monotonic() < deadline:
                time.sleep(0.005)
            return cond()

        # Load established → kill backend 0 (listener gone:
        # connection-refused, the way a deleted pod fails).
        wait_until(lambda: len(ok) >= 20, 10.0)
        fleet.kill(0)
        t_kill = time.monotonic()
        # The first transport failure trips the victim's breaker —
        # sub-second, so at most one request per client eats a
        # connect attempt (and retries elsewhere inside its budget).
        assert wait_until(
            lambda: victim.rest_breaker.state == "open", 1.0), \
            victim.rest_breaker.state
        assert time.monotonic() - t_kill < 1.0
        # The prober ejects it from rotation shortly after.
        assert wait_until(lambda: not victim.routable(), 2.5), \
            victim.snapshot()
        # Keep hammering through the degraded window, then revive.
        before_revive = fleet.completed[0]
        stop.wait(0.3)
        fleet.revive(0)
        # Readmission: one good probe brings it back...
        assert wait_until(lambda: victim.health == "healthy", 2.5), \
            victim.snapshot()
        # ...and it actually takes traffic again (rejoins rotation).
        assert wait_until(
            lambda: fleet.completed[0] > before_revive, 10.0), \
            fleet.completed
        stop.set()
        for t in threads:
            t.join(15)
        assert not any(t.is_alive() for t in threads)
        # The headline invariant: every in-deadline request succeeded
        # across kill, degraded window, and readmission.
        assert errors == [], errors[:5]
        assert len(ok) > 40, len(ok)
    finally:
        fleet.stop()


def test_deadline_less_timeout_is_one_placement_no_failover():
    """A timed-out placement may still be executing on its replica;
    with no deadline budget to bound re-dispatch, the router must NOT
    replay the request on other replicas (retry amplification is
    worst exactly when the fleet is slow). One placement, one 504 —
    the pre-pool contract."""
    import json as _json
    import urllib.error
    import urllib.request

    from kubeflow_tpu.scaling.benchmark import MODEL, StubBackendFleet

    fleet = StubBackendFleet(2, service_time_s=1.0, proxy_kwargs={
        "rpc_timeout": 0.25, "retry_attempts": 2,
        "probe_interval_s": 5.0}).start()
    try:
        payload = _json.dumps({"instances": [[1.0]]}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{fleet.proxy_port}/model/{MODEL}:predict",
            data=payload,
            headers={"Content-Type": "application/json"})  # NO deadline
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=10.0)
        assert exc_info.value.code == 504
        # Both backends eventually finish whatever was placed on them;
        # only ONE may have been.
        time.sleep(1.5)
        assert sum(fleet.completed) == 1, fleet.completed
    finally:
        fleet.stop()


def test_proxy_healthz_degrades_on_any_open_breaker():
    """The pre-pool /healthz contract (docs/observability.md): ANY
    open breaker — including a dead binary wire whose requests
    silently fall back to REST — reads "degraded", so alerts keyed on
    status fire before clients notice."""
    import json as _json
    import urllib.request

    from kubeflow_tpu.scaling.benchmark import StubBackendFleet

    fleet = StubBackendFleet(1, service_time_s=0.01, proxy_kwargs={
        "probe_interval_s": 5.0}).start()
    try:
        def healthz():
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{fleet.proxy_port}/healthz",
                    timeout=5.0) as resp:
                return _json.load(resp)

        assert healthz()["status"] == "ok"
        ep = fleet.proxy_app.settings["pool"].endpoints()[0]
        for _ in range(ep.grpc_breaker.failure_threshold):
            ep.grpc_breaker.record_failure()
        assert ep.grpc_breaker.state == "open"
        assert healthz()["status"] == "degraded"  # still routable, though
        assert ep.routable()
        ep.grpc_breaker.record_success()
        assert healthz()["status"] == "ok"
    finally:
        fleet.stop()
