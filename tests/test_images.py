# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Image-plane tests: every first-party image referenced by the
manifests must be buildable from this repo, and the zero-CUDA
north-star invariant must hold across every Dockerfile (reference
shipped 9 Dockerfiles incl. a CUDA build, Dockerfile.gpu; the TPU
rebuild must have none)."""

import re
from pathlib import Path

import pytest

from kubeflow_tpu.params import get_prototype, list_prototypes

REPO = Path(__file__).resolve().parent.parent
IMAGES = REPO / "images"

# Minimal overrides for required params (mirrors test_manifests.py).
OVERRIDES = {
    "tpu-job": {"name": "j"},
    "tpu-cnn": {"name": "c"},
    "tpu-finetune": {"name": "f"},
    "tpu-lm": {"name": "lm"},
    "tpu-serving": {"name": "s", "model_path": "gs://b/m"},
    "cert-manager": {"acme_email": "a@b.com"},
    "iap-envoy": {"audiences": "aud"},
    "iap-ingress": {"ip_name": "ip", "hostname": "h.example.com"},
    "seldon-serve-simple": {"name": "m", "image": "img:1"},
    "nfs": {"disks": "d1"},
    "ci-e2e": {"name": "e"},
    "ci-release": {"name": "r", "version_tag": "v0"},
}

FIRST_PARTY = re.compile(r"ghcr\.io/kubeflow-tpu/([a-z0-9-]+):")


def _all_manifest_json() -> str:
    import json

    chunks = []
    for proto in list_prototypes():
        objs = get_prototype(proto.name).build(OVERRIDES.get(proto.name, {}))
        chunks.append(json.dumps(objs))
    return "\n".join(chunks)


def test_every_referenced_image_has_a_dockerfile():
    referenced = set(FIRST_PARTY.findall(_all_manifest_json()))
    assert referenced, "no first-party images found — regex broken?"
    missing = {
        name for name in referenced
        if not (IMAGES / name / "Dockerfile").is_file()
    }
    assert not missing, f"manifests reference unbuildable images: {missing}"


def test_release_workflow_covers_every_image_dir():
    families = {
        p.name for p in IMAGES.iterdir() if (p / "Dockerfile").is_file()
    }
    wf = get_prototype("ci-release").build(
        {"name": "r", "version_tag": "v0"})[0]
    built = {
        t["name"].removeprefix("build-")
        for t in wf["spec"]["templates"]
        if t["name"].startswith("build-")
    }
    assert built == families, (
        f"release DAG != images/: only-in-dag={built - families}, "
        f"unreleased={families - built}")


FORBIDDEN = re.compile(r"cuda|nccl|nvidia|cudnn", re.IGNORECASE)


@pytest.mark.parametrize(
    "path",
    [p for p in IMAGES.rglob("*") if p.is_file()],
    ids=lambda p: str(p.relative_to(IMAGES)),
)
def test_zero_cuda_invariant(path):
    text = path.read_text(errors="replace")
    match = FORBIDDEN.search(text)
    assert match is None, (
        f"{path} mentions {match.group(0)!r} — zero-CUDA invariant")


def test_manifests_reference_no_gpu_resources():
    text = _all_manifest_json()
    assert FORBIDDEN.search(text) is None, "GPU/CUDA leaked into manifests"
    assert "google.com/tpu" in text


def test_build_script_rejects_unknown_family():
    import subprocess

    r = subprocess.run(
        ["/bin/sh", str(IMAGES / "build_image.sh"), "no-such-family",
         "ghcr.io/kubeflow-tpu/no-such-family:v0"],
        capture_output=True, text=True)
    assert r.returncode == 1
    assert "unknown image family" in r.stderr
