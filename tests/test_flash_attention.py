# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Pallas flash attention vs dense reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.ops.attention import dense_attention
from kubeflow_tpu.ops.flash_attention import flash_attention


def make_qkv(key, b=2, l=128, h=4, d=16, kv_heads=None):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, l, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, l, kv_heads or h, d), jnp.float32)
    v = jax.random.normal(kv, (b, l, kv_heads or h, d), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(causal):
    q, k, v = make_qkv(jax.random.PRNGKey(0))
    ref = dense_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_uneven_blocks():
    # block_q != block_k and q/kv lengths differ
    q, k, v = make_qkv(jax.random.PRNGKey(1), l=64)
    k = k[:, :32]
    v = v[:, :32]
    ref = dense_attention(q, k, v)
    out = flash_attention(q, k, v, block_q=16, block_k=32, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_gqa():
    q, k, v = make_qkv(jax.random.PRNGKey(2), h=8, kv_heads=2, l=64)
    ref = dense_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_fallback_on_indivisible():
    q, k, v = make_qkv(jax.random.PRNGKey(3), l=48)  # 48 % 32 != 0
    ref = dense_attention(q, k, v)
    out = flash_attention(q, k, v, block_q=32, block_k=32, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_gradients_match_dense():
    q, k, v = make_qkv(jax.random.PRNGKey(4), l=64, d=16)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=32,
                                       block_k=32, interpret=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_flash_in_llama():
    from kubeflow_tpu.models.llama import llama_test
    import flax.linen as nn
    import functools

    ids = jax.random.randint(jax.random.PRNGKey(0), (2, 64), 0, 512)
    dense_model = llama_test()
    flash_model = llama_test(attention_fn=functools.partial(
        flash_attention, causal=True, block_q=32, block_k=32,
        interpret=True))
    variables = dense_model.init(jax.random.PRNGKey(1), ids)
    params = nn.meta.unbox(variables["params"])
    ref = dense_model.apply({"params": params}, ids)
    out = flash_model.apply({"params": params}, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-2, rtol=3e-2)


def test_flash_mask_matches_dense():
    q, k, v = make_qkv(jax.random.PRNGKey(5), l=64)
    lengths = jax.random.randint(jax.random.PRNGKey(6), (2,), 1, 65)
    mask = (jnp.arange(64)[None, :] < lengths[:, None]).astype(jnp.int32)
    ref = dense_attention(q, k, v, kv_segment_valid=mask)
    out = flash_attention(q, k, v, block_q=32, block_k=32,
                          kv_segment_valid=mask, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_mask_gradients_match_dense():
    q, k, v = make_qkv(jax.random.PRNGKey(7), l=64)
    mask = (jnp.arange(64)[None, :] < jnp.array([[40], [64]])).astype(
        jnp.int32).reshape(2, 64)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(
            q, k, v, block_q=32, block_k=32, kv_segment_valid=mask,
            interpret=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, kv_segment_valid=mask) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_bert_sequence_parallel_respects_padding():
    """ADVICE r1: a custom attention_fn (ring) must mask padded tokens
    exactly like the default path on a padded batch."""
    import flax.linen as nn
    from kubeflow_tpu.models.bert import bert_test
    from kubeflow_tpu.parallel.mesh import MeshSpec, build_mesh
    from kubeflow_tpu.parallel.ring_attention import (
        make_sequence_parallel_attention,
    )

    mesh = build_mesh(MeshSpec(data=2, seq=4))
    ids = jax.random.randint(jax.random.PRNGKey(0), (2, 64), 0, 512)
    valid = (jnp.arange(64)[None, :] < jnp.array([[37], [64]])).astype(
        jnp.int32).reshape(2, 64)

    dense_model = bert_test(dtype=jnp.float32)
    ring_model = bert_test(
        dtype=jnp.float32,
        attention_fn=make_sequence_parallel_attention(
            mesh, strategy="ring", head_axis=None))
    variables = dense_model.init(jax.random.PRNGKey(1), ids)
    params = nn.meta.unbox(variables["params"])
    ref = dense_model.apply({"params": params}, ids, None, valid)
    out = ring_model.apply({"params": params}, ids, None, valid)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_fit_block_alignment_and_floor():
    from kubeflow_tpu.ops.flash_attention import _fit_block

    assert _fit_block(2048, 2048) == 2048
    assert _fit_block(3072, 2048) == 1024   # degrade to dividing pow2
    assert _fit_block(1500, 2048) == 512    # pow2 only, never 1500
    assert 1500 % _fit_block(1500, 2048) != 0  # -> XLA fallback
    assert _fit_block(2176, 2048) == 512    # floor at 512, not 128
    assert 2176 % 512 != 0                  # -> XLA fallback
    assert _fit_block(128, 2048) == 128     # short L: exact block


def test_non_dividing_length_falls_back_not_crashes():
    # L=1500 must route to the XLA path (any backend), not a
    # misaligned Pallas launch.
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 1500, 4, 64),
                          jnp.bfloat16)
    out = flash_attention(q, q, q, causal=True)
    assert out.shape == q.shape
