# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Engine tests on the 8-device virtual CPU mesh: mesh specs, sharded
train step, benchmark smoke, graft entries."""

import jax
import jax.numpy as jnp
import optax
import pytest

from kubeflow_tpu.models.resnet import resnet18ish
from kubeflow_tpu.parallel.mesh import (
    MeshSpec,
    batch_sharding,
    build_mesh,
    fsdp_params_sharding,
)
from kubeflow_tpu.training.train import (
    create_train_state,
    make_train_step,
    place_batch,
    place_state,
)


def test_mesh_spec_wildcard(cpu_devices):
    spec = MeshSpec(data=-1, fsdp=2).resolve(8)
    assert spec.data == 4 and spec.fsdp == 2


def test_mesh_spec_mismatch():
    with pytest.raises(ValueError, match="devices"):
        MeshSpec(data=3).resolve(8)
    with pytest.raises(ValueError, match="one -1"):
        MeshSpec(data=-1, fsdp=-1).resolve(8)


def test_build_mesh_axes(cpu_devices):
    mesh = build_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
    assert mesh.shape["data"] == 2
    assert mesh.shape["tensor"] == 2
    assert mesh.size == 8


def test_build_mesh_megascale_env(cpu_devices, monkeypatch):
    """The operator-injected MEGASCALE_NUM_SLICES supplies the
    dcn_data axis: a spec that doesn't name it gets the slice count
    automatically, a conflicting explicit value fails loudly, and an
    agreeing one passes through."""
    monkeypatch.setenv("MEGASCALE_NUM_SLICES", "2")
    mesh = build_mesh(MeshSpec(data=-1))
    assert mesh.shape["dcn_data"] == 2
    assert mesh.shape["data"] == 4
    mesh = build_mesh(MeshSpec(data=2, fsdp=2))  # 2×2×2 = 8
    assert mesh.shape["dcn_data"] == 2
    mesh = build_mesh(MeshSpec(dcn_data=2, data=4))  # explicit, agrees
    assert mesh.shape["dcn_data"] == 2
    with pytest.raises(ValueError, match="provisioned"):
        build_mesh(MeshSpec(dcn_data=4, data=2))
    # Absent (single-slice) env leaves specs untouched.
    monkeypatch.delenv("MEGASCALE_NUM_SLICES")
    assert build_mesh(MeshSpec(data=-1)).shape["dcn_data"] == 1


def test_launcher_slice_config(monkeypatch):
    """slice_config surfaces the megascale identity to in-pod code;
    single-slice pods (no MEGASCALE vars) read None."""
    from kubeflow_tpu.training.launcher import slice_config

    assert slice_config({}) is None
    env = {
        "MEGASCALE_NUM_SLICES": "2",
        "MEGASCALE_SLICE_ID": "1",
        "MEGASCALE_COORDINATOR_ADDRESS": "j-s0-tpu-worker-0.j.ns:8477",
    }
    cfg = slice_config(env)
    assert cfg == {
        "num_slices": 2,
        "slice_id": 1,
        "coordinator_address": "j-s0-tpu-worker-0.j.ns:8477",
    }


def test_fsdp_sharding_splits_large_weights(cpu_devices):
    mesh = build_mesh(MeshSpec(data=2, fsdp=4))
    params = {
        "big": jnp.zeros((1024, 512)),
        "small": jnp.zeros((3,)),
    }
    sh = fsdp_params_sharding(mesh, params, min_weight_size=1024)
    assert "fsdp" in str(sh["big"].spec)
    assert sh["small"].spec == jax.sharding.PartitionSpec()


@pytest.fixture(scope="module")
def trained():
    mesh = build_mesh(MeshSpec(data=4, fsdp=2))
    model = resnet18ish(num_classes=10)
    tx = optax.sgd(0.1, momentum=0.9)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(model, tx, rng, jnp.zeros((1, 32, 32, 3), jnp.bfloat16))
    state = place_state(mesh, state)
    batch = place_batch(mesh, {
        "inputs": jax.random.normal(rng, (16, 32, 32, 3), jnp.bfloat16),
        "labels": jax.random.randint(rng, (16,), 0, 10),
    })
    step = make_train_step(mesh)
    metrics_log = []
    for _ in range(3):
        state, metrics = step(state, batch)
        metrics_log.append(jax.tree.map(float, metrics))
    return state, metrics_log


def test_train_step_runs_and_advances(trained):
    state, metrics_log = trained
    assert int(state.step) == 3
    assert all(m["loss"] > 0 for m in metrics_log)


def test_train_step_learns_on_fixed_batch(trained):
    _, metrics_log = trained
    # Same batch 3x: loss must strictly decrease (sanity that gradients flow).
    losses = [m["loss"] for m in metrics_log]
    assert losses[2] < losses[0]


def test_batch_stats_update(trained):
    state, _ = trained
    # BN statistics must have moved off their init (mean 0 / var 1).
    leaves = jax.tree.leaves(state.batch_stats)
    assert any(float(jnp.abs(l).max()) > 1e-6 for l in leaves if l.ndim)


# Throughput/profiler smokes compile a full train loop each and assert
# no numerics — slow tier so tier-1 spends its budget on correctness
# tests (ISSUE 16 suite-speed pass).
@pytest.mark.slow
def test_benchmark_smoke(cpu_devices):
    from kubeflow_tpu.training.benchmark import BenchConfig, run_benchmark

    result = run_benchmark(BenchConfig(
        model="resnet-test", batch_size=16, steps=2, warmup_steps=1))
    assert result["images_per_sec"] > 0
    assert result["n_chips"] == 8
    assert result["images_per_sec_per_chip"] * 8 == pytest.approx(
        result["images_per_sec"])


@pytest.mark.slow
def test_benchmark_profile_capture(cpu_devices, tmp_path):
    """--profile_dir writes an XPlane trace of the timed steps that the
    trace scanner (utils/traces.py — the dashboard's source) finds."""
    from kubeflow_tpu.training.benchmark import BenchConfig, run_benchmark
    from kubeflow_tpu.utils.traces import list_traces

    profile_dir = tmp_path / "prof" / "smokejob"
    result = run_benchmark(BenchConfig(
        model="resnet-test", batch_size=16, steps=2, warmup_steps=1,
        profile_dir=str(profile_dir)))
    assert result["images_per_sec"] > 0
    traces = list_traces(str(tmp_path / "prof"))
    assert traces, "profiler wrote no discoverable trace"
    assert traces[0]["job"].startswith("smokejob")
    assert any(f["name"].endswith(".xplane.pb")
               for f in traces[0]["files"])


def test_graft_entry_single(cpu_devices):
    import __graft_entry__ as graft

    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 1000)


# Spawns an 8-device child interpreter (full jax re-import + compile
# under XLA_FLAGS device forcing) — by far the heaviest single test in
# the file and exercises no numerics in-process: slow tier.
@pytest.mark.slow
def test_graft_dryrun_multichip(cpu_devices):
    import __graft_entry__ as graft

    graft.dryrun_multichip(8)


def test_s2d_stem_is_equivalent_reparametrization():
    """The space-to-depth stem must compute the SAME function as the
    7x7/s2 stem once the kernel is transformed (MLPerf conv0 trick)."""
    import numpy as np

    from kubeflow_tpu.models.resnet import (
        ResNet,
        space_to_depth,
        stem_kernel_to_s2d,
    )

    ref = ResNet(stage_sizes=(1,), num_classes=10, width=16,
                 dtype=jnp.float32, stem="conv7")
    s2d = ResNet(stage_sizes=(1,), num_classes=10, width=16,
                 dtype=jnp.float32, stem="s2d")
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 64, 3),
                          jnp.float32)
    variables = ref.init(jax.random.PRNGKey(1), x, train=False)
    w7 = variables["params"]["conv_init"]["kernel"]
    s2d_vars = jax.tree_util.tree_map(lambda v: v, variables)
    s2d_params = dict(s2d_vars["params"])
    s2d_params["conv_init"] = {"kernel": stem_kernel_to_s2d(w7)}
    out_ref = ref.apply(variables, x, train=False)
    out_s2d = s2d.apply(
        {"params": s2d_params, "batch_stats": variables["batch_stats"]},
        x, train=False)
    np.testing.assert_allclose(np.asarray(out_s2d), np.asarray(out_ref),
                               atol=2e-4, rtol=2e-4)
    # And the raw packing matches the kernel derivation's channel order.
    probe = jnp.arange(2 * 4 * 4 * 3, dtype=jnp.float32).reshape(2, 4, 4, 3)
    packed = space_to_depth(probe)
    assert packed.shape == (2, 2, 2, 12)
    np.testing.assert_array_equal(
        np.asarray(packed[0, 0, 0]),
        np.asarray(jnp.concatenate(
            [probe[0, 0, 0], probe[0, 0, 1], probe[0, 1, 0],
             probe[0, 1, 1]])))
