# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Watch-driven operator (VERDICT-r4 next #2): event streams with
resourceVersion resume on the fake apiserver, the informer-style
controller's sub-second reaction, the relist safety net, the
production stdlib-HTTP client driven over REAL sockets (REST + a
streaming watch against an HTTP facade of the fake), and event-driven
chaos fuzz.
"""

import json
import threading
import time
import urllib.request

import pytest

from kubeflow_tpu.manifests.tpujob import KIND
from kubeflow_tpu.operator import FakeApiServer
from kubeflow_tpu.operator.controller import WatchController
from kubeflow_tpu.operator.fake import Conflict, Gone, NotFound
from kubeflow_tpu.operator.http_client import HttpApiClient
from kubeflow_tpu.operator.reconciler import JOB_LABEL

from tests._http_apiserver import HttpFakeApiServer
from tests.test_operator import make_job, submit


def _collect(api, kind, n, resource_version=0, timeout=5.0):
    """First n watch events of `kind` (helper thread + join)."""
    out = []
    stop = threading.Event()

    def run():
        for event in api.watch(kind, resource_version=resource_version,
                               stop=stop):
            out.append(event)
            if len(out) >= n:
                return

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout)
    stop.set()
    return out


# -- fake watch semantics -------------------------------------------------


def test_fake_watch_streams_and_resumes():
    api = FakeApiServer()
    job = make_job(name="w1", workers=1)
    api.create(job)
    events = _collect(api, KIND, 1)
    assert [(t, o["metadata"]["name"]) for t, o in events] == \
        [("ADDED", "w1")]
    horizon = int(events[0][1]["metadata"]["resourceVersion"])

    api.patch(KIND, "default", "w1",
              lambda o: o.setdefault("status", {}).update({"phase": "X"}))
    api.delete(KIND, "default", "w1")
    # Resume AFTER the ADDED: exactly the two later events replay.
    events = _collect(api, KIND, 2, resource_version=horizon)
    assert [t for t, _ in events] == ["MODIFIED", "DELETED"]


def test_fake_watch_filters_kind_and_namespace():
    api = FakeApiServer()
    api.create({"kind": "Pod", "metadata": {"name": "p", "namespace": "a",
                                            "labels": {}}})
    api.create(make_job(name="w2", workers=1))
    events = _collect(api, "Pod", 1)
    assert events[0][1]["metadata"]["name"] == "p"
    assert _collect(api, "Pod", 1, timeout=0.5,
                    resource_version=api.current_revision()) == []


def test_fake_watch_gone_on_compacted_version(monkeypatch):
    monkeypatch.setattr(FakeApiServer, "EVENT_WINDOW", 2)
    api = FakeApiServer()
    for i in range(5):
        api.create({"kind": "Pod",
                    "metadata": {"name": f"p{i}", "namespace": "a"}})
    with pytest.raises(Gone):
        list(api.watch("Pod", resource_version=1, timeout=0.1))


# -- watch controller -----------------------------------------------------


@pytest.fixture()
def controller_on(request):
    """Start a WatchController over an api in a thread; stop at exit."""

    def start(api, **kwargs):
        ctl = WatchController(api, relist_seconds=kwargs.pop(
            "relist_seconds", 30.0), **kwargs)
        t = threading.Thread(target=ctl.run, daemon=True)
        t.start()
        request.addfinalizer(lambda: (ctl.stop.set(), t.join(timeout=10)))
        return ctl

    return start


def _wait_for(predicate, timeout=5.0, interval=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def test_watch_controller_subsecond_reaction(controller_on):
    """The r4 poll loop reacted in up to resync_seconds (5 s); the
    watch controller must react to job creation AND to a pod failure
    in event latency — asserted here at well under a second each."""
    api = FakeApiServer()
    controller_on(api)

    t0 = time.monotonic()
    submit(api, make_job(name="wjob", workers=2))
    assert _wait_for(lambda: len(
        api.list("Pod", "default", {JOB_LABEL: "wjob"})) == 2, 1.0), \
        "gang not created within 1s of the TPUJob event"
    created_in = time.monotonic() - t0

    api.set_all_pod_phases("default", "Running", {JOB_LABEL: "wjob"})
    assert _wait_for(lambda: api.get(KIND, "default", "wjob")
                     .get("status", {}).get("phase") == "Running", 1.0)

    t1 = time.monotonic()
    api.set_pod_phase("default", "wjob-tpu-worker-1", "Failed")
    assert _wait_for(lambda: api.get(KIND, "default", "wjob")
                     .get("status", {}).get("restartCount", 0) == 1, 1.0), \
        "slice fault not reacted to within 1s of the pod event"
    reacted_in = time.monotonic() - t1
    # Both reactions are event-driven, not resync-period-driven.
    assert created_in < 1.0 and reacted_in < 1.0, (created_in, reacted_in)


def test_watch_gone_relists_immediately_without_error_backoff(
        controller_on):
    """410 Gone (compacted resourceVersion) is NOT a transport error:
    the watch loop must relist-and-resume immediately — counted in
    watch_gone, never in watch_errors, and never delayed by the
    error backoff (a compaction storm must not slow reconciliation).
    """
    api = FakeApiServer()
    real_watch = api.watch
    gone_raised = threading.Event()

    def watch_gone_once(kind, *args, **kwargs):
        if kind == KIND and not gone_raised.is_set():
            gone_raised.set()
            raise Gone("resourceVersion 1 compacted")
        return real_watch(kind, *args, **kwargs)

    api.watch = watch_gone_once
    ctl = controller_on(api)
    submit(api, make_job(name="gjob", workers=1))
    t0 = time.monotonic()
    assert _wait_for(lambda: len(
        api.list("Pod", "default", {JOB_LABEL: "gjob"})) == 1, 2.0), \
        "job not reconciled after a Gone'd watch"
    # Sub-second reaction even though the first watch died with 410:
    # the relist-and-resume is immediate, not error-backoff-delayed.
    assert time.monotonic() - t0 < 2.0
    assert gone_raised.is_set()
    assert ctl.watch_gone.get(KIND, 0) >= 1
    assert ctl.watch_errors == {}, ctl.watch_errors


def test_watch_transport_errors_are_counted_and_backed_off(
        controller_on):
    """Contrast with Gone: a genuine transport failure increments
    watch_errors and the loop retries with backoff (but the relist
    safety net still converges the world — see the broken-watch test
    below)."""
    api = FakeApiServer()

    def broken_watch(*a, **k):
        raise RuntimeError("watch transport down")
        yield  # pragma: no cover

    api.watch = broken_watch
    ctl = controller_on(api, relist_seconds=0.2)
    submit(api, make_job(name="tjob", workers=1))
    assert _wait_for(lambda: len(
        api.list("Pod", "default", {JOB_LABEL: "tjob"})) == 1, 5.0)
    assert _wait_for(lambda: sum(ctl.watch_errors.values()) >= 2, 5.0)
    assert ctl.watch_gone == {}


def test_watch_controller_relist_fallback_survives_broken_watch(
        controller_on):
    """Watch streams can drop events (compaction, restarts); the
    periodic relist must still converge the world. Break watch()
    entirely — the controller's only signal is the relist."""
    api = FakeApiServer()

    def broken_watch(*a, **k):
        raise RuntimeError("watch transport down")
        yield  # pragma: no cover

    api.watch = broken_watch
    controller_on(api, relist_seconds=0.2)
    submit(api, make_job(name="rjob", workers=1))
    assert _wait_for(lambda: len(
        api.list("Pod", "default", {JOB_LABEL: "rjob"})) == 1, 5.0), \
        "relist fallback never reconciled the job"


# -- production HTTP client over real sockets -----------------------------


def test_http_client_store_surface_and_taxonomy():
    with HttpFakeApiServer(token="sekret") as srv:
        client = HttpApiClient(srv.url, token="sekret")
        job = make_job(name="hjob", workers=1)
        created = client.create(job)
        assert created["metadata"]["name"] == "hjob"
        with pytest.raises(Conflict):
            client.create(job)

        got = client.get(KIND, "default", "hjob")
        assert got["spec"]["replicaSpecs"]

        client.patch(KIND, "default", "hjob",
                     lambda o: o.setdefault("status", {}).update(
                         {"phase": "Running"}))
        assert client.get(KIND, "default", "hjob")["status"]["phase"] == \
            "Running"

        items, version = client.list_with_version(KIND, "default")
        assert [i["metadata"]["name"] for i in items] == ["hjob"]
        assert version > 0
        # Label selectors ride the query string.
        srv.fake.create({"kind": "Pod", "metadata": {
            "name": "lp", "namespace": "default",
            "labels": {JOB_LABEL: "hjob"}}})
        assert [p["metadata"]["name"] for p in client.list(
            "Pod", "default", {JOB_LABEL: "hjob"})] == ["lp"]

        client.delete("Pod", "default", "lp")
        with pytest.raises(NotFound):
            client.get("Pod", "default", "lp")
        with pytest.raises(NotFound):
            client.delete("Pod", "default", "lp")

        # Bad token → RuntimeError (401), not silent success.
        with pytest.raises(RuntimeError):
            HttpApiClient(srv.url, token="wrong").get(
                KIND, "default", "hjob")


def test_http_client_optimistic_concurrency_conflict():
    """Two writers read the same resourceVersion; the slower PUT must
    Conflict (the reconciler's retry taxonomy), not lose the update."""
    with HttpFakeApiServer() as srv:
        client = HttpApiClient(srv.url)
        client.create(make_job(name="cjob", workers=1))

        def racing_mutate(obj):
            # Interleave: another writer commits AFTER our read.
            srv.fake.patch(KIND, "default", "cjob",
                           lambda o: o.setdefault("status", {}).update(
                               {"phase": "Sneaky"}))
            obj.setdefault("status", {})["phase"] = "Mine"

        with pytest.raises(Conflict):
            client.patch(KIND, "default", "cjob", racing_mutate)


def test_http_client_watch_stream_and_gone():
    with HttpFakeApiServer() as srv:
        client = HttpApiClient(srv.url)
        client.create(make_job(name="wjob", workers=1))
        events = list(client.watch(KIND, "default", timeout=1))
        # The idle-timeout BOOKMARK rides last (resume-point refresh).
        assert [(t, o["metadata"]["name"]) for t, o in events
                if t != "BOOKMARK"] == [("ADDED", "wjob")]
        # Compacted resume point → Gone surfaced from the ERROR event.
        srv.fake.EVENT_WINDOW = 1
        for i in range(4):
            srv.fake.create({"kind": "Pod", "metadata": {
                "name": f"p{i}", "namespace": "default"}})
        with pytest.raises(Gone):
            list(client.watch("Pod", "default", resource_version=1,
                              timeout=1))


def test_http_watch_emits_bookmark_frames():
    """An idle watch with allowWatchBookmarks (which HttpApiClient
    always sends) must end with a BOOKMARK frame whose only payload is
    the store-head resourceVersion — the resume-point refresh that
    keeps a quiet watcher from aging into a 410."""
    with HttpFakeApiServer() as srv:
        client = HttpApiClient(srv.url)
        client.create(make_job(name="bmk", workers=1))
        events = list(client.watch(KIND, "default", timeout=1))
        assert events, "expected at least the ADDED event"
        assert events[0][0] == "ADDED"
        event_type, obj = events[-1]
        assert event_type == "BOOKMARK"
        assert int(obj["metadata"]["resourceVersion"]) == \
            srv.fake.current_revision()
        # Only a resume point rides a bookmark — no object payload.
        assert "name" not in obj["metadata"]
        assert "spec" not in obj


def test_http_watch_410_error_object_is_real_shaped():
    """The expired-watch ERROR frame must carry a real v1 Status
    (status/reason/code), byte-compatible with what a genuine
    apiserver emits — not a bare {code: 410} stub."""
    with HttpFakeApiServer() as srv:
        srv.fake.EVENT_WINDOW = 1
        for i in range(4):
            srv.fake.create({"kind": "Pod", "metadata": {
                "name": f"p{i}", "namespace": "default"}})
        client = HttpApiClient(srv.url)
        url = (client._path("Pod", "default")
               + "?watch=1&resourceVersion=1&timeoutSeconds=1"
               + "&allowWatchBookmarks=true")
        with urllib.request.urlopen(url, timeout=5) as resp:
            frame = json.loads(resp.readline())
        assert frame["type"] == "ERROR"
        status = frame["object"]
        assert status["kind"] == "Status"
        assert status["apiVersion"] == "v1"
        assert status["status"] == "Failure"
        assert status["reason"] == "Expired"
        assert status["code"] == 410
        assert "compacted" in status["message"]
        # And the client maps that frame back onto the Gone taxonomy.
        with pytest.raises(Gone):
            list(client.watch("Pod", "default", resource_version=1,
                              timeout=1))


def test_watch_controller_bookmarks_refresh_resume_point(
        controller_on):
    """The controller's BOOKMARK special-case, finally executed end to
    end: unrelated churn compacts the event window while the
    controller's watches idle, and the bookmark-refreshed resume point
    keeps every re-watch inside the window — zero 410s, zero relists
    from Gone. (Contrast: the direct-fake test below runs the same
    churn without bookmarks and MUST go Gone.)"""
    with HttpFakeApiServer() as srv:
        srv.fake.EVENT_WINDOW = 4
        client = HttpApiClient(srv.url)
        ctl = controller_on(client, relist_seconds=1.0)
        submit(client, make_job(name="bmjob", workers=1))
        assert _wait_for(lambda: len(srv.fake.list(
            "Pod", "default", {JOB_LABEL: "bmjob"})) == 1, 5.0)
        # Churn a foreign namespace in sub-window bursts: the live
        # watches skip every event (kind/ns filtered, nothing
        # yielded), so only bookmarks can keep the resume point ahead
        # of the compaction horizon.
        for burst in range(15):
            for j in range(2):
                with srv.fake.as_kubelet():
                    srv.fake.create({"kind": "Pod", "metadata": {
                        "name": f"churn-{burst}-{j}",
                        "namespace": "elsewhere"}})
            time.sleep(0.03)
        time.sleep(2.5)  # >= 2 idle watch timeouts + re-watches
        assert ctl.watch_gone == {}, \
            f"bookmark resume point went stale: {ctl.watch_gone}"
        assert ctl.watch_errors == {}
        # Liveness after all that: a fresh job still reconciles.
        submit(client, make_job(name="bmjob2", workers=1))
        assert _wait_for(lambda: len(srv.fake.list(
            "Pod", "default", {JOB_LABEL: "bmjob2"})) == 1, 5.0)


def test_watch_controller_goes_gone_without_bookmarks(controller_on):
    """The contrast case: the direct in-process FakeApiServer watch
    defaults to no bookmarks, so the same foreign churn ages the
    controller's resume point past the window and the next re-watch
    410s — proving the bookmark test above exercises a path that
    actually matters (and the Gone recovery path still converges)."""
    api = FakeApiServer()
    api.EVENT_WINDOW = 4
    ctl = controller_on(api, relist_seconds=0.5)
    submit(api, make_job(name="gjob", workers=1))
    assert _wait_for(lambda: len(api.list(
        "Pod", "default", {JOB_LABEL: "gjob"})) == 1, 5.0)
    for i in range(30):
        with api.as_kubelet():
            api.create({"kind": "Pod", "metadata": {
                "name": f"gchurn-{i}", "namespace": "elsewhere"}})
    assert _wait_for(
        lambda: sum(ctl.watch_gone.values()) >= 1, 8.0), \
        "stale resume point never went Gone without bookmarks"
    assert ctl.watch_errors == {}  # Gone is not a transport error
    submit(api, make_job(name="gjob2", workers=1))
    assert _wait_for(lambda: len(api.list(
        "Pod", "default", {JOB_LABEL: "gjob2"})) == 1, 5.0)


def test_watch_controller_end_to_end_over_http(controller_on):
    """The full production stack minus the real apiserver: reconciler
    → WatchController → HttpApiClient → HTTP socket → store. Job
    creation and slice fault both flow through the wire."""
    with HttpFakeApiServer(token="t0k") as srv:
        client = HttpApiClient(srv.url, token="t0k")
        controller_on(client)
        submit(client, make_job(name="ejob", workers=2))
        assert _wait_for(lambda: len(srv.fake.list(
            "Pod", "default", {JOB_LABEL: "ejob"})) == 2, 5.0)
        srv.fake.set_all_pod_phases("default", "Running",
                                    {JOB_LABEL: "ejob"})
        assert _wait_for(
            lambda: srv.fake.get(KIND, "default", "ejob")
            .get("status", {}).get("phase") == "Running", 5.0)
        srv.fake.set_pod_phase("default", "ejob-tpu-worker-0", "Failed")
        assert _wait_for(
            lambda: srv.fake.get(KIND, "default", "ejob")
            .get("status", {}).get("restartCount", 0) == 1, 5.0)


def test_crd_declares_status_subresource():
    """The operator writes status through /status (kubectl
    --subresource and the HTTP client's PUT); a CRD without
    subresources.status makes the real apiserver 404 that endpoint —
    and _set_status swallows NotFound, silently dropping every status
    update (r5 review finding)."""
    from kubeflow_tpu.manifests.tpujob import crd

    version = crd()["spec"]["versions"][0]
    assert version["subresources"] == {"status": {}}


def test_noop_status_write_emits_no_event():
    """Steady state must be quiescent: re-writing an identical status
    bumps nothing and emits nothing — otherwise the controller's own
    status write would re-enqueue the job it just reconciled, forever
    (r5 review finding)."""
    api = FakeApiServer()
    submit(api, make_job(name="q", workers=1))
    rev = api.current_revision()

    def same_status(obj):
        obj.setdefault("status", {}).update({"phase": "Pending"})

    api.patch(KIND, "default", "q", same_status)
    first_write = api.current_revision()
    assert first_write > rev  # real change: event
    api.patch(KIND, "default", "q", same_status)
    assert api.current_revision() == first_write  # no-op: no event
    assert _collect(api, KIND, 1, resource_version=first_write,
                    timeout=0.3) == []


def test_watch_controller_is_quiescent_at_steady_state(controller_on):
    """With no-op suppression in place, a Running job generates zero
    further events: the controller must go idle (no reconcile churn),
    observable as a frozen store revision."""
    api = FakeApiServer()
    controller_on(api)
    submit(api, make_job(name="idle", workers=1))
    assert _wait_for(lambda: len(
        api.list("Pod", "default", {JOB_LABEL: "idle"})) == 1, 2.0)
    api.set_all_pod_phases("default", "Running", {JOB_LABEL: "idle"})
    assert _wait_for(lambda: api.get(KIND, "default", "idle")
                     .get("status", {}).get("phase") == "Running", 2.0)
    time.sleep(0.3)  # several event-latency periods
    rev = api.current_revision()
    time.sleep(0.5)
    assert api.current_revision() == rev, \
        "controller churned events at steady state"


def test_pod_watch_is_label_bounded():
    """The operator's pod watch/list must be selector-bounded: it
    scales with gang count, not with unrelated cluster churn (r5
    review finding). Presence selectors work over the wire too."""
    api = FakeApiServer()
    api.create({"kind": "Pod", "metadata": {
        "name": "unrelated", "namespace": "default", "labels": {}}})
    api.create({"kind": "Pod", "metadata": {
        "name": "ours", "namespace": "default",
        "labels": {JOB_LABEL: "j"}}})
    assert [p["metadata"]["name"] for p in api.list(
        "Pod", "default", {JOB_LABEL: None})] == ["ours"]
    # And over HTTP: labelSelector=key (existence, no '=').
    with HttpFakeApiServer(fake=api) as srv:
        client = HttpApiClient(srv.url)
        assert [p["metadata"]["name"] for p in client.list(
            "Pod", "default", {JOB_LABEL: None})] == ["ours"]
        events = list(client.watch(
            "Pod", "default", timeout=0.5,
            label_selector={JOB_LABEL: None}))
        assert [o["metadata"]["name"] for t, o in events
                if t != "BOOKMARK"] == ["ours"]


def test_reconciler_fuzz_through_http_client():
    """The r4 weakness: the fuzz exercised the reconciler against the
    fake directly, never the production client layer. Re-run seeded
    chaos episodes with every reconciler operation flowing through
    HttpApiClient → HTTP socket → facade → store (chaos still mutates
    the store directly, as a kubelet would)."""
    import random

    from kubeflow_tpu.operator.reconciler import Reconciler

    with HttpFakeApiServer(token="fz") as srv:
        client = HttpApiClient(srv.url, token="fz")
        for seed in range(6):
            rng = random.Random(seed)
            name = f"fz{seed}"
            max_restarts = rng.randint(0, 2)
            job = make_job(name=name, workers=rng.randint(1, 3))
            client.create(job)
            r = Reconciler(client, max_restarts=max_restarts)
            for _ in range(rng.randint(10, 25)):
                pods = srv.fake.list("Pod", "default", {JOB_LABEL: name})
                roll = rng.random()
                if roll < 0.5 or not pods:
                    r.reconcile(client.get(KIND, "default", name))
                elif roll < 0.85:
                    srv.fake.set_pod_phase(
                        "default",
                        rng.choice(pods)["metadata"]["name"],
                        rng.choice(("Pending", "Running", "Succeeded",
                                    "Failed")))
                else:
                    srv.fake.delete(
                        "Pod", "default",
                        rng.choice(pods)["metadata"]["name"])
                status = client.get(KIND, "default", name).get(
                    "status", {})
                assert int(status.get("restartCount", 0)) <= max_restarts
            # Liveness wind-down over the wire.
            for _ in range(4 * (max_restarts + 1) + 7):
                srv.fake.set_all_pod_phases("default", "Succeeded",
                                            {JOB_LABEL: name})
                phase = r.reconcile(client.get(KIND, "default", name))
                if phase in ("Succeeded", "Failed"):
                    break
            assert phase in ("Succeeded", "Failed"), (seed, phase)


# -- event-driven chaos fuzz ----------------------------------------------


def test_watch_controller_fuzz_event_driven(controller_on):
    """The r4 fuzz drove reconcile() synchronously; event delivery
    adds a new interleaving class (events landing while a pass is
    mid-flight). Chaos-mutate pod phases under a LIVE controller,
    sample the safety invariants, then require liveness: once chaos
    stops, the job reaches a terminal phase and stays there."""
    import random

    for seed in range(12):
        rng = random.Random(seed)
        api = FakeApiServer()
        max_restarts = rng.randint(0, 3)
        from kubeflow_tpu.operator.reconciler import Reconciler

        ctl = WatchController(
            api, relist_seconds=0.3,
            reconciler=Reconciler(api, max_restarts=max_restarts))
        t = threading.Thread(target=ctl.run, daemon=True)
        t.start()
        try:
            submit(api, make_job(name="fz", workers=rng.randint(1, 3),
                                 recovery="restart-slice"))
            prev_restarts = 0
            for _ in range(rng.randint(10, 25)):
                pods = api.list("Pod", "default", {JOB_LABEL: "fz"})
                roll = rng.random()
                if pods and roll < 0.6:
                    victim = rng.choice(pods)["metadata"]["name"]
                    try:
                        api.set_pod_phase(
                            "default", victim,
                            rng.choice(("Pending", "Running",
                                        "Succeeded", "Failed")))
                    except NotFound:
                        pass  # reconciler deleted it mid-roll
                elif pods and roll < 0.8:
                    try:
                        api.delete("Pod", "default",
                                   rng.choice(pods)["metadata"]["name"])
                    except NotFound:
                        pass
                time.sleep(rng.random() * 0.02)
                status = api.get(KIND, "default", "fz").get("status", {})
                restarts = int(status.get("restartCount", 0))
                assert restarts <= max_restarts
                assert restarts >= prev_restarts  # monotone
                prev_restarts = restarts

            # Liveness: chaos over; drive every pod that appears to
            # Succeeded until the job goes terminal.
            def terminal():
                api.set_all_pod_phases("default", "Succeeded",
                                       {JOB_LABEL: "fz"})
                return api.get(KIND, "default", "fz").get(
                    "status", {}).get("phase") in ("Succeeded", "Failed")

            assert _wait_for(terminal, 15.0, interval=0.05), seed
            phase = api.get(KIND, "default", "fz")["status"]["phase"]
            time.sleep(0.5)  # controller keeps running; must not move
            assert api.get(KIND, "default", "fz")["status"]["phase"] == \
                phase, seed
        finally:
            ctl.stop.set()
            t.join(timeout=10)
