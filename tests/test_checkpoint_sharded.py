# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Continuous sharded checkpoints (ISSUE 12): per-host shard writes,
manifest-last crash safety, and the mesh-resharding restore math — a
4-host checkpoint restored into 3- and 2-host dp/fsdp meshes (and
back up to 4) bitwise-equal to the single-host reassembly reference,
optimizer moments included.

Cost discipline: exactly ONE test builds full LM train states (the
resharding acceptance — it needs real params + adamw moments on real
meshes); every other protocol property (commit ordering, torn writes,
async overlap, pruning, fit() wiring) is proven on small plain
pytrees, which the checkpointer treats identically."""

import json
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import struct

from kubeflow_tpu.parallel.mesh import (
    MeshSpec,
    build_mesh,
    respec_for_devices,
)
from kubeflow_tpu.training.checkpoint import (
    MANIFEST_FILE,
    CheckpointConfig,
    Checkpointer,
    ContinuousCheckpointConfig,
    ShardedCheckpointer,
    atomic_write_bytes,
    flatten_state,
)

HOSTS = 4


def _gang(tmp_path, num_hosts=HOSTS, **kw):
    """An emulated num_hosts-host gang: one checkpointer per host over
    one shared directory (exactly the multi-host protocol, minus the
    network)."""
    kw.setdefault("save_interval_steps", 1)
    kw.setdefault("min_shard_size", 8)
    kw.setdefault("commit_timeout_seconds", 10.0)
    return [ShardedCheckpointer(ContinuousCheckpointConfig(
        directory=str(tmp_path / "cont"), num_hosts=num_hosts,
        host_id=h, **kw)) for h in range(num_hosts)]


def _small_state(step=1, scale=1.0):
    """A cheap stand-in train state: sharded-sized leaves (divisible
    by 4 AND re-split-able to any host count after reassembly), a
    replicated small leaf, and a scalar step."""
    return {
        "params": {"w": (jnp.arange(48, dtype=jnp.float32)
                         .reshape(12, 4) * scale),
                   "b": jnp.ones((3,)) * scale},
        "opt": {"mu": jnp.full((8, 2), 0.25 * scale)},
        "step": jnp.asarray(step),
    }


def _save_all(gang, step, state):
    for ckpt in gang:
        assert ckpt.save(step, state, force=True)
    for ckpt in gang:
        assert ckpt.wait(15.0)


def _assert_states_equal(a, b):
    flat_a, _ = flatten_state(a)
    flat_b, _ = flatten_state(b)
    assert set(flat_a) == set(flat_b)
    for key in flat_a:
        np.testing.assert_array_equal(
            np.asarray(flat_a[key]), np.asarray(flat_b[key]),
            err_msg=key)


# -- the resharding acceptance (the one full-LM test) ---------------------


def _adamw_train_state(mesh, *, updates=2):
    """A REAL sharded adamw train state without the cost of a model
    forward: fsdp-sharded params placed via the production sharding
    rules (parallel/mesh.fsdp_params_sharding), adamw moments
    mirrored onto the same layouts, a couple of deterministic
    optimizer updates applied. Bitwise-deterministic for any mesh
    (updates are elementwise — no cross-device reductions), so
    cross-mesh restores can be compared EXACTLY. (The full llama
    path, where gradients DO reduce across the mesh, rides the
    slow-tier elastic citest with its documented tolerance.)"""
    from kubeflow_tpu.parallel.mesh import (
        fsdp_params_sharding,
        mirror_param_shardings,
        replicated,
    )

    params = {
        "dense": {"w": jnp.arange(48 * 16, dtype=jnp.float32)
                  .reshape(48, 16) / 97.0,
                  "b": jnp.ones((8,))},
        "scale": jnp.asarray(2.0),
    }
    shardings = fsdp_params_sharding(mesh, params, min_weight_size=64)
    params = jax.tree.map(jax.device_put, params, shardings)
    tx = optax.adamw(1e-2)
    opt_state = tx.init(params)
    opt_sh = mirror_param_shardings(opt_state, shardings,
                                    replicated(mesh))
    opt_state = jax.tree.map(
        lambda leaf, sh: jax.device_put(leaf, sh)
        if hasattr(leaf, "shape") else leaf, opt_state, opt_sh)
    step = 0
    for _ in range(updates):
        grads = jax.tree.map(lambda p: p * 0.01 + 0.5, params)
        upd, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, upd)
        step += 1
    return {"step": jnp.asarray(step), "params": params,
            "opt_state": opt_state}


def test_reshard_4_to_3_to_2_and_back_with_moments(tmp_path):
    """The elastic acceptance math: a 4-host dp×fsdp checkpoint of a
    real sharded adamw train state (params + first/second moments)
    restores bitwise into 3- and 2-host meshes and back up to 4,
    equal to the single-host reassembly reference."""
    devices = jax.devices()
    mesh4 = build_mesh(MeshSpec(data=2, fsdp=2), devices[:4])
    state = _adamw_train_state(mesh4)
    # The fsdp rule actually sharded the big weight (white-box: the
    # test must exercise resharding, not replication).
    w = state["params"]["dense"]["w"]
    assert not w.sharding.is_fully_replicated

    gang = _gang(tmp_path, min_shard_size=64,
                 mesh_shape={"data": 2, "fsdp": 2})
    _save_all(gang, 2, state)
    for ckpt in gang:
        ckpt.close()
    reader = ShardedCheckpointer(ContinuousCheckpointConfig(
        directory=str(tmp_path / "cont"), num_hosts=1, host_id=0))

    # The manifest records the saving mesh factorization + host count.
    step_dirs = sorted((tmp_path / "cont").glob("step-*"))
    manifest = json.loads((step_dirs[-1] / MANIFEST_FILE).read_text())
    assert manifest["mesh"] == {"data": 2, "fsdp": 2}
    assert manifest["num_hosts"] == HOSTS

    # Single-host reassembly reference: restore into a 1-device mesh.
    mesh1 = build_mesh(MeshSpec(data=1), devices[:1])
    reference = reader.restore(_adamw_train_state(mesh1, updates=0))
    ref_flat, _ = flatten_state(reference)
    live_flat, _ = flatten_state(state)
    for key in live_flat:
        np.testing.assert_array_equal(
            np.asarray(ref_flat[key]), np.asarray(live_flat[key]),
            err_msg=key)

    # Mismatched dp/fsdp factorizations: 3 hosts (fsdp folds away),
    # 2 hosts (a DIFFERENT fsdp split than the saver's 2×2), then
    # back up to 4. Params AND moments land bitwise on each mesh,
    # ON the mesh (live shardings, not host arrays) — and the
    # optimizer keeps stepping identically from the restored moments.
    for n_devices, spec in (
            (3, respec_for_devices(MeshSpec(data=2, fsdp=2), 3)),
            (2, MeshSpec(data=2, fsdp=1)),
            (4, MeshSpec(data=2, fsdp=2))):
        mesh = build_mesh(spec, devices[:n_devices])
        target = _adamw_train_state(mesh, updates=0)
        restored = reader.restore(target)
        assert int(restored["step"]) == 2
        got_flat, _ = flatten_state(restored)
        for key in live_flat:
            np.testing.assert_array_equal(
                np.asarray(got_flat[key]), np.asarray(live_flat[key]),
                err_msg=f"{key} on {n_devices} devices")
        moment_leaves = [
            leaf for leaf in jax.tree.leaves(restored["opt_state"])
            if getattr(leaf, "shape", None) == (48, 16)]
        assert moment_leaves  # adamw mu AND nu mirror the weight
        assert all(getattr(leaf, "sharding", None) is not None
                   for leaf in moment_leaves)
        # Continuation equality: one more elementwise adamw update on
        # the restored state matches the uninterrupted one bitwise.
        cont_ref = _adamw_train_state(mesh4, updates=3)
        tx = optax.adamw(1e-2)
        grads = jax.tree.map(lambda p: p * 0.01 + 0.5,
                             restored["params"])
        upd, _ = tx.update(grads, restored["opt_state"],
                           restored["params"])
        cont = optax.apply_updates(restored["params"], upd)
        np.testing.assert_array_equal(
            np.asarray(cont["dense"]["w"]),
            np.asarray(cont_ref["params"]["dense"]["w"]))
    reader.close()


# -- commit protocol (plain pytrees) --------------------------------------


def test_manifest_commits_last_and_torn_write_is_invisible(tmp_path):
    """Crash-safety: a writer killed mid-shard-write never yields a
    restorable-but-wrong state. (a) White-box ordering — the manifest
    is not on disk until EVERY host's shard is; (b) a step whose
    writer died after 2 of 4 shards stays uncommitted and restore
    falls back to the previous committed step; (c) even a COMMITTED
    step whose bytes got truncated later (disk fault) is skipped."""
    state1 = _small_state(step=1, scale=1.0)
    state2 = _small_state(step=2, scale=2.0)
    gang = _gang(tmp_path, async_save=False,
                 commit_timeout_seconds=0.3)

    # (a) host 0 saves FIRST (sync): with peers missing, its commit
    # barrier times out and no manifest lands.
    assert gang[0].save(1, state1, force=True)
    step_dir = tmp_path / "cont" / "step-00000001"
    assert step_dir.is_dir()
    assert not (step_dir / MANIFEST_FILE).exists()
    assert gang[0].all_steps() == []
    # Peers arrive; the commit barrier completes the step.
    for ckpt in gang[1:]:
        ckpt.save(1, state1, force=True)
    gang[0]._commit(1, gang[0]._plan(flatten_state(state1)[0]))
    assert (step_dir / MANIFEST_FILE).exists()
    assert gang[0].all_steps() == [1]

    # (b) step 2: only hosts 0-1 write (the "kill"); the step stays
    # invisible and restore lands on step 1.
    for ckpt in gang[:2]:
        ckpt.save(2, state2, force=True)
    assert gang[0].all_steps() == [1]
    restored = gang[0].restore(_small_state(step=0, scale=0.0))
    assert int(restored["step"]) == 1
    _assert_states_equal(restored, state1)

    # (c) complete + commit step 2, then truncate one of its shards:
    # restore must skip it with a warning and land on step 1 again.
    for ckpt in gang[2:]:
        ckpt.save(2, state2, force=True)
    gang[0]._commit(2, gang[0]._plan(flatten_state(state2)[0]))
    assert gang[0].all_steps() == [1, 2]
    victim = sorted((tmp_path / "cont" / "step-00000002").glob(
        "state.shard-*"))[1]
    victim.write_bytes(victim.read_bytes()[:10])
    restored = gang[0].restore(_small_state(step=0, scale=0.0))
    assert int(restored["step"]) == 1
    # An EXPLICIT step request for the torn step raises instead.
    with pytest.raises(Exception):
        gang[0].restore(_small_state(), step=2)
    for ckpt in gang:
        ckpt.close()


def test_restore_reshards_plain_state_across_host_counts(tmp_path):
    """Host-count independence on the wire format itself: 4 writer
    shards reassemble identically regardless of the reader's own host
    count, and leaves land per the live template."""
    state = _small_state(step=7, scale=3.0)
    gang = _gang(tmp_path)
    _save_all(gang, 7, state)
    for ckpt in gang:
        ckpt.close()
    for reader_hosts in (1, 2, 3):
        reader = ShardedCheckpointer(ContinuousCheckpointConfig(
            directory=str(tmp_path / "cont"),
            num_hosts=reader_hosts, host_id=0))
        restored = reader.restore(_small_state(step=0, scale=0.0))
        _assert_states_equal(restored, state)
        reader.close()
    # Structure drift fails loudly, never a silent partial restore.
    reader = ShardedCheckpointer(ContinuousCheckpointConfig(
        directory=str(tmp_path / "cont")))
    bad = _small_state()
    bad["params"]["extra"] = jnp.zeros((2,))
    with pytest.raises(ValueError):
        reader.restore(bad)
    reader.close()


def test_async_writes_overlap_compute(tmp_path):
    """save() returns before the shard bytes are durable (the step
    loop pays only the device→host snapshot); wait() makes them so.
    White-box: gate the writer and observe save() return while the
    write is parked."""
    ckpt = ShardedCheckpointer(ContinuousCheckpointConfig(
        directory=str(tmp_path / "cont"), num_hosts=1, host_id=0,
        save_interval_steps=1, min_shard_size=8))
    gate = threading.Event()
    original = ckpt._write_one

    def gated(item):
        gate.wait(timeout=10)
        original(item)

    ckpt._write_one = gated
    assert ckpt.save(1, _small_state(), force=True)  # returns now
    assert ckpt.latest_step() is None                # nothing durable
    assert not ckpt.wait(timeout=0.2)                # writer parked
    gate.set()
    assert ckpt.wait(10.0)
    assert ckpt.latest_step() == 1
    ckpt.close()


def test_interval_policy_dedupe_and_prune(tmp_path):
    ckpt = ShardedCheckpointer(ContinuousCheckpointConfig(
        directory=str(tmp_path / "cont"), num_hosts=1, host_id=0,
        save_interval_steps=5, keep=2, min_shard_size=8))
    state = _small_state()
    assert not ckpt.save(3, state)                   # below interval
    for step in (5, 10, 15, 20):
        assert ckpt.save(step, state)                # on the interval
        assert not ckpt.save(step, state, force=True)  # deduped
        assert ckpt.wait(15.0)  # drain: the writer slot is
        # newest-wins, so back-to-back saves would coalesce
    assert ckpt.all_steps() == [15, 20]              # keep=2 pruned
    ckpt.close()


def test_writer_slot_coalesces_newest_wins(tmp_path):
    """A writer that falls behind never queues snapshots without
    bound: a save handed over while one is parked REPLACES it (only
    the freshest step matters for restore)."""
    ckpt = ShardedCheckpointer(ContinuousCheckpointConfig(
        directory=str(tmp_path / "cont"), num_hosts=1, host_id=0,
        save_interval_steps=1, min_shard_size=8))
    gate = threading.Event()
    original = ckpt._write_one

    def gated(item):
        gate.wait(timeout=10)
        original(item)

    ckpt._write_one = gated
    assert ckpt.save(1, _small_state(step=1), force=True)
    # Writer is parked on step 1's write... actually on nothing yet —
    # park it by letting it pick step 1 up, then pile on 2 and 3.
    for _ in range(100):
        with ckpt._slot_lock:
            if ckpt._writing:
                break
        import time as _t
        _t.sleep(0.01)
    assert ckpt.save(2, _small_state(step=2), force=True)
    assert ckpt.save(3, _small_state(step=3), force=True)  # replaces 2
    gate.set()
    assert ckpt.wait(10.0)
    steps = ckpt.all_steps()
    assert 3 in steps and 2 not in steps, steps  # newest won
    assert ckpt._dropped >= 1
    ckpt.close()


def test_atomic_write_never_leaves_truncation(tmp_path):
    path = tmp_path / "blob.bin"
    atomic_write_bytes(path, b"a" * 1024)
    assert path.read_bytes() == b"a" * 1024
    atomic_write_bytes(path, b"b" * 10)
    assert path.read_bytes() == b"b" * 10
    # No temp litter after a completed write.
    assert [p.name for p in tmp_path.iterdir()] == ["blob.bin"]


# -- fit() integration (cheap synthetic state) ----------------------------


class _TinyState(struct.PyTreeNode):
    step: jax.Array
    w: jax.Array


def test_fit_continuous_tier_saves_and_resumes(tmp_path):
    """Loop integration: fit() with LoopConfig.continuous writes the
    shard tier alongside steps, and a second fit() resumes from the
    freshest continuous step (ahead of the coarser Orbax tier)."""
    from kubeflow_tpu.training.loop import LoopConfig, fit

    def step_fn(state, batch):
        new = state.replace(step=state.step + 1,
                            w=state.w + batch)
        return new, {"loss": jnp.sum(new.w)}

    def batches():
        while True:
            yield jnp.ones((16,))

    config = LoopConfig(
        total_steps=3, log_every=10,
        checkpoint=CheckpointConfig(
            directory=str(tmp_path / "mono"),
            save_interval_steps=100, async_save=False),
        continuous=ContinuousCheckpointConfig(
            directory=str(tmp_path / "cont"),
            save_interval_steps=1, min_shard_size=8),
        drain_signals=())
    state = _TinyState(step=jnp.asarray(0), w=jnp.zeros((16,)))
    done = fit(state, step_fn, batches(), config)
    assert int(done.step) == 3
    reader = ShardedCheckpointer(ContinuousCheckpointConfig(
        directory=str(tmp_path / "cont")))
    assert reader.latest_step() == 3
    reader.close()

    # Resume for 2 more steps: picks up at 3, not 0 (the continuous
    # tier is at least as fresh as Orbax's final force-save and wins
    # the restore).
    config2 = LoopConfig(
        total_steps=5, log_every=10,
        checkpoint=config.checkpoint, continuous=config.continuous,
        drain_signals=())
    fresh = _TinyState(step=jnp.asarray(0), w=jnp.zeros((16,)))
    resumed = fit(fresh, step_fn, batches(), config2)
    assert int(resumed.step) == 5
    np.testing.assert_array_equal(np.asarray(resumed.w),
                                  np.full((16,), 5.0))


# -- monolithic (Orbax) hardening -----------------------------------------


def test_monolithic_restore_skips_corrupt_latest_step(tmp_path):
    """The r16 satellite: a truncated latest Orbax step — the
    artifact of the crash being recovered from — falls back to the
    previous step with a warning instead of raising mid-recovery."""
    ckpt = Checkpointer(CheckpointConfig(
        directory=str(tmp_path / "mono"), save_interval_steps=1,
        async_save=False))
    state1 = _small_state(step=1, scale=1.0)
    state2 = _small_state(step=2, scale=2.0)
    assert ckpt.save(1, state1, force=True)
    assert ckpt.save(2, state2, force=True)
    ckpt.wait()

    # Truncate every sizeable file of step 2 (a torn disk artifact
    # that slipped past the rename commit).
    corrupted = 0
    for root, _, files in os.walk(tmp_path / "mono" / "2"):
        for fname in files:
            path = os.path.join(root, fname)
            if os.path.getsize(path) > 64:
                with open(path, "r+b") as f:
                    f.truncate(32)
                corrupted += 1
    assert corrupted > 0

    ckpt2 = Checkpointer(CheckpointConfig(
        directory=str(tmp_path / "mono"), save_interval_steps=1,
        async_save=False))
    restored = ckpt2.restore(_small_state(step=0, scale=0.0))
    assert int(restored["step"]) == 1
    _assert_states_equal(restored, state1)
    # An EXPLICIT step request still raises — the caller asked for
    # that exact artifact.
    with pytest.raises(Exception):
        ckpt2.restore(_small_state(), step=2)
    ckpt.close()
    ckpt2.close()


# -- mesh respec math -----------------------------------------------------


def test_respec_for_devices_math():
    spec = MeshSpec(data=2, fsdp=2)
    assert respec_for_devices(spec, 3).sizes()["data"] == 3
    assert respec_for_devices(spec, 3).sizes()["fsdp"] == 1
    out = respec_for_devices(spec, 2)
    assert out.sizes()["data"] * out.sizes()["fsdp"] == 2
    assert out.sizes()["fsdp"] == 2  # kept: still divides
    assert respec_for_devices(spec, 4) == MeshSpec(data=2, fsdp=2)
    # Model axes are pinned: tensor=2 cannot fit 3 devices.
    with pytest.raises(ValueError):
        respec_for_devices(MeshSpec(tensor=2, data=2), 3)
    tp = respec_for_devices(MeshSpec(tensor=2, data=2), 6)
    assert tp.sizes()["tensor"] == 2 and tp.sizes()["data"] == 3


def test_flatten_state_keys_are_stable():
    state = {"params": {"w": jnp.ones((4, 4))},
             "step": jnp.asarray(0)}
    flat, treedef = flatten_state(state)
    assert set(flat) == {"params/w", "step"}
    rebuilt = jax.tree_util.tree_unflatten(
        treedef, [flat["params/w"], flat["step"]])
    assert set(rebuilt) == {"params", "step"}
