# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Checkpoint → export → serve: the full model lifecycle."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from kubeflow_tpu.models.llama import llama_test
from kubeflow_tpu.serving.export_cli import export_from_checkpoint, main
from kubeflow_tpu.serving.model import load_version
from kubeflow_tpu.training.checkpoint import CheckpointConfig, Checkpointer
from kubeflow_tpu.training.data import token_shard_batches
from kubeflow_tpu.training.finetune import (
    create_lora_state,
    make_lora_train_step,
)
from kubeflow_tpu.training.loop import LoopConfig, fit


def test_export_fresh_generate_model_and_serve(tmp_path):
    out = str(tmp_path / "models" / "lm")
    path = export_from_checkpoint(
        registry_name="llama-test", out=out, version=1,
        seq_len=8, generate_config={"max_new_tokens": 4,
                                    "temperature": 0.0},
        model_kwargs={"dtype": "float32"})
    loaded = load_version(path)
    prompt = np.asarray(jax.random.randint(
        jax.random.PRNGKey(0), (2, 8), 0, 512))
    tokens = loaded.run({"input_ids": prompt})["tokens"]
    assert tokens.shape == (2, 4)


def test_export_lora_finetune_checkpoint_and_serve(tmp_path):
    """fit() checkpoint (full LoRAState) → merged export → the served
    model reproduces the adapter model's greedy decode."""
    rng = np.random.RandomState(0)
    shard = tmp_path / "s.npy"
    np.save(shard, rng.randint(0, 512, 20_000).astype(np.uint16))

    model = llama_test(lora_rank=4, dtype="float32")
    batches = token_shard_batches([str(shard)], 4, 16, seed=3)
    first = next(token_shard_batches([str(shard)], 4, 16, seed=3))
    state, _ = create_lora_state(
        model, optax.adamw(5e-3), jax.random.PRNGKey(1), first)
    step = make_lora_train_step(None, None, donate=False)
    ckpt_dir = str(tmp_path / "ckpt")
    state = fit(state, step, batches, LoopConfig(
        total_steps=3, log_every=3,
        checkpoint=CheckpointConfig(directory=ckpt_dir,
                                    save_interval_steps=1,
                                    async_save=False)))

    out = str(tmp_path / "models" / "ft")
    path = export_from_checkpoint(
        registry_name="llama-test", out=out, version=1,
        checkpoint=ckpt_dir, lora=True, lora_rank=4, seq_len=8,
        generate_config={"max_new_tokens": 4, "temperature": 0.0},
        model_kwargs={"dtype": "float32"})
    loaded = load_version(path)

    prompt = np.asarray(jax.random.randint(
        jax.random.PRNGKey(2), (1, 8), 0, 512))
    served = loaded.run({"input_ids": prompt})["tokens"]

    # Reference: greedy decode through the unmerged adapter model.
    from kubeflow_tpu.inference import generate

    gen_model = llama_test(lora_rank=0, dtype="float32", cache_size=16)
    from kubeflow_tpu.ops.lora import merge_lora

    merged = merge_lora(
        jax.tree.map(np.asarray, state.base_params),
        jax.tree.map(np.asarray, state.lora),
        alpha=model.lora_alpha)
    want, _ = generate(gen_model, merged, jnp.asarray(prompt),
                       max_new_tokens=4, temperature=0.0)
    np.testing.assert_array_equal(served, np.asarray(want))


def test_export_cli_main_smoke(tmp_path):
    out = str(tmp_path / "m")
    rc = main(["--model", "llama-test", "--out", out, "--version", "3",
               "--seq_len", "8",
               "--generate", '{"max_new_tokens": 4}',
               "--model_kwargs", '{"dtype": "float32"}'])
    assert rc == 0
    loaded = load_version(out + "/3")
    assert loaded.version == 3
    assert loaded.signature().method == "generate"


def test_export_missing_checkpoint_fails_loudly(tmp_path):
    with pytest.raises(FileNotFoundError, match="no checkpoint"):
        export_from_checkpoint(
            registry_name="llama-test", out=str(tmp_path / "x"),
            version=1, checkpoint=str(tmp_path / "empty"), seq_len=8,
            model_kwargs={"dtype": "float32"})


def test_export_vision_model_with_batch_stats(tmp_path):
    """Vision models carry batch_stats; the export must include them
    or load_version rejects the version dir."""
    path = export_from_checkpoint(
        registry_name="resnet-test", out=str(tmp_path / "vision"),
        version=1)
    loaded = load_version(path)
    out = loaded.run({"images": np.zeros((2, 32, 32, 3), np.float32)})
    assert out["logits"].shape[0] == 2


def test_export_vision_fit_checkpoint_carries_trained_batch_stats(tmp_path):
    """The documented checkpoint→serving loop for VISION models: a
    fit()-saved TrainState carries batch_stats, and the export must
    serve the TRAINED statistics, not fresh-init ones."""
    import optax as _optax

    from kubeflow_tpu.models.registry import get_model
    from kubeflow_tpu.training.train import (
        create_train_state,
        make_train_step,
    )

    model = get_model("resnet-test").make(num_classes=10)
    state = create_train_state(
        model, _optax.sgd(0.1), jax.random.PRNGKey(0),
        jnp.zeros((1, 32, 32, 3), jnp.bfloat16))
    step = make_train_step(None)
    rng = np.random.RandomState(0)
    batch = {"inputs": jnp.asarray(rng.rand(4, 32, 32, 3), jnp.bfloat16),
             "labels": jnp.asarray(rng.randint(0, 10, 4))}
    for _ in range(2):
        state, _ = step(state, batch)
    ckpt_dir = str(tmp_path / "ckpt")
    ckpt = Checkpointer(CheckpointConfig(directory=ckpt_dir,
                                         async_save=False))
    assert ckpt.save(int(state.step), state, force=True)
    ckpt.close()

    path = export_from_checkpoint(
        registry_name="resnet-test", out=str(tmp_path / "served"),
        version=1, checkpoint=ckpt_dir)
    loaded = load_version(path)
    # Trained BN stats differ from init zeros/ones; the export must
    # carry the trained values.
    trained = jax.tree.leaves(
        jax.tree.map(np.asarray, state.batch_stats))
    served = jax.tree.leaves(
        jax.tree.map(np.asarray, loaded.variables["batch_stats"]))
    assert any(np.abs(t).sum() > 0 for t in trained)
    for t, s in zip(trained, served):
        np.testing.assert_allclose(t, s, rtol=1e-6)
    out = loaded.run({"images": np.zeros((2, 32, 32, 3), np.float32)})
    assert out["logits"].shape == (2, 10)


def test_generate_config_validation(tmp_path):
    from kubeflow_tpu.serving.export_cli import validate_generate_config

    # Coercion: JSON floats that are integral ints pass; e.g. 50.0.
    cfg = validate_generate_config(
        {"top_k": 50.0, "temperature": 1, "max_new_tokens": 8})
    assert cfg["top_k"] == 50 and isinstance(cfg["top_k"], int)
    assert isinstance(cfg["temperature"], float)
    with pytest.raises(ValueError, match="unknown generate config"):
        validate_generate_config({"max_tokens": 8})
    with pytest.raises(ValueError, match="must be an integer"):
        validate_generate_config({"top_k": 50.5})
    with pytest.raises(ValueError, match="int-like"):
        validate_generate_config({"max_new_tokens": "many"})
    with pytest.raises(ValueError, match="top_p"):
        validate_generate_config({"top_p": 1.5})
    with pytest.raises(ValueError, match="boolean"):
        validate_generate_config({"deterministic": "false"})
    # bool subclasses int: {"top_k": true} must not become top_k=1.
    with pytest.raises(ValueError, match="int-like"):
        validate_generate_config({"top_k": True})
    # Serving batching knobs are exportable: decode-slicing K and
    # prompt-length buckets (deduped ascending).
    cfg = validate_generate_config(
        {"decode_chunk_tokens": 16, "prompt_buckets": [512, 128, 128]})
    assert cfg["decode_chunk_tokens"] == 16
    assert cfg["prompt_buckets"] == [128, 512]
    with pytest.raises(ValueError, match="decode_chunk_tokens"):
        validate_generate_config({"decode_chunk_tokens": 0})
    with pytest.raises(ValueError, match="prompt_buckets"):
        validate_generate_config({"prompt_buckets": []})
    with pytest.raises(ValueError, match="prompt_buckets"):
        validate_generate_config({"prompt_buckets": [0, 8]})
    with pytest.raises(ValueError, match="prompt_buckets"):
        validate_generate_config({"prompt_buckets": "128,512"})
    # Tiered-KV knobs (ISSUE 20): both ride the version dir like the
    # engine_* family; 0 is the documented "off", negatives rejected,
    # bools never coerce to ints.
    cfg = validate_generate_config(
        {"engine_host_cache_bytes": 2 ** 30,
         "kv_fetch_deadline_ms": 250.0})
    assert cfg["engine_host_cache_bytes"] == 2 ** 30
    assert cfg["kv_fetch_deadline_ms"] == 250
    assert isinstance(cfg["kv_fetch_deadline_ms"], int)
    assert validate_generate_config(
        {"engine_host_cache_bytes": 0,
         "kv_fetch_deadline_ms": 0}) == \
        {"engine_host_cache_bytes": 0, "kv_fetch_deadline_ms": 0}
    with pytest.raises(ValueError, match="engine_host_cache_bytes"):
        validate_generate_config({"engine_host_cache_bytes": -1})
    with pytest.raises(ValueError, match="kv_fetch_deadline_ms"):
        validate_generate_config({"kv_fetch_deadline_ms": -250})
    with pytest.raises(ValueError, match="int-like"):
        validate_generate_config({"engine_host_cache_bytes": True})
    with pytest.raises(ValueError, match="int-like"):
        validate_generate_config({"kv_fetch_deadline_ms": "fast"})
    # And the exporter runs it: a bad config must not produce a
    # version dir.
    with pytest.raises(ValueError, match="unknown generate config"):
        export_from_checkpoint(
            registry_name="llama-test", out=str(tmp_path / "bad"),
            version=1, seq_len=8,
            generate_config={"max_new_tokens": 4, "typo_key": 1},
            model_kwargs={"dtype": "float32"})
    assert not (tmp_path / "bad").exists()


def test_export_rejects_incoherent_signatures(tmp_path):
    with pytest.raises(ValueError, match="language model"):
        export_from_checkpoint(
            registry_name="resnet-test", out=str(tmp_path / "a"),
            version=1, signature_kind="generate",
            generate_config={"max_new_tokens": 4})
    with pytest.raises(ValueError, match="vision model"):
        export_from_checkpoint(
            registry_name="llama-test", out=str(tmp_path / "b"),
            version=1, signature_kind="classify", seq_len=8,
            model_kwargs={"dtype": "float32"})
