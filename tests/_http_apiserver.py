# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""A real-socket HTTP facade over FakeApiServer: the k8s REST subset
the operator's HttpApiClient speaks (typed paths, list/watch
semantics, optimistic-concurrency PUT, the 404/409/410 taxonomy).

Lets tests drive the PRODUCTION client — urllib request building,
streaming watch parsing, error mapping — over an actual HTTP
connection instead of injecting the fake directly (closing the r4
weakness: the client layer was the one place prod and test behavior
could diverge).
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubeflow_tpu.operator.fake import (
    Conflict,
    FakeApiServer,
    Gone,
    NotFound,
    ServerError,
    TooManyRequests,
)

_PLURAL_TO_KIND = {
    "tpujobs": "TPUJob",
    "pods": "Pod",
    "services": "Service",
    "poddisruptionbudgets": "PodDisruptionBudget",
    "events": "Event",
    "configmaps": "ConfigMap",
    "leases": "Lease",
    "deployments": "Deployment",
}


def _parse_selector(query):
    """labelSelector → dict; ``key`` (no =) is existence → None value,
    matching FakeApiServer._labels_match."""
    if "labelSelector" not in query:
        return None
    out = {}
    for pair in query["labelSelector"][0].split(","):
        key, eq, value = pair.partition("=")
        out[key] = value if eq else None
    return out


def _parse_field_selector(query):
    """fieldSelector → dict of dotted-path equality terms, matching
    FakeApiServer._fields_match."""
    if "fieldSelector" not in query:
        return None
    out = {}
    for pair in query["fieldSelector"][0].split(","):
        key, _, value = pair.partition("=")
        out[key] = value
    return out


class _Handler(BaseHTTPRequestHandler):
    # HTTP/1.0: close-delimited bodies, so the watch stream needs no
    # chunked framing — urllib reads lines as they flush.
    protocol_version = "HTTP/1.0"

    @property
    def fake(self) -> FakeApiServer:
        return self.server.fake  # type: ignore[attr-defined]

    def log_message(self, *args):  # quiet test output
        pass

    def _parse(self):
        """path → (kind, namespace, name, subresource, query)."""
        parsed = urllib.parse.urlsplit(self.path)
        query = urllib.parse.parse_qs(parsed.query)
        parts = [p for p in parsed.path.split("/") if p]
        # /api/v1/... or /apis/<group>/<version>/...
        parts = parts[2:] if parts[0] == "api" else parts[3:]
        namespace = name = subresource = None
        if parts and parts[0] == "namespaces":
            namespace = parts[1]
            parts = parts[2:]
        plural = parts[0] if parts else ""
        if len(parts) > 1:
            name = parts[1]
        if len(parts) > 2:
            subresource = parts[2]
        kind = _PLURAL_TO_KIND.get(plural)
        return kind, namespace, name, subresource, query

    def _send(self, code: int, body: dict) -> None:
        payload = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _error(self, code: int, message: str) -> None:
        self._send(code, {"kind": "Status", "code": code,
                          "message": message})

    def _body(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(length)) if length else {}

    def _authorized(self) -> bool:
        token = getattr(self.server, "token", None)
        if not token:
            return True
        return self.headers.get("Authorization") == f"Bearer {token}"

    # -- verbs ------------------------------------------------------------

    def do_GET(self):
        try:
            return self._do_get()
        except TooManyRequests as err:  # injected 429 (fake.faults)
            return self._error(429, str(err))
        except ServerError as err:  # injected 5xx
            return self._error(500, str(err))

    def _do_get(self):
        if not self._authorized():
            return self._error(401, "bad bearer token")
        kind, ns, name, subresource, query = self._parse()
        if kind is None:
            return self._error(404, "unknown resource")
        if kind == "Pod" and subresource == "log":
            tail = int(query.get("tailLines", ["100"])[0])
            try:
                text = self.fake.pod_logs(ns, name, tail=tail)
            except NotFound as err:
                return self._error(404, str(err))
            payload = text.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
            return
        if name is not None and subresource == "scale":
            try:
                return self._send(200,
                                  self.fake.get_scale(kind, ns, name))
            except NotFound as err:
                return self._error(404, str(err))
        if name is not None:
            try:
                return self._send(200, self.fake.get(kind, ns, name))
            except NotFound as err:
                return self._error(404, str(err))
        if query.get("watch", ["0"])[0] in ("1", "true"):
            return self._watch(kind, ns, query)
        items, version = self.fake.list_with_version(
            kind, ns, _parse_selector(query), _parse_field_selector(query))
        return self._send(200, {
            "kind": f"{kind}List",
            "items": items,
            "metadata": {"resourceVersion": str(version)},
        })

    def _watch(self, kind, ns, query):
        version = int(query.get("resourceVersion", ["0"])[0] or 0)
        timeout = float(query.get("timeoutSeconds", ["5"])[0])
        bookmarks = query.get("allowWatchBookmarks",
                              ["false"])[0] in ("1", "true")
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.end_headers()

        def emit(event: dict) -> None:
            self.wfile.write(json.dumps(event).encode() + b"\n")
            self.wfile.flush()

        try:
            for event_type, obj in self.fake.watch(
                    kind, ns, resource_version=version, timeout=timeout,
                    label_selector=_parse_selector(query),
                    allow_bookmarks=bookmarks):
                emit({"type": event_type, "object": obj})
        except Gone as err:
            # Byte-for-byte the real apiserver's expired-watch frame: a
            # v1 Status with status/reason/code, NOT a bare code — the
            # controller's resume-point taxonomy keys off this shape.
            emit({"type": "ERROR",
                  "object": {"kind": "Status", "apiVersion": "v1",
                             "metadata": {}, "status": "Failure",
                             "message": str(err), "reason": "Expired",
                             "code": 410}})
        except TooManyRequests as err:
            # Injected throttle mid-stream: headers are already out,
            # so the 429 rides the stream as an ERROR event (the
            # client maps it back onto the exception taxonomy).
            emit({"type": "ERROR",
                  "object": {"kind": "Status", "code": 429,
                             "message": str(err)}})
        except ServerError as err:
            emit({"type": "ERROR",
                  "object": {"kind": "Status", "code": 500,
                             "message": str(err)}})
        except (BrokenPipeError, ConnectionResetError):
            pass  # client hung up

    def do_POST(self):
        if not self._authorized():
            return self._error(401, "bad bearer token")
        try:
            return self._send(201, self.fake.create(self._body()))
        except Conflict as err:
            return self._error(409, str(err))
        except TooManyRequests as err:
            return self._error(429, str(err))
        except ServerError as err:
            return self._error(500, str(err))

    def do_PUT(self):
        if not self._authorized():
            return self._error(401, "bad bearer token")
        kind, ns, name, subresource, _ = self._parse()
        if subresource == "scale":
            # The scale subresource PUT carries an autoscaling/v1
            # Scale object; spec.replicas is honored plus the
            # optimistic-concurrency resourceVersion (apiserver
            # contract: a stale carried version is a 409).
            try:
                body = self._body()
                replicas = int(
                    body.get("spec", {}).get("replicas", 0))
                rv = body.get("metadata", {}).get("resourceVersion")
                return self._send(
                    200, self.fake.update_scale(
                        kind, ns, name, replicas,
                        resource_version=rv))
            except NotFound as err:
                return self._error(404, str(err))
            except Conflict as err:
                return self._error(409, str(err))
            except TooManyRequests as err:
                return self._error(429, str(err))
            except ServerError as err:
                return self._error(500, str(err))
        if subresource not in (None, "status"):
            # Only the declared status subresource exists (the CRD
            # declares subresources.status; anything else 404s on a
            # real apiserver).
            return self._error(404, f"no subresource {subresource}")
        obj = self._body()
        # Status subresource PUTs replace the whole object here (the
        # fake stores status inline).
        try:
            return self._send(200, self.fake.replace(obj))
        except NotFound as err:
            return self._error(404, str(err))
        except Conflict as err:
            return self._error(409, str(err))
        except TooManyRequests as err:
            return self._error(429, str(err))
        except ServerError as err:
            return self._error(500, str(err))

    def do_DELETE(self):
        if not self._authorized():
            return self._error(401, "bad bearer token")
        kind, ns, name, _, _ = self._parse()
        try:
            self.fake.delete(kind, ns, name)
            return self._send(200, {"kind": "Status", "status": "Success"})
        except NotFound as err:
            return self._error(404, str(err))
        except TooManyRequests as err:
            return self._error(429, str(err))
        except ServerError as err:
            return self._error(500, str(err))


class HttpFakeApiServer:
    """ThreadingHTTPServer wrapper; use as a context manager."""

    def __init__(self, fake: FakeApiServer = None, token: str = ""):
        self.fake = fake or FakeApiServer()
        self.server = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
        self.server.fake = self.fake  # type: ignore[attr-defined]
        self.server.token = token  # type: ignore[attr-defined]
        self.url = f"http://127.0.0.1:{self.server.server_address[1]}"
        self.token = token
        self._thread = threading.Thread(
            target=self.server.serve_forever, daemon=True)

    def __enter__(self) -> "HttpFakeApiServer":
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self.server.shutdown()
        self.server.server_close()
        self._thread.join(timeout=5)
