# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Data pipeline + training loop (resume, metrics, prefetch)."""

import json

import jax
import numpy as np
import optax
import pytest

from kubeflow_tpu.models.llama import llama_test
from kubeflow_tpu.parallel.mesh import MeshSpec, build_mesh
from kubeflow_tpu.training.checkpoint import CheckpointConfig
from kubeflow_tpu.training.data import (
    DevicePrefetcher,
    host_shard_range,
    synthetic_causal_lm,
    synthetic_images,
    synthetic_mlm,
)
from kubeflow_tpu.training.lm import create_lm_state, make_lm_train_step
from kubeflow_tpu.training.loop import LoopConfig, fit
from kubeflow_tpu.utils.metrics import MetricsLogger, StatsdClient


def test_host_shard_range_partitions():
    ranges = [host_shard_range(64, pi, 4) for pi in range(4)]
    rows = [i for r in ranges for i in r]
    assert rows == list(range(64))
    with pytest.raises(ValueError):
        host_shard_range(10, 0, 4)


def test_synthetic_generators_deterministic():
    a = next(synthetic_images(16, (8, 8, 3), seed=7))
    b = next(synthetic_images(16, (8, 8, 3), seed=7))
    np.testing.assert_array_equal(np.asarray(a["inputs"], np.float32),
                                  np.asarray(b["inputs"], np.float32))
    m = next(synthetic_mlm(8, seq_len=16, vocab_size=100))
    assert m["input_ids"].shape == (8, 16)
    # Masked positions carry the mask token and a weight of 1.
    masked = m["mlm_weights"] == 1
    assert (m["input_ids"][masked] == 103).all()
    assert (m["input_ids"][~masked] == m["mlm_labels"][~masked]).all()


def test_prefetcher_places_on_mesh():
    mesh = build_mesh(MeshSpec(data=8))
    it = DevicePrefetcher(synthetic_causal_lm(16, seq_len=8, vocab_size=64),
                          mesh, prefetch=2)
    batch = next(it)
    assert batch["input_ids"].shape == (16, 8)
    assert "data" in str(batch["input_ids"].sharding.spec)
    it.close()


def test_prefetcher_propagates_errors_and_stops():
    def bad_gen():
        yield {"x": np.zeros((2,))}
        raise RuntimeError("boom")

    it = DevicePrefetcher(bad_gen(), None, prefetch=1)
    next(it)
    with pytest.raises(RuntimeError, match="boom"):
        next(it)

    def short_gen():
        yield {"x": np.zeros((2,))}

    it2 = DevicePrefetcher(short_gen(), None)
    next(it2)
    with pytest.raises(StopIteration):
        next(it2)


def test_fit_resume_and_metrics(tmp_path):
    mesh = build_mesh(MeshSpec(data=8))
    model = llama_test()
    gen = synthetic_causal_lm(8, seq_len=16, vocab_size=512, seed=3)
    sample = next(gen)
    state, shardings = create_lm_state(
        model, optax.sgd(0.01), jax.random.PRNGKey(0), sample, mesh)
    step_fn = make_lm_train_step(mesh, shardings, objective="causal",
                                 donate=False)
    ckpt_cfg = CheckpointConfig(directory=str(tmp_path / "ckpt"),
                                save_interval_steps=2, async_save=False)
    metrics_path = tmp_path / "metrics.jsonl"
    cfg = LoopConfig(total_steps=4, log_every=2, checkpoint=ckpt_cfg,
                     metrics_path=str(metrics_path))

    data = DevicePrefetcher(gen, mesh)
    state = fit(state, step_fn, data, cfg)
    assert int(state.step) == 4
    lines = [json.loads(l) for l in metrics_path.read_text().splitlines()]
    assert lines and lines[-1]["step"] == 4 and "loss" in lines[-1]

    # Simulated slice restart: fresh state, same checkpoint dir →
    # resumes at 4 and runs to 6.
    state2, shardings2 = create_lm_state(
        model, optax.sgd(0.01), jax.random.PRNGKey(0), sample, mesh)
    step_fn2 = make_lm_train_step(mesh, shardings2, objective="causal",
                                  donate=False)
    cfg2 = LoopConfig(total_steps=6, log_every=2, checkpoint=ckpt_cfg,
                      metrics_path=str(metrics_path))
    data2 = DevicePrefetcher(synthetic_causal_lm(8, 16, 512, seed=4), mesh)
    state2 = fit(state2, step_fn2, data2, cfg2)
    assert int(state2.step) == 6
    data.close()
    data2.close()


def test_statsd_client_emits_udp():
    import socket

    recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    recv.bind(("127.0.0.1", 0))
    recv.settimeout(2)
    port = recv.getsockname()[1]
    client = StatsdClient(port=port, prefix="t")
    client.gauge("loss", 1.5)
    client.incr("requests")
    client.timing("predict", 12.5)
    seen = {recv.recv(1024).decode() for _ in range(3)}
    assert seen == {"t.loss:1.5|g", "t.requests:1|c", "t.predict:12.5|ms"}
    client.close()
    recv.close()


def test_token_shard_batches_roundtrip(tmp_path):
    """File-backed token shards: exact coverage, static shapes,
    cross-shard chunk stitching, seeded epoch shuffle."""
    import numpy as np

    from kubeflow_tpu.training.data import token_shard_batches

    # 3 shards of awkward sizes; total 1000 tokens; values = position.
    tokens = np.arange(1000, dtype=np.int64)
    paths = []
    for i, sl in enumerate([(0, 333), (333, 700), (700, 1000)]):
        p = tmp_path / f"shard{i}.npy"
        np.save(p, tokens[sl[0]:sl[1]].astype(np.uint16))
        paths.append(str(p))

    seq_len, batch = 16, 4  # 62 chunks -> 15 batches/epoch
    it = token_shard_batches(paths, batch, seq_len, seed=3, epochs=1)
    seen = []
    for b in it:
        assert b["input_ids"].shape == (batch, seq_len)
        assert b["input_ids"].dtype == np.int32
        for row in b["input_ids"]:
            # Every row is a contiguous run from the global stream —
            # including runs that straddle shard boundaries.
            assert (np.diff(row) == 1).all()
            seen.append(int(row[0]))
    assert len(seen) == 15 * batch
    assert len(set(seen)) == len(seen)  # no chunk repeats in an epoch

    # Same seed -> same order; different seed -> different order.
    a = [int(b["input_ids"][0, 0]) for b in
         token_shard_batches(paths, batch, seq_len, seed=3, epochs=1)]
    a_again = [int(b["input_ids"][0, 0]) for b in
               token_shard_batches(paths, batch, seq_len, seed=3, epochs=1)]
    b2 = [int(b["input_ids"][0, 0]) for b in
          token_shard_batches(paths, batch, seq_len, seed=4, epochs=1)]
    assert a == a_again  # deterministic for a fixed seed
    assert a != b2

    # Too-small stream fails loudly.
    import pytest as _pytest
    with _pytest.raises(ValueError, match="chunks"):
        token_shard_batches(paths[:1], 64, 512, epochs=1).__next__()

    # Host-indivisible global batch fails AT CALL TIME, before any
    # next() — in multi-host training the first next() happens inside
    # the DevicePrefetcher thread, and a deferred raise there is
    # exactly the mid-training failure the API promises not to have.
    import unittest.mock as _mock
    with _mock.patch("jax.process_count", return_value=3), \
         _mock.patch("jax.process_index", return_value=0):
        with _pytest.raises(ValueError, match="% hosts"):
            token_shard_batches(paths, batch, seq_len, epochs=1)


def test_image_shard_batches_roundtrip(tmp_path):
    """Paired image/label .npy shards → static {"inputs","labels"}
    batches: coverage, shuffling, cross-shard reads, validation."""
    import numpy as np

    from kubeflow_tpu.training.data import image_shard_batches

    rng = np.random.RandomState(0)
    img_paths, lab_paths = [], []
    # 2 shards, 23 + 17 = 40 examples; label = image[0,0,0] for
    # pairing checks across the shuffle.
    for i, n in enumerate((23, 17)):
        imgs = rng.randint(0, 256, (n, 8, 8, 3)).astype(np.uint8)
        labs = imgs[:, 0, 0, 0].astype(np.int64) % 10
        ip, lp = tmp_path / f"img{i}.npy", tmp_path / f"lab{i}.npy"
        np.save(ip, imgs)
        np.save(lp, labs)
        img_paths.append(str(ip))
        lab_paths.append(str(lp))

    batches = list(image_shard_batches(
        img_paths, lab_paths, 8, seed=1, epochs=1, dtype="float32",
        scale=1.0))
    assert len(batches) == 5  # 40 // 8
    seen = []
    for b in batches:
        assert b["inputs"].shape == (8, 8, 8, 3)
        assert b["inputs"].dtype == np.float32
        assert b["labels"].dtype == np.int32
        # pairing survives the shuffle: label == pixel[0,0,0] % 10
        np.testing.assert_array_equal(
            b["labels"], b["inputs"][:, 0, 0, 0].astype(np.int64) % 10)
        seen.extend(b["inputs"][:, 0, 0, 0].tolist())
    assert len(seen) == 40
    # Exact multiset coverage: every example appears exactly once per
    # epoch (catches duplicate/dropped rows from a shuffle bug).
    expected = sorted(
        float(v) for p in img_paths
        for v in np.load(p)[:, 0, 0, 0])
    assert sorted(seen) == expected

    # Determinism + seed sensitivity.
    a = [b["labels"].tolist() for b in image_shard_batches(
        img_paths, lab_paths, 8, seed=1, epochs=1)]
    a2 = [b["labels"].tolist() for b in image_shard_batches(
        img_paths, lab_paths, 8, seed=1, epochs=1)]
    b2 = [b["labels"].tolist() for b in image_shard_batches(
        img_paths, lab_paths, 8, seed=2, epochs=1)]
    assert a == a2 and a != b2

    # Validation is eager and loud.
    import pytest as _pytest
    with _pytest.raises(ValueError, match="labels for"):
        image_shard_batches(img_paths, lab_paths[::-1], 8, epochs=1)
    with _pytest.raises(ValueError, match="global batch"):
        image_shard_batches(img_paths, lab_paths, 64, epochs=1)
    with _pytest.raises(ValueError, match="shard lists"):
        image_shard_batches(img_paths, [], 8, epochs=1)


def test_vision_eval_on_image_shards(tmp_path):
    """image shards → evaluate_vision: exact accuracy over the
    stream, eval-mode BN."""
    import numpy as np
    import optax

    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models.resnet import resnet18ish
    from kubeflow_tpu.training.data import image_shard_batches
    from kubeflow_tpu.training.evaluate import evaluate_vision

    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (32, 32, 32, 3)).astype(np.uint8)
    labs = rng.randint(0, 10, 32).astype(np.int64)
    np.save(tmp_path / "i.npy", imgs)
    np.save(tmp_path / "l.npy", labs)

    model = resnet18ish(num_classes=10)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 32, 32, 3), jnp.bfloat16),
                           train=False)
    batches = image_shard_batches(
        [str(tmp_path / "i.npy")], [str(tmp_path / "l.npy")], 8,
        epochs=1)
    metrics = evaluate_vision(model.apply, variables, batches)
    assert metrics["examples"] == 32
    assert 0.0 <= metrics["accuracy"] <= 1.0
    assert np.isfinite(metrics["loss"])
